"""Embedded web console: the minio/console role, self-contained.

The reference embeds the external `minio/console` React app on a separate
port (cmd/common-main.go:197 initConsoleServer). This build serves a single
self-contained page plus a small JSON API under the reserved /mtpu prefix
(same port; "mtpu" is a reserved namespace like the reference's "minio"
bucket), covering the operator surface: login, cluster info, per-bucket
usage, object browsing, and a Prometheus snapshot. Everything else is the
admin REST's job (api/admin.py).

Auth: POST /mtpu/console/api/login with root or admin:*-allowed
credentials returns an HS256 JWT (signed with the root secret, 12 h
expiry; verified with api/jwt.verify); API calls carry it as a Bearer
token. The page renders all server-supplied strings through DOM
textContent -- object keys are attacker-controlled and must never reach
innerHTML.
"""

from __future__ import annotations

import asyncio
import hmac
import json
import time

from aiohttp import web

from ..control.logging import GLOBAL_LOGGER
from ..utils import errors as oerr
from .jwt import JWTError, sign_hs256, verify as jwt_verify
from .server import _display_size

CONSOLE_PREFIX = "/mtpu/console"
TOKEN_TTL_S = 12 * 3600


def make_console_app(ctx) -> web.Application:
    """ctx: the admin context (iam, layer, metrics, node back-reference)."""
    app = web.Application()

    def _ready() -> None:
        if not getattr(ctx, "ready", True):
            raise web.HTTPServiceUnavailable(text="server initializing")

    def _secret() -> str:
        return ctx.iam.root.secret_key

    def _authed(request: web.Request) -> str:
        _ready()
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            raise web.HTTPUnauthorized(text="missing bearer token")
        try:
            payload = jwt_verify(auth[7:], hmac_secret=_secret())
        except JWTError as e:
            raise web.HTTPUnauthorized(text=str(e)) from None
        ak = payload.get("sub", "")
        # Re-check the principal on EVERY call: a deleted/disabled admin's
        # token must die with the account, not live out its 12h expiry.
        if ak != ctx.iam.root.access_key and (
            ctx.iam.lookup(ak) is None
            or not ctx.iam.is_allowed(ak, "admin:*", "arn:aws:s3:::*")
        ):
            raise web.HTTPUnauthorized(text="account no longer authorized")
        return ak

    def _json(data, status=200) -> web.Response:
        return web.json_response(data, status=status)

    async def login(request: web.Request) -> web.Response:
        _ready()
        try:
            doc = await _body(request)
        except web.HTTPBadRequest:
            return _json({"error": "bad json"}, 400)
        ak = doc.get("accessKey", "")
        sk = doc.get("secretKey", "")
        if not isinstance(ak, str) or not isinstance(sk, str):
            return _json({"error": "invalid credentials"}, 401)
        creds = ctx.iam.lookup(ak)
        try:
            ok = creds is not None and hmac.compare_digest(
                creds.secret_key.encode(), sk.encode()
            )
        except (TypeError, UnicodeError):
            ok = False
        if not ok:
            return _json({"error": "invalid credentials"}, 401)
        if ak != ctx.iam.root.access_key and not ctx.iam.is_allowed(
            ak, "admin:*", "arn:aws:s3:::*"
        ):
            return _json({"error": "console requires admin privileges"}, 403)
        token = sign_hs256({"sub": ak, "exp": int(time.time()) + TOKEN_TTL_S}, _secret())
        return _json({"token": token})

    def _usage_summary() -> dict:
        scanner = getattr(ctx, "scanner", None)
        if scanner is not None and getattr(scanner, "usage", None) is not None:
            try:
                return scanner.usage.summary()
            except Exception as e:  # noqa: BLE001 - usage is advisory
                GLOBAL_LOGGER.log_once(f"usage summary unavailable: {e}", key="console-usage")
        return {}

    async def info(request: web.Request) -> web.Response:
        _authed(request)

        def work():
            layer = ctx.layer
            pools = getattr(layer, "pools", [])
            drives_total = drives_online = sets = 0
            for p in pools:
                for s in getattr(p, "sets", []):
                    sets += 1
                    for d in s.disks:
                        drives_total += 1
                        if d is not None and d.is_online():
                            drives_online += 1
            return {
                "pools": len(pools),
                "sets": sets,
                "drivesTotal": drives_total,
                "drivesOnline": drives_online,
                "usage": _usage_summary(),
            }

        return _json(await asyncio.to_thread(work))

    async def buckets(request: web.Request) -> web.Response:
        _authed(request)

        def work():
            usage = _usage_summary().get("bucketsUsage", {})
            out = []
            for b in ctx.layer.list_buckets():
                u = usage.get(b.name, {})
                out.append(
                    {
                        "name": b.name,
                        "created": b.created,
                        "objects": u.get("objectsCount", None),
                        "size": u.get("size", None),
                    }
                )
            return {"buckets": out}

        return _json(await asyncio.to_thread(work))

    async def objects(request: web.Request) -> web.Response:
        _authed(request)
        q = request.rel_url.query
        bucket = q.get("bucket", "")
        if not bucket:
            return _json({"error": "bucket required"}, 400)
        try:
            max_keys = int(q.get("max-keys", "100"))
        except ValueError:
            return _json({"error": "bad max-keys"}, 400)

        def work():
            return ctx.layer.list_objects(
                bucket,
                prefix=q.get("prefix", ""),
                marker=q.get("marker", ""),
                delimiter=q.get("delimiter", "/"),
                max_keys=max_keys,
            )

        try:
            res = await asyncio.to_thread(work)
        except (oerr.BucketNotFound, oerr.BucketNameInvalid) as e:
            return _json({"error": str(e)}, 404)
        except oerr.StorageError as e:
            return _json({"error": str(e)}, 400)
        return _json(
            {
                "objects": [
                    {"name": o.name, "size": _display_size(o), "modTime": o.mod_time, "etag": o.etag}
                    for o in res.objects
                ],
                "prefixes": res.prefixes,
                "truncated": res.is_truncated,
                "nextMarker": res.next_marker,
            }
        )

    async def metrics(request: web.Request) -> web.Response:
        _authed(request)
        m = getattr(ctx, "metrics", None)
        text = await asyncio.to_thread(m.render) if m is not None else ""
        return web.Response(text=text, content_type="text/plain")

    # -- management actions (the minio/console mutation surface: bucket,
    # user, service-account CRUD and policy attach). These run the SAME
    # post-mutation fan-out as the admin REST / S3 paths — peer IAM reload
    # and site replication — or multi-node state diverges. -----------------

    async def _body(request: web.Request) -> dict:
        try:
            doc = json.loads(await request.read() or b"{}")
        except ValueError:
            raise web.HTTPBadRequest(text="bad json")
        if not isinstance(doc, dict):
            raise web.HTTPBadRequest(text="bad json")
        return doc

    def _policies_field(doc: dict) -> list[str]:
        policies = doc.get("policies", [])
        if not isinstance(policies, list) or not all(
            isinstance(p, str) for p in policies
        ):
            # A bare string would iterate per-character into nonsense
            # policy names and "succeed" while denying everything.
            raise web.HTTPBadRequest(text="policies must be a list of names")
        return policies

    def _iam_fanout(kind: str, payload: dict) -> None:
        notification = getattr(ctx, "notification", None)
        if notification is not None:
            notification.reload_iam_all()
        site = getattr(ctx, "site_repl", None)
        if site is not None and getattr(site, "enabled", False):
            site.on_iam(kind, payload)

    async def bucket_create(request: web.Request) -> web.Response:
        _authed(request)
        doc = await _body(request)
        name = doc.get("name", "")
        if not isinstance(name, str) or not name:
            return _json({"error": "name required"}, 400)

        def work():
            ctx.layer.make_bucket(name)
            # Same hooks as the S3 PUT-bucket path (server.py _make_bucket):
            # seed bucket metadata and fan out to site replication.
            bm = getattr(ctx, "bucket_meta", None)
            if bm is not None:
                bm.save(bm.get(name))
            site = getattr(ctx, "site_repl", None)
            if site is not None and getattr(site, "enabled", False):
                site.on_bucket_make(name)

        try:
            await asyncio.to_thread(work)
        except (oerr.BucketExists,):
            return _json({"error": f"bucket {name!r} exists"}, 409)
        except oerr.StorageError as e:
            return _json({"error": str(e)}, 400)
        return _json({"ok": True})

    async def bucket_delete(request: web.Request) -> web.Response:
        _authed(request)
        name = request.rel_url.query.get("name", "")
        if not name:
            return _json({"error": "name required"}, 400)

        def work():
            from .server import delete_bucket_with_hooks

            delete_bucket_with_hooks(
                ctx.layer, name,
                bucket_meta=getattr(ctx, "bucket_meta", None),
                notification=getattr(ctx, "notification", None),
                site_repl=getattr(ctx, "site_repl", None),
                notifier=getattr(ctx, "notifier", None),
            )

        try:
            await asyncio.to_thread(work)
        except oerr.BucketNotEmpty:
            return _json({"error": "bucket not empty"}, 409)
        except oerr.BucketNotFound:
            return _json({"error": "no such bucket"}, 404)
        except oerr.StorageError as e:
            return _json({"error": str(e)}, 400)
        return _json({"ok": True})

    async def users_list(request: web.Request) -> web.Response:
        _authed(request)
        out = []
        for ak, ident in sorted(ctx.iam.list_users().items()):
            d = ident.to_dict(with_secret=False)
            d.pop("sessionPolicy", None)
            out.append(d)
        return _json({"users": out})

    async def user_create(request: web.Request) -> web.Response:
        _authed(request)
        doc = await _body(request)
        ak, sk = doc.get("accessKey", ""), doc.get("secretKey", "")
        if not ak or not sk or not isinstance(ak, str) or not isinstance(sk, str):
            return _json({"error": "accessKey and secretKey required"}, 400)
        if ak == ctx.iam.root.access_key:
            return _json({"error": "cannot overwrite the root account"}, 403)
        policies = _policies_field(doc)

        def work():
            ctx.iam.add_user(ak, sk, policies)
            _iam_fanout("user", ctx.iam.users[ak].to_dict())

        await asyncio.to_thread(work)
        return _json({"ok": True})

    async def user_delete(request: web.Request) -> web.Response:
        _authed(request)
        ak = request.rel_url.query.get("accessKey", "")

        def work():
            # remove_user cascades to the user's service accounts / STS
            # creds inside one persisted mutation; one fanout reloads the
            # whole IAM store on every peer.
            ctx.iam.remove_user(ak)
            _iam_fanout("user-delete", {"access_key": ak})

        try:
            await asyncio.to_thread(work)
        except oerr.StorageError as e:
            return _json({"error": str(e)}, 404)
        return _json({"ok": True})

    async def user_policy(request: web.Request) -> web.Response:
        _authed(request)
        doc = await _body(request)
        ak = doc.get("accessKey", "")
        policies = _policies_field(doc)

        def work():
            ctx.iam.attach_policy(ak, policies)
            _iam_fanout("policy-mapping", {"access_key": ak, "policies": policies})

        try:
            await asyncio.to_thread(work)
        except oerr.StorageError as e:
            return _json({"error": str(e)}, 404)
        return _json({"ok": True})

    async def sa_create(request: web.Request) -> web.Response:
        ak = _authed(request)
        doc = await _body(request)
        parent = doc.get("parent", "") or ak

        def work():
            creds = ctx.iam.new_service_account(parent)
            _iam_fanout("user", ctx.iam.users[creds.access_key].to_dict())
            return creds

        creds = await asyncio.to_thread(work)
        # The secret is shown ONCE at creation, as in the reference console.
        return _json({"accessKey": creds.access_key, "secretKey": creds.secret_key})

    async def policies_list(request: web.Request) -> web.Response:
        _authed(request)
        from ..control import policy as policy_mod

        names = sorted({*policy_mod.CANNED, *ctx.iam.custom_policies})
        return _json({"policies": names})

    async def groups_list(request: web.Request) -> web.Response:
        _authed(request)
        out = []
        for g in ctx.iam.list_groups():
            try:
                out.append(ctx.iam.group_info(g))
            except oerr.StorageError:
                continue  # deleted between snapshot and info: skip, not 500
        return _json({"groups": out})

    async def group_update(request: web.Request) -> web.Response:
        # Members add/remove (creates on first add) + policy attach, the
        # console face of the admin /groups handlers. Every field validates
        # BEFORE any mutation: a bad later field must not leave an earlier
        # one half-applied with the peer fanout skipped.
        _authed(request)
        doc = await _body(request)
        name = doc.get("name", "")
        if not isinstance(name, str) or not name:
            return _json({"error": "name required"}, 400)
        members = None
        if "members" in doc:
            members = doc.get("members", [])
            if not isinstance(members, list) or not all(
                isinstance(m, str) for m in members
            ):
                return _json({"error": "members must be a list of strings"}, 400)
        is_remove = doc.get("isRemove", False)
        if not isinstance(is_remove, bool):
            # bool("false") is True: a stringly-typed flag would silently
            # flip an add into a removal.
            return _json({"error": "isRemove must be a boolean"}, 400)
        policies = _policies_field(doc) if "policies" in doc else None
        status = None
        if "status" in doc:
            status = doc["status"]
            if status not in ("enabled", "disabled"):
                # Anything else persists and silently disables the group's
                # grants (only the exact string 'enabled' confers policies).
                return _json({"error": "status must be enabled|disabled"}, 400)
        if members is None and policies is None and status is None:
            return _json({"error": "nothing to change (members/policies/status)"}, 400)

        def work():
            if members is not None:
                ctx.iam.update_group_members(name, members, remove=is_remove)
            if policies is not None:
                ctx.iam.attach_group_policy(name, policies)
            if status is not None:
                ctx.iam.set_group_status(name, status)
            _iam_fanout("group", ctx.iam.group_info(name))

        try:
            await asyncio.to_thread(work)
        except oerr.StorageError as e:
            return _json({"error": str(e)}, 400)
        return _json({"ok": True})

    async def group_delete(request: web.Request) -> web.Response:
        _authed(request)
        name = request.rel_url.query.get("name", "")

        def work():
            ctx.iam.remove_group(name)
            _iam_fanout("group-delete", {"name": name})

        try:
            await asyncio.to_thread(work)
        except oerr.StorageError as e:
            return _json({"error": str(e)}, 400)
        return _json({"ok": True})

    async def index(request: web.Request) -> web.Response:
        return web.Response(text=_PAGE, content_type="text/html")

    app.router.add_post("/api/login", login)
    app.router.add_get("/api/info", info)
    app.router.add_get("/api/buckets", buckets)
    app.router.add_get("/api/objects", objects)
    app.router.add_get("/api/metrics", metrics)
    app.router.add_post("/api/buckets", bucket_create)
    app.router.add_delete("/api/buckets", bucket_delete)
    app.router.add_get("/api/users", users_list)
    app.router.add_post("/api/users", user_create)
    app.router.add_delete("/api/users", user_delete)
    app.router.add_put("/api/users/policy", user_policy)
    app.router.add_post("/api/service-accounts", sa_create)
    app.router.add_get("/api/policies", policies_list)
    app.router.add_get("/api/groups", groups_list)
    app.router.add_post("/api/groups", group_update)
    app.router.add_delete("/api/groups", group_delete)
    app.router.add_get("", index)
    app.router.add_get("/", index)
    return app


# The page builds every data-driven node with document.createElement +
# textContent (never innerHTML) -- bucket names and object keys are
# user-controlled input.
_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>minio_tpu console</title>
<style>
 :root { color-scheme: dark; }
 body { font: 14px/1.5 system-ui, sans-serif; margin: 0; background: #101418; color: #dde3ea; }
 header { padding: 14px 24px; background: #161c24; border-bottom: 1px solid #232b36;
          display: flex; align-items: baseline; gap: 12px; }
 header h1 { font-size: 16px; margin: 0; } header span { color: #7c8a9c; font-size: 12px; }
 main { padding: 24px; max-width: 1080px; margin: auto; }
 .cards { display: flex; gap: 16px; flex-wrap: wrap; margin-bottom: 24px; }
 .card { background: #161c24; border: 1px solid #232b36; border-radius: 8px;
         padding: 14px 20px; min-width: 130px; }
 .card b { display: block; font-size: 22px; } .card span { color: #7c8a9c; font-size: 12px; }
 table { width: 100%; border-collapse: collapse; background: #161c24;
         border: 1px solid #232b36; border-radius: 8px; }
 th, td { text-align: left; padding: 8px 14px; border-bottom: 1px solid #1d2530; }
 th { color: #7c8a9c; font-weight: 500; font-size: 12px; }
 tr:hover td { background: #1a2129; } a { color: #62b0ff; cursor: pointer; text-decoration: none; }
 #login { max-width: 320px; margin: 12vh auto; background: #161c24; padding: 28px;
          border-radius: 10px; border: 1px solid #232b36; }
 input { width: 100%; box-sizing: border-box; margin: 6px 0; padding: 9px 10px;
         background: #0d1116; color: #dde3ea; border: 1px solid #2a3442; border-radius: 6px; }
 button { margin-top: 10px; width: 100%; padding: 9px; background: #2463eb; color: white;
          border: 0; border-radius: 6px; cursor: pointer; font-size: 14px; }
 .err { color: #ff7a7a; font-size: 13px; min-height: 18px; }
 .crumbs { margin: 12px 0; color: #7c8a9c; } .hide { display: none; }
</style></head><body>
<header><h1>minio_tpu</h1><span>console</span>
 <nav id="nav" class="hide" style="margin-left:24px">
  <a id="nav-b">buckets</a> &nbsp; <a id="nav-u">users</a> &nbsp;
  <a id="nav-g">groups</a> &nbsp; <a id="nav-p">policies</a>
 </nav>
 <span style="margin-left:auto"><a id="logout" class="hide">sign out</a></span></header>
<main>
 <div id="login"><h3>Sign in</h3>
  <input id="ak" placeholder="access key" autocomplete="username">
  <input id="sk" placeholder="secret key" type="password" autocomplete="current-password">
  <div class="err" id="lerr"></div><button id="go">Sign in</button></div>
 <div id="dash" class="hide">
  <div class="cards" id="cards"></div>
  <div class="crumbs" id="crumbs"></div>
  <div id="actions"></div>
  <div class="err" id="aerr"></div>
  <table id="tbl"><thead></thead><tbody></tbody></table>
 </div>
</main><script>
const $ = q => document.querySelector(q);
let tok = sessionStorage.getItem('tok') || '';
const api = async (p, opt = {}) => {
  opt.headers = Object.assign({Authorization: 'Bearer ' + tok}, opt.headers || {});
  const r = await fetch('/mtpu/console/api' + p, opt);
  if (r.status === 401) { out(); throw 0; }
  return r;
};
function out() {
  tok = ''; sessionStorage.removeItem('tok');
  $('#login').classList.remove('hide'); $('#dash').classList.add('hide');
  $('#logout').classList.add('hide'); $('#nav').classList.add('hide');
}
$('#logout').onclick = out;
$('#go').onclick = async () => {
  const r = await fetch('/mtpu/console/api/login', {method: 'POST',
    body: JSON.stringify({accessKey: $('#ak').value, secretKey: $('#sk').value})});
  let d = {};
  try { d = await r.json(); }
  catch { $('#lerr').textContent = 'server error (' + r.status + ')'; return; }
  if (!r.ok) { $('#lerr').textContent = d.error || 'login failed'; return; }
  tok = d.token; sessionStorage.setItem('tok', tok); boot();
};
const fmt = n => n == null ? '\\u2013' :
  n >= 1<<30 ? (n/(1<<30)).toFixed(1)+' GiB' : n >= 1<<20 ? (n/(1<<20)).toFixed(1)+' MiB' :
  n >= 1024 ? (n/1024).toFixed(1)+' KiB' : n + ' B';
// DOM builders: every data string lands in textContent, never markup.
const el = (tag, text, onclick) => {
  const e = document.createElement(tag);
  if (text != null) e.textContent = text;
  if (onclick) { e.addEventListener('click', onclick); }
  return e;
};
const row = cells => {
  const tr = document.createElement('tr');
  for (const c of cells) { const td = document.createElement('td');
    td.append(c instanceof Node ? c : el('span', c)); tr.append(td); }
  return tr;
};
const head = cols => {
  const tr = document.createElement('tr');
  for (const c of cols) tr.append(el('th', c));
  $('#tbl thead').replaceChildren(tr);
  $('#tbl tbody').replaceChildren();
};
// Mutations report failures in #aerr; the acting view refreshes after.
const act = async (method, p, body) => {
  $('#aerr').textContent = '';
  const r = await api(p, {method, body: body == null ? undefined : JSON.stringify(body)});
  let d = {};
  try { d = await r.json(); } catch {}
  if (!r.ok) { $('#aerr').textContent = d.error || ('failed (' + r.status + ')'); throw 0; }
  return d;
};
const input = (ph, type) => {
  const i = el('input'); i.placeholder = ph; if (type) i.type = type;
  i.style.width = '180px'; i.style.margin = '0 8px 0 0'; return i;
};
const btn = (label, onclick) => {
  const b = el('button', label, onclick);
  b.style.width = 'auto'; b.style.marginTop = '0'; b.style.padding = '7px 14px'; return b;
};
async function boot() {
  $('#login').classList.add('hide'); $('#dash').classList.remove('hide');
  $('#logout').classList.remove('hide'); $('#nav').classList.remove('hide');
  const i = await (await api('/info')).json();
  const cards = [['pools', i.pools], ['sets', i.sets], ['drives online', i.drivesOnline],
    ['drives total', i.drivesTotal], ['objects', i.usage.objectsCount ?? '\\u2013'],
    ['data', fmt(i.usage.objectsTotalSize)]];
  $('#cards').replaceChildren(...cards.map(([k, v]) => {
    const c = el('div'); c.className = 'card'; c.append(el('b', v), el('span', k)); return c;
  }));
  showBuckets();
}
$('#nav-b').onclick = () => showBuckets();
$('#nav-u').onclick = () => showUsers();
$('#nav-g').onclick = () => showGroups();
$('#nav-p').onclick = () => showPolicies();
async function showBuckets() {
  $('#crumbs').replaceChildren(el('a', 'buckets', showBuckets));
  const name = input('new bucket name');
  $('#actions').replaceChildren(name,
    btn('create bucket', async () => {
      await act('POST', '/buckets', {name: name.value}); showBuckets();
    }));
  const d = await (await api('/buckets')).json();
  head(['bucket', 'objects', 'size', '']);
  const body = $('#tbl tbody');
  if (!d.buckets.length) body.append(row(['no buckets', '', '', '']));
  for (const b of d.buckets)
    body.append(row([el('a', b.name, () => showObjs(b.name, '')),
      b.objects ?? '\\u2013', fmt(b.size),
      el('a', 'delete', async () => {
        if (!confirm('Delete bucket ' + b.name + '?')) return;
        await act('DELETE', '/buckets?' + new URLSearchParams({name: b.name}));
        showBuckets();
      })]));
}
async function showUsers() {
  $('#crumbs').replaceChildren(el('b', 'users'));
  const ak = input('access key'), sk = input('secret key', 'password'),
        pol = input('policies (comma-sep)');
  $('#actions').replaceChildren(ak, sk, pol,
    btn('create user', async () => {
      await act('POST', '/users', {accessKey: ak.value, secretKey: sk.value,
        policies: pol.value.split(',').map(s => s.trim()).filter(Boolean)});
      showUsers();
    }));
  const d = await (await api('/users')).json();
  head(['access key', 'status', 'policies', 'parent', '']);
  const body = $('#tbl tbody');
  if (!d.users.length) body.append(row(['no users', '', '', '', '']));
  for (const u of d.users) {
    const actions = el('span');
    actions.append(
      el('a', 'attach policy', async () => {
        const p = prompt('Policies for ' + u.accessKey + ' (comma-sep):',
          u.policies.join(','));
        if (p == null) return;
        await act('PUT', '/users/policy', {accessKey: u.accessKey,
          policies: p.split(',').map(s => s.trim()).filter(Boolean)});
        showUsers();
      }),
      el('span', ' \\u00b7 '),
      el('a', 'svc acct', async () => {
        const c = await act('POST', '/service-accounts', {parent: u.accessKey});
        // shown once; the secret is not retrievable later
        prompt('Service account created \\u2014 copy these now:',
          c.accessKey + ' / ' + c.secretKey);
      }),
      el('span', ' \\u00b7 '),
      el('a', 'delete', async () => {
        if (!confirm('Delete user ' + u.accessKey + '?')) return;
        await act('DELETE', '/users?' + new URLSearchParams({accessKey: u.accessKey}));
        showUsers();
      }));
    body.append(row([u.accessKey, u.status, u.policies.join(', ') || '\\u2013',
      u.parentUser || '\\u2013', actions]));
  }
}
async function showGroups() {
  $('#crumbs').replaceChildren(el('b', 'groups'));
  const gn = input('group name'), gm = input('members (comma-sep)');
  $('#actions').replaceChildren(gn, gm,
    btn('add members', async () => {
      await act('POST', '/groups', {name: gn.value,
        members: gm.value.split(',').map(s => s.trim()).filter(Boolean)});
      showGroups();
    }));
  const d = await (await api('/groups')).json();
  head(['group', 'status', 'members', 'policies', '']);
  const body = $('#tbl tbody');
  if (!d.groups.length) body.append(row(['no groups', '', '', '', '']));
  for (const g of d.groups) {
    const actions = el('span');
    actions.append(
      el('a', 'policies', async () => {
        const p = prompt('Policies for ' + g.name + ' (comma-sep):',
          g.policies.join(','));
        if (p == null) return;
        await act('POST', '/groups', {name: g.name,
          policies: p.split(',').map(s => s.trim()).filter(Boolean)});
        showGroups();
      }),
      el('span', ' \\u00b7 '),
      el('a', g.status === 'enabled' ? 'disable' : 'enable', async () => {
        await act('POST', '/groups', {name: g.name,
          status: g.status === 'enabled' ? 'disabled' : 'enabled'});
        showGroups();
      }),
      el('span', ' \\u00b7 '),
      el('a', 'remove members', async () => {
        const m = prompt('Members to REMOVE from ' + g.name + ':', g.members.join(','));
        if (m == null) return;
        await act('POST', '/groups', {name: g.name, isRemove: true,
          members: m.split(',').map(s => s.trim()).filter(Boolean)});
        showGroups();
      }),
      el('span', ' \\u00b7 '),
      el('a', 'delete', async () => {
        if (!confirm('Delete group ' + g.name + '? (must be empty)')) return;
        await act('DELETE', '/groups?' + new URLSearchParams({name: g.name}));
        showGroups();
      }));
    body.append(row([g.name, g.status, g.members.join(', ') || '\\u2013',
      g.policies.join(', ') || '\\u2013', actions]));
  }
}
async function showPolicies() {
  $('#crumbs').replaceChildren(el('b', 'policies'));
  $('#actions').replaceChildren();
  const d = await (await api('/policies')).json();
  head(['policy']);
  const body = $('#tbl tbody');
  for (const p of d.policies) body.append(row([p]));
}
async function showObjs(bucket, prefix, marker = '') {
  $('#crumbs').replaceChildren(el('a', 'buckets', showBuckets),
    el('span', ' / '), el('b', bucket), el('span', ' / ' + prefix));
  $('#actions').replaceChildren();
  const q = new URLSearchParams({bucket, prefix, marker, 'max-keys': '100'});
  const d = await (await api('/objects?' + q)).json();
  head(['key', 'size', 'modified']);
  const body = $('#tbl tbody');
  for (const p of d.prefixes)
    body.append(row([el('a', p, () => showObjs(bucket, p)), '\\u2013', '\\u2013']));
  for (const o of d.objects)
    body.append(row([o.name, fmt(o.size),
      new Date(o.modTime * 1000).toISOString().slice(0, 19)]));
  if (!d.prefixes.length && !d.objects.length) body.append(row(['empty', '', '']));
  if (d.truncated)
    body.append(row([el('a', 'next page \\u2192',
      () => showObjs(bucket, prefix, d.nextMarker)), '', '']));
}
if (tok) boot();
</script></body></html>
"""
