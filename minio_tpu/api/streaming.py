"""Streaming signature V4 (aws-chunked) encoding and verification.

Role of the reference's cmd/streaming-signature-v4.go
(``newSignV4ChunkedReader`` :160): the client splits the payload into chunks,
each carrying a signature chained from the previous one; the server verifies
every chunk signature while decoding.

Wire format per chunk::

    <hex-size>;chunk-signature=<sig>\r\n
    <size bytes of data>\r\n

terminated by a zero-size chunk whose signature covers the empty hash.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import List, Tuple

from ..control.profiler import COPIED, GLOBAL_PROFILER, MOVED
from .auth import Credentials, STREAMING_PAYLOAD, signing_key
from .errors import S3Error

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
MAX_CHUNK_SIZE = 16 * (1 << 20)  # reference maxChunkSize, streaming-signature-v4.go

# Header lines are parsed out of a small carry buffer; reads this size keep
# the spill (payload bytes swallowed with a header) bounded and cheap while
# one read usually covers a whole "<hex-size>;chunk-signature=<64 hex>" line.
_HEADER_READ = 256


def _chunk_digest_string_to_sign(
    amz_date: str, scope: str, prev_sig: str, chunk_sha_hex: str
) -> str:
    return "\n".join(
        [
            "AWS4-HMAC-SHA256-PAYLOAD",
            amz_date,
            scope,
            prev_sig,
            _EMPTY_SHA256,
            chunk_sha_hex,
        ]
    )


def _chunk_string_to_sign(amz_date: str, scope: str, prev_sig: str, chunk: bytes) -> str:
    return _chunk_digest_string_to_sign(
        amz_date, scope, prev_sig, hashlib.sha256(chunk).hexdigest()
    )


def _sign(key: bytes, msg: str) -> str:
    return hmac.new(key, msg.encode(), hashlib.sha256).hexdigest()


def encode_chunked(
    payload: bytes,
    seed_signature: str,
    creds: Credentials,
    amz_date: str,
    region: str,
    chunk_size: int = 64 * 1024,
) -> bytes:
    """Client side: produce the aws-chunked body for a payload."""
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    key = signing_key(creds.secret_key, date, region)
    out = bytearray()
    prev = seed_signature
    offsets = list(range(0, len(payload), chunk_size)) or [0]
    for off in offsets:
        chunk = payload[off:off + chunk_size]
        sig = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, chunk))
        out += f"{len(chunk):x};chunk-signature={sig}\r\n".encode()  # mtpulint: disable=hot-path-copy -- client-side wire helper
        out += chunk + b"\r\n"  # mtpulint: disable=hot-path-copy -- client-side wire helper
        prev = sig
    final_sig = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, b""))
    out += f"0;chunk-signature={final_sig}\r\n\r\n".encode()  # mtpulint: disable=hot-path-copy -- client-side wire helper
    return bytes(out)  # mtpulint: disable=hot-path-copy -- client-side wire helper


def decode_chunked(
    body: bytes,
    seed_signature: str,
    secret_key: str,
    amz_date: str,
    region: str,
) -> bytes:
    """Server side: decode and verify an aws-chunked body; returns the payload.

    Raises SignatureDoesNotMatch on any broken chunk signature chain.
    """
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    key = signing_key(secret_key, date, region)
    out = bytearray()
    prev = seed_signature
    i = 0
    n = len(body)
    while True:
        nl = body.find(b"\r\n", i)
        if nl < 0:
            raise S3Error("IncompleteBody", "truncated chunk header")
        header = body[i:nl].decode("latin-1")
        i = nl + 2
        if ";" not in header:
            raise S3Error("InvalidRequest", "malformed chunk header")
        size_hex, _, attrs = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise S3Error("InvalidRequest", "bad chunk size")
        sig = ""
        for attr in attrs.split(";"):
            k, _, v = attr.partition("=")
            if k.strip() == "chunk-signature":
                sig = v.strip()
        if not sig:
            raise S3Error("InvalidRequest", "missing chunk-signature")
        if i + size > n:
            raise S3Error("IncompleteBody", "truncated chunk data")
        chunk = body[i:i + size]
        i += size
        if body[i:i + 2] != b"\r\n":
            # trailing CRLF after data (the final chunk has an extra blank line)
            raise S3Error("InvalidRequest", "missing chunk trailer")
        i += 2
        want = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, chunk))
        if not hmac.compare_digest(want, sig):
            raise S3Error("SignatureDoesNotMatch", "chunk signature mismatch")
        prev = want
        if size == 0:
            break
        out += chunk  # mtpulint: disable=hot-path-copy -- buffered compat path; the server streams via SignedChunkReader
    return bytes(out)  # mtpulint: disable=hot-path-copy -- buffered compat path


def is_streaming_request(headers: dict) -> bool:
    h = {k.lower(): v for k, v in headers.items()}
    return h.get("x-amz-content-sha256", "") == STREAMING_PAYLOAD


class SignedChunkReader:
    """Incremental aws-chunked decoder+verifier over a sync readinto source.

    The streaming-PUT analogue of decode_chunked: the reference's
    newSignV4ChunkedReader (cmd/streaming-signature-v4.go:160) wraps the
    request body and verifies each chunk's chained signature as the object
    layer consumes it -- memory stays O(header + spill).

    Zero-copy contract: ``readinto(dest)`` decodes chunk payload straight
    into the caller's buffer (the pooled erasure window) -- only header
    lines and the few payload bytes a header read happens to swallow pass
    through the small carry buffer. The chunk signature is checked from an
    incrementally-updated sha256 once the chunk's last byte has landed;
    bytes from a not-yet-verified chunk may therefore already sit in the
    caller's buffer, which is safe because a signature mismatch raises
    before EOF and the PUT path never commits an errored body (staged
    shards are deleted on abort)."""

    def __init__(self, reader, seed_signature: str, secret_key: str, amz_date: str, region: str):
        self._r = reader
        self._amz_date = amz_date
        date = amz_date[:8]
        self._scope = f"{date}/{region}/s3/aws4_request"
        self._key = signing_key(secret_key, date, region)
        self._prev = seed_signature
        self._raw = bytearray()  # carry: header bytes + payload spill
        self._data_left = 0      # payload bytes remaining in current chunk
        self._sha = None         # running sha256 of current chunk payload
        self._sig = ""           # declared signature of current chunk
        self._done = False

    def _read_header_line(self) -> str:
        while True:
            nl = self._raw.find(b"\r\n")
            if nl >= 0:
                line = bytes(self._raw[:nl]).decode("latin-1")
                del self._raw[: nl + 2]
                return line
            if len(self._raw) > 16384:
                raise S3Error("InvalidRequest", "oversized chunk header")
            chunk = self._r.read(_HEADER_READ)
            if not chunk:
                raise S3Error("IncompleteBody", "truncated chunk header")
            self._raw += chunk

    def _verify_sig(self, chunk_sha_hex: str) -> None:
        want = _sign(
            self._key,
            _chunk_digest_string_to_sign(
                self._amz_date, self._scope, self._prev, chunk_sha_hex
            ),
        )
        if not hmac.compare_digest(want, self._sig):
            raise S3Error("SignatureDoesNotMatch", "chunk signature mismatch")
        self._prev = want

    def _begin_chunk(self) -> None:
        header = self._read_header_line()
        if ";" not in header:
            raise S3Error("InvalidRequest", "malformed chunk header")
        size_hex, _, attrs = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise S3Error("InvalidRequest", "bad chunk size")
        if size > MAX_CHUNK_SIZE:
            # Memory stays bounded: a declared terabyte chunk must not
            # buffer before its signature check (the reference caps chunks
            # at 16 MiB, streaming-signature-v4.go maxChunkSize).
            raise S3Error("InvalidRequest", "chunk size exceeds maximum")
        sig = ""
        for attr in attrs.split(";"):
            k, _, v = attr.partition("=")
            if k.strip() == "chunk-signature":
                sig = v.strip()
        if not sig:
            raise S3Error("InvalidRequest", "missing chunk-signature")
        self._sig = sig
        if size == 0:
            self._verify_sig(_EMPTY_SHA256)
            self._done = True
            return
        self._data_left = size
        self._sha = hashlib.sha256()

    def _finish_chunk(self) -> None:
        """Current chunk's payload fully landed: trailer CRLF + signature."""
        while len(self._raw) < 2:
            more = self._r.read(_HEADER_READ)
            if not more:
                raise S3Error("IncompleteBody", "truncated chunk data")
            self._raw += more
        if self._raw[:2] != b"\r\n":
            raise S3Error("InvalidRequest", "missing chunk trailer")
        del self._raw[:2]
        self._verify_sig(self._sha.hexdigest())
        self._sha = None

    def _land(self, dest, want: int) -> int:
        """Move up to `want` payload bytes into dest[:], carry buffer first."""
        if self._raw:
            t = min(want, len(self._raw))
            dest[:t] = self._raw[:t]
            del self._raw[:t]
            return t
        ri = getattr(self._r, "readinto", None)
        if ri is not None:
            t = ri(dest[:want])
            if not t:
                raise S3Error("IncompleteBody", "truncated chunk data")
            return t
        b = self._r.read(want)
        if not b:
            raise S3Error("IncompleteBody", "truncated chunk data")
        dest[: len(b)] = b
        return len(b)

    def _decode_into(self, dest: memoryview) -> int:
        total = 0
        n = len(dest)
        while total < n and not self._done:
            if self._data_left:
                t = self._land(dest[total:], min(self._data_left, n - total))
                self._sha.update(dest[total : total + t])
                self._data_left -= t
                total += t
                if self._data_left == 0:
                    self._finish_chunk()
            else:
                self._begin_chunk()
        return total

    def readinto(self, dest) -> int:
        """Decode verified payload straight into `dest` (a writable buffer);
        returns bytes landed, 0 at end of the chunked body."""
        if not isinstance(dest, memoryview):
            dest = memoryview(dest)
        total = self._decode_into(dest)
        if total:
            # Copy-ledger hop: payload decodes straight into the caller's
            # pooled buffer -- verified bytes are never restaged.
            GLOBAL_PROFILER.copy.record("sigv4-chunk-parse", MOVED, total)
        return total

    def read(self, n: int) -> bytes:
        """Legacy bytes-returning fallback for non-pooled consumers."""
        if n <= 0:
            return b""
        buf = bytearray(n)
        got = self._decode_into(memoryview(buf))
        if got:
            GLOBAL_PROFILER.copy.record("sigv4-chunk-parse", COPIED, got)
        # mtpulint: disable=hot-path-copy -- materializing is this
        # fallback's contract; the pooled path uses readinto above
        return bytes(buf[:got])
