"""Streaming signature V4 (aws-chunked) encoding and verification.

Role of the reference's cmd/streaming-signature-v4.go
(``newSignV4ChunkedReader`` :160): the client splits the payload into chunks,
each carrying a signature chained from the previous one; the server verifies
every chunk signature while decoding.

Wire format per chunk::

    <hex-size>;chunk-signature=<sig>\r\n
    <size bytes of data>\r\n

terminated by a zero-size chunk whose signature covers the empty hash.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import List, Tuple

from ..control.profiler import COPIED, GLOBAL_PROFILER
from .auth import Credentials, STREAMING_PAYLOAD, signing_key
from .errors import S3Error

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
MAX_CHUNK_SIZE = 16 * (1 << 20)  # reference maxChunkSize, streaming-signature-v4.go


def _chunk_string_to_sign(amz_date: str, scope: str, prev_sig: str, chunk: bytes) -> str:
    return "\n".join(
        [
            "AWS4-HMAC-SHA256-PAYLOAD",
            amz_date,
            scope,
            prev_sig,
            _EMPTY_SHA256,
            hashlib.sha256(chunk).hexdigest(),
        ]
    )


def _sign(key: bytes, msg: str) -> str:
    return hmac.new(key, msg.encode(), hashlib.sha256).hexdigest()


def encode_chunked(
    payload: bytes,
    seed_signature: str,
    creds: Credentials,
    amz_date: str,
    region: str,
    chunk_size: int = 64 * 1024,
) -> bytes:
    """Client side: produce the aws-chunked body for a payload."""
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    key = signing_key(creds.secret_key, date, region)
    out = bytearray()
    prev = seed_signature
    offsets = list(range(0, len(payload), chunk_size)) or [0]
    for off in offsets:
        chunk = payload[off:off + chunk_size]
        sig = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, chunk))
        out += f"{len(chunk):x};chunk-signature={sig}\r\n".encode()
        out += chunk + b"\r\n"
        prev = sig
    final_sig = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, b""))
    out += f"0;chunk-signature={final_sig}\r\n\r\n".encode()
    return bytes(out)


def decode_chunked(
    body: bytes,
    seed_signature: str,
    secret_key: str,
    amz_date: str,
    region: str,
) -> bytes:
    """Server side: decode and verify an aws-chunked body; returns the payload.

    Raises SignatureDoesNotMatch on any broken chunk signature chain.
    """
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    key = signing_key(secret_key, date, region)
    out = bytearray()
    prev = seed_signature
    i = 0
    n = len(body)
    while True:
        nl = body.find(b"\r\n", i)
        if nl < 0:
            raise S3Error("IncompleteBody", "truncated chunk header")
        header = body[i:nl].decode("latin-1")
        i = nl + 2
        if ";" not in header:
            raise S3Error("InvalidRequest", "malformed chunk header")
        size_hex, _, attrs = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise S3Error("InvalidRequest", "bad chunk size")
        sig = ""
        for attr in attrs.split(";"):
            k, _, v = attr.partition("=")
            if k.strip() == "chunk-signature":
                sig = v.strip()
        if not sig:
            raise S3Error("InvalidRequest", "missing chunk-signature")
        if i + size > n:
            raise S3Error("IncompleteBody", "truncated chunk data")
        chunk = body[i:i + size]
        i += size
        if body[i:i + 2] != b"\r\n":
            # trailing CRLF after data (the final chunk has an extra blank line)
            raise S3Error("InvalidRequest", "missing chunk trailer")
        i += 2
        want = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, chunk))
        if not hmac.compare_digest(want, sig):
            raise S3Error("SignatureDoesNotMatch", "chunk signature mismatch")
        prev = want
        if size == 0:
            break
        out += chunk
    return bytes(out)


def is_streaming_request(headers: dict) -> bool:
    h = {k.lower(): v for k, v in headers.items()}
    return h.get("x-amz-content-sha256", "") == STREAMING_PAYLOAD


class SignedChunkReader:
    """Incremental aws-chunked decoder+verifier over a sync .read(n) source.

    The streaming-PUT analogue of decode_chunked: the reference's
    newSignV4ChunkedReader (cmd/streaming-signature-v4.go:160) wraps the
    request body and verifies each chunk's chained signature as the object
    layer consumes it -- memory stays O(chunkSize)."""

    def __init__(self, reader, seed_signature: str, secret_key: str, amz_date: str, region: str):
        self._r = reader
        self._amz_date = amz_date
        date = amz_date[:8]
        self._scope = f"{date}/{region}/s3/aws4_request"
        self._key = signing_key(secret_key, date, region)
        self._prev = seed_signature
        self._raw = bytearray()  # undecoded wire bytes
        self._out = bytearray()  # decoded payload ready to serve
        self._done = False

    def _fill_raw(self, need: int) -> None:
        while len(self._raw) < need:
            chunk = self._r.read(max(64 * 1024, need - len(self._raw)))
            if not chunk:
                raise S3Error("IncompleteBody", "truncated aws-chunked body")
            self._raw += chunk

    def _read_header_line(self) -> str:
        while True:
            nl = self._raw.find(b"\r\n")
            if nl >= 0:
                line = bytes(self._raw[:nl]).decode("latin-1")
                del self._raw[: nl + 2]
                return line
            if len(self._raw) > 16384:
                raise S3Error("InvalidRequest", "oversized chunk header")
            chunk = self._r.read(64 * 1024)
            if not chunk:
                raise S3Error("IncompleteBody", "truncated chunk header")
            self._raw += chunk

    def _decode_one(self) -> None:
        header = self._read_header_line()
        if ";" not in header:
            raise S3Error("InvalidRequest", "malformed chunk header")
        size_hex, _, attrs = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise S3Error("InvalidRequest", "bad chunk size")
        if size > MAX_CHUNK_SIZE:
            # Memory stays O(MAX_CHUNK_SIZE): a declared terabyte chunk must
            # not buffer before its signature check (the reference caps
            # chunks at 16 MiB, streaming-signature-v4.go maxChunkSize).
            raise S3Error("InvalidRequest", "chunk size exceeds maximum")
        sig = ""
        for attr in attrs.split(";"):
            k, _, v = attr.partition("=")
            if k.strip() == "chunk-signature":
                sig = v.strip()
        if not sig:
            raise S3Error("InvalidRequest", "missing chunk-signature")
        self._fill_raw(size + 2)
        chunk = bytes(self._raw[:size])
        if self._raw[size : size + 2] != b"\r\n":
            raise S3Error("InvalidRequest", "missing chunk trailer")
        del self._raw[: size + 2]
        want = _sign(self._key, _chunk_string_to_sign(self._amz_date, self._scope, self._prev, chunk))
        if not hmac.compare_digest(want, sig):
            raise S3Error("SignatureDoesNotMatch", "chunk signature mismatch")
        self._prev = want
        if size == 0:
            self._done = True
        else:
            self._out += chunk

    def read(self, n: int) -> bytes:
        while not self._done and len(self._out) < n:
            self._decode_one()
        out = bytes(self._out[:n])
        del self._out[:n]
        # Copy-ledger hop: decode stages wire bytes into _raw, verified
        # payload into _out, and every read() slices _out into a fresh
        # bytes -- this hop copies by construction today.
        GLOBAL_PROFILER.copy.record("sigv4-chunk-parse", COPIED, len(out))
        return out
