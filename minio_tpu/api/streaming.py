"""Streaming signature V4 (aws-chunked) encoding and verification.

Role of the reference's cmd/streaming-signature-v4.go
(``newSignV4ChunkedReader`` :160): the client splits the payload into chunks,
each carrying a signature chained from the previous one; the server verifies
every chunk signature while decoding.

Wire format per chunk::

    <hex-size>;chunk-signature=<sig>\r\n
    <size bytes of data>\r\n

terminated by a zero-size chunk whose signature covers the empty hash.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import List, Tuple

from .auth import Credentials, STREAMING_PAYLOAD, signing_key
from .errors import S3Error

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _chunk_string_to_sign(amz_date: str, scope: str, prev_sig: str, chunk: bytes) -> str:
    return "\n".join(
        [
            "AWS4-HMAC-SHA256-PAYLOAD",
            amz_date,
            scope,
            prev_sig,
            _EMPTY_SHA256,
            hashlib.sha256(chunk).hexdigest(),
        ]
    )


def _sign(key: bytes, msg: str) -> str:
    return hmac.new(key, msg.encode(), hashlib.sha256).hexdigest()


def encode_chunked(
    payload: bytes,
    seed_signature: str,
    creds: Credentials,
    amz_date: str,
    region: str,
    chunk_size: int = 64 * 1024,
) -> bytes:
    """Client side: produce the aws-chunked body for a payload."""
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    key = signing_key(creds.secret_key, date, region)
    out = bytearray()
    prev = seed_signature
    offsets = list(range(0, len(payload), chunk_size)) or [0]
    for off in offsets:
        chunk = payload[off:off + chunk_size]
        sig = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, chunk))
        out += f"{len(chunk):x};chunk-signature={sig}\r\n".encode()
        out += chunk + b"\r\n"
        prev = sig
    final_sig = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, b""))
    out += f"0;chunk-signature={final_sig}\r\n\r\n".encode()
    return bytes(out)


def decode_chunked(
    body: bytes,
    seed_signature: str,
    secret_key: str,
    amz_date: str,
    region: str,
) -> bytes:
    """Server side: decode and verify an aws-chunked body; returns the payload.

    Raises SignatureDoesNotMatch on any broken chunk signature chain.
    """
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    key = signing_key(secret_key, date, region)
    out = bytearray()
    prev = seed_signature
    i = 0
    n = len(body)
    while True:
        nl = body.find(b"\r\n", i)
        if nl < 0:
            raise S3Error("IncompleteBody", "truncated chunk header")
        header = body[i:nl].decode("latin-1")
        i = nl + 2
        if ";" not in header:
            raise S3Error("InvalidRequest", "malformed chunk header")
        size_hex, _, attrs = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise S3Error("InvalidRequest", "bad chunk size")
        sig = ""
        for attr in attrs.split(";"):
            k, _, v = attr.partition("=")
            if k.strip() == "chunk-signature":
                sig = v.strip()
        if not sig:
            raise S3Error("InvalidRequest", "missing chunk-signature")
        if i + size > n:
            raise S3Error("IncompleteBody", "truncated chunk data")
        chunk = body[i:i + size]
        i += size
        if body[i:i + 2] != b"\r\n":
            # trailing CRLF after data (the final chunk has an extra blank line)
            raise S3Error("InvalidRequest", "missing chunk trailer")
        i += 2
        want = _sign(key, _chunk_string_to_sign(amz_date, scope, prev, chunk))
        if not hmac.compare_digest(want, sig):
            raise S3Error("SignatureDoesNotMatch", "chunk signature mismatch")
        prev = want
        if size == 0:
            break
        out += chunk
    return bytes(out)


def is_streaming_request(headers: dict) -> bool:
    h = {k.lower(): v for k, v in headers.items()}
    return h.get("x-amz-content-sha256", "") == STREAMING_PAYLOAD
