"""STS: temporary credentials (AssumeRole).

Role of the reference's cmd/sts-handlers.go (AssumeRole :184): POST to the
root path with Action=AssumeRole, signed with long-lived user credentials,
returns short-lived credentials inheriting (and optionally narrowing, via the
Policy parameter) the parent's permissions. The WebIdentity/LDAP/Certificate
variants share this issuance path with different authenticators.
"""

from __future__ import annotations

import json
import time
import urllib.parse
from xml.sax.saxutils import escape

from aiohttp import web

from ..control.iam import IAMSys
from .errors import S3Error

STS_VERSION = "2011-06-15"
MIN_DURATION = 900
MAX_DURATION = 7 * 24 * 3600


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def handle_sts(iam: IAMSys, access_key: str, form: dict[str, str]) -> web.Response:
    """Dispatch an STS action for an already-authenticated principal."""
    action = form.get("Action", "")
    if action == "AssumeRole":
        return _assume_role(iam, access_key, form)
    raise S3Error("NotImplemented", f"STS action {action}")


def _assume_role(iam: IAMSys, access_key: str, form: dict[str, str]) -> web.Response:
    if not access_key:
        raise S3Error("AccessDenied")
    duration = int(form.get("DurationSeconds", "3600"))
    duration = max(MIN_DURATION, min(duration, MAX_DURATION))
    session_policy = None
    if form.get("Policy"):
        try:
            session_policy = json.loads(form["Policy"])
        except ValueError:
            raise S3Error("MalformedXML", "invalid session policy")
    creds, expiry = iam.new_sts_credentials(access_key, duration, session_policy)
    # Session token: we key STS creds by access key server-side, so the token
    # is informational (the reference embeds signed claims; same contract to
    # clients: pass it along, server validates).
    token = f"mtpu-sts-{creds.access_key}"
    body = f"""<AssumeRoleResponse xmlns="https://sts.amazonaws.com/doc/{STS_VERSION}/">
  <AssumeRoleResult>
    <Credentials>
      <AccessKeyId>{escape(creds.access_key)}</AccessKeyId>
      <SecretAccessKey>{escape(creds.secret_key)}</SecretAccessKey>
      <SessionToken>{escape(token)}</SessionToken>
      <Expiration>{_iso(expiry)}</Expiration>
    </Credentials>
  </AssumeRoleResult>
  <ResponseMetadata/>
</AssumeRoleResponse>"""
    return web.Response(body=body.encode(), content_type="application/xml")


def parse_form(body: bytes) -> dict[str, str]:
    return {k: v[0] for k, v in urllib.parse.parse_qs(body.decode()).items()}
