"""STS: temporary credentials (AssumeRole + federation variants).

Role of the reference's cmd/sts-handlers.go:
  * AssumeRole (:184) — signed with long-lived user credentials, returns
    short-lived credentials inheriting (optionally narrowing via Policy)
    the parent's permissions.
  * AssumeRoleWithWebIdentity / AssumeRoleWithClientGrants (:301) — OIDC
    JWT authenticated (anonymous HTTP), policies mapped from a token claim
    (internal/config/identity/openid claim_name, default "policy").
  * AssumeRoleWithCertificate (:606) — mTLS client certificate, policy
    named by the certificate CN.
  * AssumeRoleWithLDAPIdentity (:419) — LDAP lookup-bind + user bind via
    the zero-dep BER client (control/ldap.py); user/group DNs map to
    policies through the IAM LDAP policy DB. Reports NotImplemented when
    identity_ldap is unconfigured, the reference's behavior.

Zero-egress: OIDC verification uses a static JWKS / shared secret from the
identity_openid config subsystem, not issuer discovery.
"""

from __future__ import annotations

import json
import time
import urllib.parse
from xml.sax.saxutils import escape

from aiohttp import web

from ..control.iam import IAMSys
from . import jwt as jwt_mod
from .errors import S3Error

STS_VERSION = "2011-06-15"
MIN_DURATION = 900
MAX_DURATION = 7 * 24 * 3600


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def handle_sts(
    iam: IAMSys,
    access_key: str,
    form: dict[str, str],
    config=None,
    request: web.Request | None = None,
) -> web.Response:
    """Dispatch an STS action. AssumeRole needs a signed principal; the
    federation variants authenticate by token/certificate instead."""
    action = form.get("Action", "")
    if action == "AssumeRole":
        return _assume_role(iam, access_key, form)
    if action == "AssumeRoleWithWebIdentity":
        return _assume_role_with_token(
            iam, config, form, form.get("WebIdentityToken", ""), action
        )
    if action == "AssumeRoleWithClientGrants":
        return _assume_role_with_token(iam, config, form, form.get("Token", ""), action)
    if action == "AssumeRoleWithCertificate":
        return _assume_role_with_certificate(iam, config, form, request)
    if action == "AssumeRoleWithLDAPIdentity":
        return _assume_role_with_ldap(iam, config, form)
    raise S3Error("NotImplemented", f"STS action {action}")


def _duration(form: dict[str, str], default: int = 3600) -> int:
    duration = int(form.get("DurationSeconds", str(default)))
    return max(MIN_DURATION, min(duration, MAX_DURATION))


def _session_policy(form: dict[str, str]) -> dict | None:
    if form.get("Policy"):
        try:
            doc = json.loads(form["Policy"])
        except ValueError:
            raise S3Error("MalformedXML", "invalid session policy")
        from ..control import policy as policy_mod

        try:
            policy_mod.Policy.from_dict(doc).validate()
        except ValueError as e:
            raise S3Error("MalformedXML", f"invalid session policy: {e}")
        return doc
    return None


def _creds_xml(action: str, creds, expiry: float, extra: str = "") -> web.Response:
    token = f"mtpu-sts-{creds.access_key}"
    body = f"""<{action}Response xmlns="https://sts.amazonaws.com/doc/{STS_VERSION}/">
  <{action}Result>
    <Credentials>
      <AccessKeyId>{escape(creds.access_key)}</AccessKeyId>
      <SecretAccessKey>{escape(creds.secret_key)}</SecretAccessKey>
      <SessionToken>{escape(token)}</SessionToken>
      <Expiration>{_iso(expiry)}</Expiration>
    </Credentials>{extra}
  </{action}Result>
  <ResponseMetadata/>
</{action}Response>"""
    return web.Response(body=body.encode(), content_type="application/xml")


def _assume_role(iam: IAMSys, access_key: str, form: dict[str, str]) -> web.Response:
    if not access_key:
        raise S3Error("AccessDenied")
    creds, expiry = iam.new_sts_credentials(
        access_key, _duration(form), _session_policy(form)
    )
    return _creds_xml("AssumeRole", creds, expiry)


# -- OIDC (WebIdentity / ClientGrants) ---------------------------------------


def _openid_conf(config) -> dict:
    get = (lambda k: config.get("identity_openid", k)) if config is not None else (lambda k: "")
    return {
        "jwks": get("jwks"),
        "hmac_secret": get("hmac_secret"),
        "claim_name": get("claim_name") or "policy",
        "client_id": get("client_id"),
    }


def _assume_role_with_token(
    iam: IAMSys, config, form: dict[str, str], token: str, action: str
) -> web.Response:
    conf = _openid_conf(config)
    if not conf["jwks"] and not conf["hmac_secret"]:
        raise S3Error("NotImplemented", "OpenID identity is not configured")
    if not token:
        raise S3Error("InvalidRequest", "missing identity token")
    jwks = None
    if conf["jwks"]:
        try:
            jwks = json.loads(conf["jwks"])
        except ValueError:
            raise S3Error("InternalError", "bad JWKS configuration")
    try:
        claims = jwt_mod.verify(
            token,
            jwks=jwks,
            hmac_secret=conf["hmac_secret"],
            audience=conf["client_id"],
        )
    except jwt_mod.JWTError as e:
        raise S3Error("AccessDenied", f"invalid identity token: {e}")

    raw = claims.get(conf["claim_name"], "")
    policies = (
        [p.strip() for p in raw.split(",") if p.strip()]
        if isinstance(raw, str)
        else [str(p) for p in raw]
    )
    if not policies:
        raise S3Error(
            "AccessDenied", f"token lacks the {conf['claim_name']!r} policy claim"
        )
    # Token exp strictly bounds the credential lifetime (the reference caps
    # at the JWT expiry; credentials must never outlive the identity token).
    duration = _duration(form)
    if claims.get("exp") is not None:
        try:
            remaining = int(float(claims["exp"]) - time.time())
        except (TypeError, ValueError):
            raise S3Error("AccessDenied", "invalid exp claim in identity token")
        if remaining <= 0:
            raise S3Error("AccessDenied", "identity token expired")
        duration = min(duration, remaining)
    creds, expiry = iam.new_sts_credentials_for_policies(
        policies, duration, _session_policy(form)
    )
    subject = str(claims.get("sub", ""))
    extra = (
        f"\n    <SubjectFromWebIdentityToken>{escape(subject)}</SubjectFromWebIdentityToken>"
        if action == "AssumeRoleWithWebIdentity"
        else ""
    )
    return _creds_xml(action, creds, expiry, extra)


# -- LDAP identity ------------------------------------------------------------


def _assume_role_with_ldap(iam: IAMSys, config, form: dict[str, str]) -> web.Response:
    """AssumeRoleWithLDAPIdentity (cmd/sts-handlers.go:447): lookup-bind the
    username, verify the password with a user bind, map the user/group DNs
    through the IAM LDAP policy DB, and issue temp credentials."""
    from ..control import ldap as ldap_mod

    conf = ldap_mod.LDAPConfig.from_config(config)
    if not conf.server_addr:
        raise S3Error("NotImplemented", "LDAP identity is not configured")
    username = form.get("LDAPUsername", "")
    password = form.get("LDAPPassword", "")
    if not username or not password:
        raise S3Error("InvalidRequest", "LDAPUsername and LDAPPassword are required")
    try:
        user_dn, groups = ldap_mod.authenticate(conf, username, password)
    except ldap_mod.LDAPError as e:
        raise S3Error("AccessDenied", f"LDAP authentication failed: {e}")
    policies = iam.ldap_policies_for(user_dn, groups)
    if not policies:
        raise S3Error(
            "AccessDenied", f"no policy mapped for LDAP identity {user_dn!r}"
        )
    creds, expiry = iam.new_sts_credentials_for_policies(
        policies, _duration(form), _session_policy(form)
    )
    return _creds_xml("AssumeRoleWithLDAPIdentity", creds, expiry)


# -- mTLS certificate ---------------------------------------------------------


def _assume_role_with_certificate(
    iam: IAMSys, config, form: dict[str, str], request: web.Request | None
) -> web.Response:
    enabled = config is not None and config.get("identity_tls", "enable") == "on"
    if not enabled:
        raise S3Error("NotImplemented", "TLS identity is not configured")
    peercert = None
    if request is not None and request.transport is not None:
        peercert = request.transport.get_extra_info("peercert")
    if not peercert:
        raise S3Error(
            "InvalidRequest", "a client certificate is required (mTLS connection)"
        )
    # CN names the policy (sts-handlers.go AssumeRoleWithCertificate: the
    # certificate CN maps to the policy of the same name).
    cn = ""
    for rdn in peercert.get("subject", ()):  # ssl module cert dict shape
        for key, value in rdn:
            if key == "commonName":
                cn = value
    if not cn:
        raise S3Error("InvalidRequest", "client certificate has no CN")
    creds, expiry = iam.new_sts_credentials_for_policies([cn], _duration(form, 3600))
    return _creds_xml("AssumeRoleWithCertificate", creds, expiry)


def parse_form(body: bytes) -> dict[str, str]:
    return {k: v[0] for k, v in urllib.parse.parse_qs(body.decode()).items()}
