"""AWS Signature V4 authentication (signing + verification).

Role of the reference's signature-v4.go / signature-v4-parser.go /
auth-handler.go: verify header-signed and presigned requests, and produce
signatures for the test client and internal clients. Streaming per-chunk
signatures (streaming-signature-v4.go) are handled in api/streaming.py.

Auth types recognized (getRequestAuthType equivalent):
  * signed (Authorization: AWS4-HMAC-SHA256 ...)
  * presigned (?X-Amz-Algorithm=AWS4-HMAC-SHA256...)
  * anonymous (no credentials)
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass

from .errors import S3Error

SIGN_V4_ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
MAX_SKEW_SECONDS = 15 * 60


@dataclass
class Credentials:
    access_key: str
    secret_key: str


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "" if encode_slash else "/"
    return urllib.parse.quote(s, safe=safe + "-_.~")


def canonical_query(params: list[tuple[str, str]], skip: set[str] = frozenset()) -> str:
    pairs = sorted(
        (_uri_encode(k), _uri_encode(v)) for k, v in params if k not in skip
    )
    return "&".join(f"{k}={v}" for k, v in pairs)


def canonical_request(
    method: str,
    path: str,
    query: list[tuple[str, str]],
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
    skip_query: set[str] = frozenset(),
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    return "\n".join(
        [
            method.upper(),
            _uri_encode(path, encode_slash=False),
            canonical_query(query, skip_query),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(timestamp: str, scope: str, canon_req: str) -> str:
    return "\n".join(
        [SIGN_V4_ALGORITHM, timestamp, scope, hashlib.sha256(canon_req.encode()).hexdigest()]
    )


def sign_request(
    creds: Credentials,
    method: str,
    path: str,
    query: list[tuple[str, str]],
    headers: dict[str, str],
    payload: bytes | None,
    region: str = "us-east-1",
    timestamp: datetime.datetime | None = None,
    unsigned_payload: bool = False,
    payload_hash: str | None = None,
) -> dict[str, str]:
    """Produce the headers for a signed request (test client / internal RPC).

    Returns the full header dict including Authorization. ``payload_hash``
    overrides the computed hash (e.g. STREAMING-AWS4-HMAC-SHA256-PAYLOAD for
    aws-chunked uploads).
    """
    t = timestamp or datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    headers = {k.lower(): v for k, v in headers.items()}
    headers["x-amz-date"] = amz_date
    if payload_hash is None:
        if unsigned_payload or payload is None:
            payload_hash = UNSIGNED_PAYLOAD
        else:
            payload_hash = hashlib.sha256(payload).hexdigest()
    headers["x-amz-content-sha256"] = payload_hash
    signed = sorted(set(headers) | {"host"})
    scope = f"{date}/{region}/s3/aws4_request"
    creq = canonical_request(method, path, query, headers, signed, payload_hash)
    sts = string_to_sign(amz_date, scope, creq)
    sig = hmac.new(signing_key(creds.secret_key, date, region), sts.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"{SIGN_V4_ALGORITHM} Credential={creds.access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}"
    )
    return headers


@dataclass
class ParsedAuth:
    access_key: str
    date: str
    region: str
    service: str
    signed_headers: list[str]
    signature: str


def parse_authorization(header: str) -> ParsedAuth:
    if not header.startswith(SIGN_V4_ALGORITHM):
        raise S3Error("AuthorizationHeaderMalformed")
    rest = header[len(SIGN_V4_ALGORITHM) :].strip()
    fields: dict[str, str] = {}
    for part in rest.split(","):
        part = part.strip()
        if "=" not in part:
            raise S3Error("AuthorizationHeaderMalformed")
        k, v = part.split("=", 1)
        fields[k.strip()] = v.strip()
    try:
        cred = fields["Credential"].split("/")
        access_key = "/".join(cred[:-4])
        date, region, service, terminal = cred[-4:]
        if terminal != "aws4_request":
            raise S3Error("AuthorizationHeaderMalformed")
        return ParsedAuth(
            access_key=access_key,
            date=date,
            region=region,
            service=service,
            signed_headers=fields["SignedHeaders"].split(";"),
            signature=fields["Signature"],
        )
    except (KeyError, ValueError):
        raise S3Error("AuthorizationHeaderMalformed")


class SigV4Verifier:
    """Verifies V4 signed and presigned requests against a credential lookup."""

    def __init__(self, lookup, region: str = "us-east-1", check_skew: bool = True):
        """lookup: access_key -> Credentials | None."""
        self.lookup = lookup
        self.region = region
        self.check_skew = check_skew

    def _creds(self, access_key: str) -> Credentials:
        c = self.lookup(access_key)
        if c is None:
            raise S3Error("InvalidAccessKeyId")
        return c

    def _check_date(self, amz_date: str) -> None:
        try:
            t = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=datetime.timezone.utc
            )
        except ValueError:
            raise S3Error("AuthorizationHeaderMalformed")
        if self.check_skew:
            skew = abs((datetime.datetime.now(datetime.timezone.utc) - t).total_seconds())
            if skew > MAX_SKEW_SECONDS:
                raise S3Error("RequestTimeTooSkewed")

    def verify_signed(
        self,
        method: str,
        path: str,
        query: list[tuple[str, str]],
        headers: dict[str, str],
        payload: bytes | None,
    ) -> str:
        """Verify a header-signed request; returns the access key
        (doesSignatureMatch, cmd/signature-v4.go:334 equivalent).

        payload=None means the caller verifies the payload hash itself while
        streaming the body (the reference's hash.Reader discipline); the
        signature is still checked against the declared header hash."""
        headers = {k.lower(): v for k, v in headers.items()}
        auth = parse_authorization(headers.get("authorization", ""))
        creds = self._creds(auth.access_key)
        amz_date = headers.get("x-amz-date", headers.get("date", ""))
        self._check_date(amz_date)
        payload_hash = headers.get("x-amz-content-sha256", UNSIGNED_PAYLOAD)
        if payload is not None and payload_hash not in (UNSIGNED_PAYLOAD, STREAMING_PAYLOAD):
            if hashlib.sha256(payload).hexdigest() != payload_hash:
                raise S3Error("XAmzContentSHA256Mismatch")
        scope = f"{auth.date}/{auth.region}/s3/aws4_request"
        creq = canonical_request(
            method, path, query, headers, auth.signed_headers, payload_hash
        )
        sts = string_to_sign(amz_date, scope, creq)
        want = hmac.new(
            signing_key(creds.secret_key, auth.date, auth.region),
            sts.encode(),
            hashlib.sha256,
        ).hexdigest()
        if not hmac.compare_digest(want, auth.signature):
            raise S3Error("SignatureDoesNotMatch")
        return auth.access_key

    def presign_url(
        self,
        creds: Credentials,
        method: str,
        path: str,
        query: list[tuple[str, str]],
        host: str,
        expires: int = 3600,
        timestamp: datetime.datetime | None = None,
    ) -> str:
        """Generate a presigned URL (client side)."""
        t = timestamp or datetime.datetime.now(datetime.timezone.utc)
        amz_date = t.strftime("%Y%m%dT%H%M%SZ")
        date = amz_date[:8]
        scope = f"{date}/{self.region}/s3/aws4_request"
        q = list(query) + [
            ("X-Amz-Algorithm", SIGN_V4_ALGORITHM),
            ("X-Amz-Credential", f"{creds.access_key}/{scope}"),
            ("X-Amz-Date", amz_date),
            ("X-Amz-Expires", str(expires)),
            ("X-Amz-SignedHeaders", "host"),
        ]
        creq = canonical_request(
            method, path, q, {"host": host}, ["host"], UNSIGNED_PAYLOAD
        )
        sts = string_to_sign(amz_date, scope, creq)
        sig = hmac.new(
            signing_key(creds.secret_key, date, self.region), sts.encode(), hashlib.sha256
        ).hexdigest()
        qs = urllib.parse.urlencode(q + [("X-Amz-Signature", sig)])
        return f"http://{host}{path}?{qs}"

    def verify_presigned(
        self,
        method: str,
        path: str,
        query: list[tuple[str, str]],
        headers: dict[str, str],
    ) -> str:
        """Verify a presigned request; returns the access key
        (doesPresignedSignatureMatch equivalent)."""
        qd = dict(query)
        try:
            if qd.get("X-Amz-Algorithm") != SIGN_V4_ALGORITHM:
                raise S3Error("AuthorizationHeaderMalformed")
            cred = qd["X-Amz-Credential"].split("/")
            access_key = "/".join(cred[:-4])
            date, region, service, terminal = cred[-4:]
            amz_date = qd["X-Amz-Date"]
            expires = int(qd.get("X-Amz-Expires", "3600"))
            signed_headers = qd["X-Amz-SignedHeaders"].split(";")
            given_sig = qd["X-Amz-Signature"]
        except (KeyError, ValueError):
            raise S3Error("AuthorizationHeaderMalformed")
        creds = self._creds(access_key)
        t = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
        if self.check_skew:
            now = datetime.datetime.now(datetime.timezone.utc)
            if now > t + datetime.timedelta(seconds=expires):
                raise S3Error("ExpiredPresignRequest")
            if now < t - datetime.timedelta(seconds=MAX_SKEW_SECONDS):
                raise S3Error("RequestTimeTooSkewed")
        headers = {k.lower(): v for k, v in headers.items()}
        scope = f"{date}/{region}/s3/aws4_request"
        creq = canonical_request(
            method,
            path,
            query,
            headers,
            signed_headers,
            UNSIGNED_PAYLOAD,
            skip_query={"X-Amz-Signature"},
        )
        sts = string_to_sign(amz_date, scope, creq)
        want = hmac.new(
            signing_key(creds.secret_key, date, region), sts.encode(), hashlib.sha256
        ).hexdigest()
        if not hmac.compare_digest(want, given_sig):
            raise S3Error("SignatureDoesNotMatch")
        return access_key
