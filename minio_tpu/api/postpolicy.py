"""POST policy form uploads (browser uploads).

Role of the reference's cmd/postpolicyform.go + PostPolicyBucketHandler
(bucket-handlers.go): a multipart/form-data POST to the bucket carrying a
base64 policy document, a V4 signature over it, the object key, and the file
payload. The policy constrains what the form may upload (key prefix,
content-length range, exact-match fields) with an expiration.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .auth import SIGN_V4_ALGORITHM, signing_key
from .errors import S3Error


# ------------------------------------------------------------- form parsing


def parse_multipart_form(body: bytes, content_type: str) -> Dict[str, bytes]:
    """Minimal multipart/form-data parser; returns field name -> value.

    The file part is stored under 'file'; its Content-Disposition filename
    (used for ``${filename}`` key substitution) under '__filename__'.
    (aiohttp's reader needs a live stream; handlers here already hold the
    full body.)
    """
    if "boundary=" not in content_type:
        raise S3Error("MalformedPOSTRequest", "missing multipart boundary")
    boundary = content_type.split("boundary=", 1)[1].split(";")[0].strip().strip('"')
    delim = b"--" + boundary.encode()
    fields: Dict[str, bytes] = {}
    parts = body.split(delim)
    for part in parts[1:]:
        if part.startswith(b"--"):
            break  # closing delimiter
        part = part.lstrip(b"\r\n")
        if b"\r\n\r\n" not in part:
            continue
        raw_headers, _, content = part.partition(b"\r\n\r\n")
        if content.endswith(b"\r\n"):
            content = content[:-2]
        disposition = ""
        for line in raw_headers.split(b"\r\n"):
            if line.lower().startswith(b"content-disposition:"):
                disposition = line.decode("latin-1")
        name = ""
        filename = None
        for attr in disposition.split(";"):
            attr = attr.strip()
            if attr.startswith("name="):
                name = attr[len("name="):].strip('"')
            elif attr.startswith("filename="):
                filename = attr[len("filename="):].strip('"')
        if name:
            fields[name] = content
            if name == "file" and filename is not None:
                fields["__filename__"] = filename.encode()
    return fields


# ------------------------------------------------------------ policy checks


@dataclass
class PostPolicy:
    expiration: Optional[datetime.datetime]
    # list of (kind, key, value[, upper]) conditions
    eq: List[Tuple[str, str]] = field(default_factory=list)
    starts_with: List[Tuple[str, str]] = field(default_factory=list)
    length_range: Optional[Tuple[int, int]] = None

    @classmethod
    def parse(cls, policy_json: bytes) -> "PostPolicy":
        try:
            doc = json.loads(policy_json)
        except ValueError as e:
            raise S3Error("MalformedPOSTRequest", f"invalid policy JSON: {e}")
        exp = None
        if "expiration" in doc:
            raw = doc["expiration"].replace("Z", "+00:00")
            try:
                exp = datetime.datetime.fromisoformat(raw)
            except ValueError:
                raise S3Error("MalformedPOSTRequest", "bad expiration")
            if exp.tzinfo is None:
                exp = exp.replace(tzinfo=datetime.timezone.utc)
        pol = cls(expiration=exp)
        for cond in doc.get("conditions", []):
            if isinstance(cond, dict):
                for k, v in cond.items():
                    pol.eq.append((k.lower(), str(v)))
            elif isinstance(cond, list) and len(cond) >= 3:
                op = str(cond[0]).lower()
                if op == "eq":
                    pol.eq.append((str(cond[1]).lstrip("$").lower(), str(cond[2])))
                elif op == "starts-with":
                    pol.starts_with.append((str(cond[1]).lstrip("$").lower(), str(cond[2])))
                elif op == "content-length-range":
                    pol.length_range = (int(cond[1]), int(cond[2]))
                else:
                    raise S3Error("MalformedPOSTRequest", f"unknown condition {op}")
            else:
                raise S3Error("MalformedPOSTRequest", "bad condition")
        return pol

    def check(self, form: Dict[str, bytes], file_size: int, bucket: str = "") -> None:
        if self.expiration is not None:
            if datetime.datetime.now(datetime.timezone.utc) > self.expiration:
                raise S3Error("AccessDenied", "policy expired")
        lower = {
            k.lower(): v.decode("utf-8", "replace")
            for k, v in form.items()
            if k not in ("file", "__filename__")
        }
        # The bucket comes from the request URL, not a form field.
        lower["bucket"] = bucket
        # Fields whose values the signature itself covers (or that only shape
        # the response), exempt from the must-be-in-policy rule.
        exempt = {"x-amz-signature", "policy", "x-amz-algorithm", "x-amz-credential",
                  "x-amz-date", "bucket"}
        for k, want in self.eq:
            got = lower.get(k)
            if got is None or got != want:
                raise S3Error("AccessDenied", f"policy condition failed: eq ${k}")
        for k, prefix in self.starts_with:
            got = lower.get(k, "")
            if not got.startswith(prefix):
                raise S3Error("AccessDenied", f"policy condition failed: starts-with ${k}")
        if self.length_range is not None:
            lo, hi = self.length_range
            if not (lo <= file_size <= hi):
                raise S3Error("EntityTooLarge" if file_size > hi else "EntityTooSmall")
        # Every other form field must be authorized by some policy condition
        # (matching real S3/MinIO: checkPostPolicy rejects unknown fields).
        allowed = {k for k, _ in self.eq} | {k for k, _ in self.starts_with}
        for k in lower:
            if k in exempt or k in allowed:
                continue
            raise S3Error(
                "AccessDenied", f"form field ${k} not covered by policy conditions"
            )


def verify_post_signature(form: Dict[str, bytes], lookup) -> str:
    """Verify the V4 signature over the base64 policy; returns the access key.

    lookup: access_key -> Credentials | None.
    """
    policy_b64 = form.get("policy", b"").decode()
    algorithm = form.get("x-amz-algorithm", b"").decode()
    credential = form.get("x-amz-credential", b"").decode()
    amz_date = form.get("x-amz-date", b"").decode()
    given = form.get("x-amz-signature", b"").decode()
    if algorithm != SIGN_V4_ALGORITHM:
        raise S3Error("AccessDenied", "unsupported signature algorithm")
    if not policy_b64 or not credential or not given:
        raise S3Error("AccessDenied", "missing policy signature fields")
    parts = credential.split("/")
    if len(parts) < 5 or parts[-1] != "aws4_request":
        raise S3Error("AuthorizationHeaderMalformed")
    access_key = "/".join(parts[:-4])
    date, region, _service, _terminal = parts[-4:]
    creds = lookup(access_key)
    if creds is None:
        raise S3Error("InvalidAccessKeyId")
    key = signing_key(creds.secret_key, date, region)
    want = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, given):
        raise S3Error("SignatureDoesNotMatch")
    return access_key


def build_post_form(
    creds,
    bucket: str,
    key: str,
    data: bytes,
    region: str = "us-east-1",
    expires_in: int = 3600,
    extra_conditions: Optional[list] = None,
    extra_fields: Optional[Dict[str, str]] = None,
) -> Tuple[bytes, str]:
    """Client side: build a signed multipart POST body; returns (body, content_type)."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    credential = f"{creds.access_key}/{date}/{region}/s3/aws4_request"
    expiration = (now + datetime.timedelta(seconds=expires_in)).strftime("%Y-%m-%dT%H:%M:%S.000Z")
    conditions = [
        {"bucket": bucket},
        ["eq", "$key", key],
        {"x-amz-algorithm": SIGN_V4_ALGORITHM},
        {"x-amz-credential": credential},
        {"x-amz-date": amz_date},
    ] + [["eq", f"${k}", v] for k, v in (extra_fields or {}).items()] + (
        extra_conditions or []
    )
    policy = base64.b64encode(
        json.dumps({"expiration": expiration, "conditions": conditions}).encode()
    ).decode()
    sig = hmac.new(
        signing_key(creds.secret_key, date, region), policy.encode(), hashlib.sha256
    ).hexdigest()
    fields = {
        "key": key,
        "x-amz-algorithm": SIGN_V4_ALGORITHM,
        "x-amz-credential": credential,
        "x-amz-date": amz_date,
        "policy": policy,
        "x-amz-signature": sig,
    }
    fields.update(extra_fields or {})
    boundary = "----minio-tpu-post-" + hashlib.md5(policy.encode()).hexdigest()[:16]
    out = bytearray()
    for name, value in fields.items():
        out += f"--{boundary}\r\nContent-Disposition: form-data; name=\"{name}\"\r\n\r\n{value}\r\n".encode()
    out += (
        f"--{boundary}\r\nContent-Disposition: form-data; name=\"file\"; filename=\"upload\"\r\n"
        "Content-Type: application/octet-stream\r\n\r\n"
    ).encode()
    out += data + f"\r\n--{boundary}--\r\n".encode()
    return bytes(out), f"multipart/form-data; boundary={boundary}"
