"""`python -m minio_tpu` entry point (reference main.go:34 -> cmd.Main)."""

from .cli import main

raise SystemExit(main())
