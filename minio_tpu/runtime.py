"""Data-plane runtime: install the block codec the server actually serves with.

The reference's hot path always runs its fast codec (AVX2 reedsolomon,
cmd/erasure-coding.go:63). Here the equivalent decision happens once at boot:
if an accelerator is reachable, every PutObject/heal block goes through the
cross-request batching device pipeline (parallel/batching.py); otherwise the
host C++/numpy codec serves (object/codec.py HostCodec).

Device init is probed in a bounded subprocess first: the environment may
register a hardware TPU plugin whose in-process client init can block on a
tunnel, and server boot must never wedge on it.

Env:
    MINIO_TPU_CODEC = auto | device | host   (default auto)
    MINIO_TPU_DEVICE_PROBE_S                 probe timeout, default 60
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

from .object import codec as codec_mod


def probe_device(timeout_s: float) -> str | None:
    """Bounded subprocess probe of jax device init; platform name or None."""
    code = "import jax; print(jax.devices()[0].platform, flush=True)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if out.returncode == 0 and out.stdout.strip():
        return out.stdout.strip().splitlines()[-1]
    return None


def _make_batching():
    from .parallel.batching import BatchingDeviceCodec

    codec = BatchingDeviceCodec()

    # Warm the jitted pipeline for the production geometry off the serving
    # path (first XLA compile can take tens of seconds; a cold first
    # PutObject should not eat it).
    def _warm():
        try:
            block = b"\0" * codec.block_size
            codec.encode([block], 12, 4)
        except Exception:  # noqa: BLE001 - warmup is best-effort
            pass

    threading.Thread(target=_warm, daemon=True, name="codec-warmup").start()
    return codec


_closed = False


def install_data_plane_codec(
    mode: str | None = None,
    probe_timeout_s: float | None = None,
    background: bool = False,
) -> codec_mod.BlockCodec:
    """Pick + install the process-wide codec; returns it.

    With background=True (server boot), auto mode installs the host codec
    immediately and upgrades the process default to the batching device
    codec from a daemon thread once the probe lands -- boot never blocks on
    a wedged device tunnel, and the object layer's lazy default-codec
    resolution makes the swap take effect on live traffic."""
    global _closed
    _closed = False
    mode = (mode or os.environ.get("MINIO_TPU_CODEC", "auto")).lower()
    if probe_timeout_s is None:
        probe_timeout_s = float(os.environ.get("MINIO_TPU_DEVICE_PROBE_S", "60"))
    if mode == "host":
        codec: codec_mod.BlockCodec = codec_mod.HostCodec()
    elif mode == "device":
        codec = _make_batching()
    elif background:
        codec = codec_mod.HostCodec()
        codec_mod.set_default_codec(codec)

        def _bg(timeout=probe_timeout_s):
            platform = probe_device(timeout)
            if platform not in (None, "cpu") and not _closed:
                codec_mod.set_default_codec(_make_batching())

        threading.Thread(target=_bg, daemon=True, name="codec-probe").start()
        return codec
    else:  # auto, synchronous: only pay device round trips for an accelerator
        platform = probe_device(probe_timeout_s)
        codec = _make_batching() if platform not in (None, "cpu") else codec_mod.HostCodec()
    codec_mod.set_default_codec(codec)
    return codec


def shutdown_data_plane(codec: codec_mod.BlockCodec | None = None) -> None:
    """Close the batching codec (if installed); safe to call many times."""
    global _closed
    _closed = True
    for c in {id(codec): codec, id(codec_mod._default): codec_mod._default}.values():
        close = getattr(c, "close", None)
        if close is not None:
            close()
