"""Data-plane runtime: install the block codec the server actually serves with.

The reference's hot path always runs its fast codec (AVX2 reedsolomon,
cmd/erasure-coding.go:63). Here the equivalent decision happens once at boot:
if an accelerator is reachable, every PutObject/heal block goes through the
cross-request batching device pipeline (parallel/batching.py); otherwise the
host C++/numpy codec serves (object/codec.py HostCodec).

Device init is probed in a bounded subprocess first: the environment may
register a hardware TPU plugin whose in-process client init can block forever
on a dead tunnel (observed: PJRT make_c_api_client retrying a refused relay
at 127.0.0.1:8083), and server boot must never wedge on it. The probe
 * runs exactly once per process (cached — repeated Node builds / tests
   must not fork probe swarms),
 * is spawned in its own session and killed as a process group on timeout
   (no orphaned children holding tunnel connections),
 * keeps the child's stdout/stderr tail — including an in-child
   faulthandler dump of the wedged stack — so a timeout carries evidence.

Env:
    MINIO_TPU_CODEC = auto | device | host   (default auto)
    MINIO_TPU_DEVICE_PROBE_S                 probe timeout, default 60
    MTPU_PROBE_CACHE                         path of a cross-process verdict
                                             cache file ("" / unset = off)
    MTPU_PROBE_CACHE_TTL_S                   verdict freshness, default 3600
                                             (failed verdicts: capped at 900)
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field


from .object import codec as codec_mod
from .control.sanitizer import san_lock, san_rlock


@dataclass
class ProbeResult:
    """Outcome of one bounded device-init probe."""

    platform: str | None  # "tpu"/"axon"/... on success, None on failure
    device_kind: str | None = None
    error: str | None = None  # short reason on failure
    detail: str = ""  # stdout+stderr tail (faulthandler dump, relay checks)
    cached: bool = False  # True when served from the cross-process file cache

    @property
    def ok(self) -> bool:
        return self.platform not in (None, "cpu")


_live_probe_pgids: set[int] = set()
_probe_lock = san_lock("runtime._probe_lock")
_probe_once_lock = san_lock("runtime._probe_once_lock")  # single-flight: at most one child at a time
_probe_cache: ProbeResult | None = None
_atexit_registered = False


def _reap_live_probes() -> None:
    """Kill any probe process groups still alive at interpreter exit."""
    with _probe_lock:
        pgids = list(_live_probe_pgids)
        _live_probe_pgids.clear()
    for pgid in pgids:
        try:
            os.killpg(pgid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass


def _tail(text: str, limit: int = 4000) -> str:
    return text[-limit:] if len(text) > limit else text


# -- cross-process probe verdict cache ----------------------------------------
#
# The in-memory cache above is per-process; bench.py and tools/loadgen.py are
# fresh processes every run and were re-paying the full probe (180 s against a
# wedged tunnel, BENCH_r04-r05) just to re-learn a verdict that rarely
# changes. When MTPU_PROBE_CACHE names a file, completed verdicts are stored
# there with a timestamp and honored within MTPU_PROBE_CACHE_TTL_S (default
# 3600 s). Failed verdicts are honored for at most 900 s -- a recovered
# device must not stay masked for an hour -- so re-probing is bounded, not
# eliminated. The cache is OPT-IN: servers and tests probe in-process as
# before unless the env names a path.

_PROBE_FAIL_TTL_CAP_S = 900.0

# Verdict *transitions* are first-class: a device falling over (ok -> fail,
# "fallback") and coming back (fail -> ok, "recovery") are the two events an
# operator actually pages on, and a cache that silently flips between them
# hides both. Every stored verdict is diffed against the previous one (file
# or in-memory); the last _TRANSITIONS_KEPT flips ride along in the cache
# file and the latest is exposed via probe_transition() for bench JSON.
_TRANSITIONS_KEPT = 8
_last_transition: dict | None = None
# In-process fallback/recovery tallies (metrics + perf endpoint): how many
# times this process saw the verdict flip each way.
_transition_counts = {"fallback": 0, "recovery": 0}


def _transition_between(prev_platform, result: "ProbeResult") -> dict | None:
    """A fallback/recovery record when the ok-ness flipped, else None."""
    prev_ok = prev_platform not in (None, "cpu")
    if prev_ok == result.ok:
        return None
    return {
        "time": time.time(),
        "kind": "recovery" if result.ok else "fallback",
        "from": prev_platform,
        "to": result.platform,
    }


def _note_transition(t: dict | None) -> None:
    global _last_transition
    if t is not None:
        with _probe_lock:
            _last_transition = t
            if t.get("kind") in _transition_counts:
                _transition_counts[t["kind"]] += 1


def probe_transition_counts() -> dict:
    """{"fallback": n, "recovery": n} verdict flips seen by this process."""
    with _probe_lock:
        return dict(_transition_counts)


def probe_transition() -> dict | None:
    """The most recent ok<->fail probe transition, or None if the verdict
    has never flipped. In-process memory first, then the cross-process
    cache file -- so a fresh bench process still reports the fallback (or
    recovery) that the verdict it inherited went through."""
    with _probe_lock:
        if _last_transition is not None:
            return dict(_last_transition)
    path = _probe_cache_file()
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    t = doc.get("transition")
    return t if isinstance(t, dict) else None


def _probe_cache_file() -> str:
    return os.environ.get("MTPU_PROBE_CACHE", "")


def _probe_cache_ttl() -> float:
    try:
        return float(os.environ.get("MTPU_PROBE_CACHE_TTL_S", "") or 3600.0)
    except ValueError:
        return 3600.0


def _load_probe_file() -> ProbeResult | None:
    """Fresh cached verdict from MTPU_PROBE_CACHE, or None (disabled /
    missing / stale / unreadable -- every miss means 'probe for real')."""
    path = _probe_cache_file()
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "time" not in doc:
        return None
    try:
        age = time.time() - float(doc["time"])
    except (TypeError, ValueError):
        return None
    ttl = _probe_cache_ttl()
    platform = doc.get("platform") or None
    if platform in (None, "cpu"):
        ttl = min(ttl, _PROBE_FAIL_TTL_CAP_S)
    if age < 0 or age >= ttl:
        return None
    return ProbeResult(
        platform,
        doc.get("device_kind") or None,
        error=doc.get("error") or None,
        detail=str(doc.get("detail", "")),
        cached=True,
    )


def _store_probe_file(result: ProbeResult) -> None:
    """Best-effort atomic write of the verdict (tmp + rename); a cache that
    cannot be written must never fail the probe that produced the result."""
    path = _probe_cache_file()
    if not path:
        return
    # Diff against whatever verdict the file held -- even a stale one: a
    # flip across a TTL expiry is still a flip worth surfacing.
    transitions: list = []
    try:
        with open(path) as f:
            old = json.load(f)
        if isinstance(old, dict):
            prior = old.get("transitions")
            if isinstance(prior, list):
                transitions = [t for t in prior if isinstance(t, dict)]
            t = _transition_between(old.get("platform") or None, result)
            if t is not None:
                transitions.append(t)
                _note_transition(t)
    except (OSError, ValueError):
        pass
    transitions = transitions[-_TRANSITIONS_KEPT:]
    doc = {
        "time": time.time(),
        "platform": result.platform,
        "device_kind": result.device_kind,
        "error": result.error,
        "detail": _tail(result.detail, 2000),
        "transitions": transitions,
        "transition": transitions[-1] if transitions else None,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def probe_device(timeout_s: float, use_cache: bool = True) -> ProbeResult:
    """Bounded, evidence-preserving, non-leaking probe of jax device init.

    The child (``minio_tpu._probe_child``) prints relay-port reachability and
    arms a faulthandler dump before importing jax, so on timeout the captured
    tail pinpoints the wedge. The child runs in its own session; on timeout
    its whole process group is SIGKILLed, and an atexit hook reaps any probe
    that outlives us (e.g. a daemon-thread caller exiting mid-probe).
    """
    global _probe_cache, _atexit_registered
    # Single-flight: concurrent callers (e.g. several in-process nodes booting
    # with background installs) must not fork a probe swarm — the second
    # caller waits and gets the first's cached result.
    with _probe_once_lock:
        with _probe_lock:
            if use_cache and _probe_cache is not None:
                return _probe_cache
            if not _atexit_registered:
                atexit.register(_reap_live_probes)
                _atexit_registered = True
        if use_cache:
            filed = _load_probe_file()
            if filed is not None:
                with _probe_lock:
                    _probe_cache = filed
                return filed
        return _probe_uncached(timeout_s)


def probe_status() -> ProbeResult | None:
    """The cached probe outcome WITHOUT triggering a probe (metrics reads
    this: a scrape must never fork a device-init subprocess). None until a
    probe has run."""
    with _probe_lock:
        return _probe_cache


def _probe_uncached(timeout_s: float) -> ProbeResult:
    global _probe_cache
    out_f = tempfile.TemporaryFile(mode="w+b")
    err_f = tempfile.TemporaryFile(mode="w+b")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "minio_tpu._probe_child", str(timeout_s)],
            stdout=out_f,
            stderr=err_f,
            start_new_session=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
    except OSError as e:
        out_f.close()
        err_f.close()
        result = ProbeResult(None, error=f"spawn failed: {e}")
        with _probe_lock:
            _probe_cache = result
        return result

    pgid = proc.pid  # start_new_session=True -> child leads its own pgrp
    with _probe_lock:
        _live_probe_pgids.add(pgid)
    try:
        try:
            proc.wait(timeout=timeout_s)
            timed_out = False
        except subprocess.TimeoutExpired:
            timed_out = True
            try:
                os.killpg(pgid, signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
    finally:
        with _probe_lock:
            _live_probe_pgids.discard(pgid)

    out_f.seek(0)
    err_f.seek(0)
    # SIGKILL can truncate mid-multibyte-sequence, and native PJRT/absl logs
    # aren't guaranteed UTF-8 — never let decoding errors mask the evidence.
    stdout = out_f.read().decode("utf-8", errors="replace")
    stderr = err_f.read().decode("utf-8", errors="replace")
    out_f.close()
    err_f.close()
    detail = _tail(stdout + ("\n--- stderr ---\n" + stderr if stderr else ""))

    if timed_out:
        result = ProbeResult(
            None, error=f"device init wedged past {timeout_s:.0f}s (killed pg)", detail=detail
        )
    else:
        ok_line = next(
            (ln for ln in reversed(stdout.splitlines()) if ln.startswith("PROBE_OK ")), None
        )
        if proc.returncode == 0 and ok_line:
            parts = ok_line.split()
            result = ProbeResult(parts[1], parts[2] if len(parts) > 2 else None, detail=detail)
        else:
            result = ProbeResult(
                None, error=f"probe exit={proc.returncode}", detail=detail
            )
    with _probe_lock:
        prev = _probe_cache
        _probe_cache = result
    if prev is not None:
        _note_transition(_transition_between(prev.platform, result))
    _store_probe_file(result)
    return result


def _make_batching():
    from .parallel.batching import BatchingDeviceCodec

    codec = BatchingDeviceCodec()

    # Warm the jitted pipeline for the production geometry off the serving
    # path (first XLA compile can take tens of seconds; a cold first
    # PutObject should not eat it).
    def _warm():
        try:
            block = b"\0" * codec.block_size
            codec.encode([block], 12, 4)
        except Exception:  # noqa: BLE001 - warmup is best-effort
            pass

    # mtpulint: disable=unjoined-thread -- bounded one-shot: encodes a single
    # block and exits on its own; joining would re-serialize boot on XLA
    # compile, the exact stall this thread exists to hide.
    threading.Thread(target=_warm, daemon=True, name="codec-warmup").start()
    return codec


# install/shutdown share one lock so a background probe can't install a fresh
# device codec (spawning worker threads) after shutdown already closed the
# data plane (TOCTOU the advisor flagged).
_state_lock = san_lock("runtime._state_lock")
_closed = False


# -- periodic recovery re-probe -----------------------------------------------
#
# BENCH r04-r05: a wedged tunnel at boot parked the node on the CPU codec for
# its whole life even after the device recovered. When an auto-mode install
# lands on the host codec, a single daemon re-probes on a cadence
# (MTPU_PROBE_RECOVERY_S, default 300 s; <= 0 disables) and swaps in the
# batching device codec on the first good verdict -- no restart. Each tick is
# the same bounded supervised child as boot, and the cross-process file cache
# still amortizes verdicts (failed verdicts honored <= 900 s), so the cadence
# bounds *wait*, not child spawns.

_reprobe_stop = threading.Event()
_reprobe_thread: threading.Thread | None = None
_recovery_probes = 0


def _recovery_interval_s() -> float:
    try:
        return float(os.environ.get("MTPU_PROBE_RECOVERY_S", "") or 300.0)
    except ValueError:
        return 300.0


def _recovery_loop(probe_timeout_s: float) -> None:
    global _probe_cache, _recovery_probes
    while True:
        interval = _recovery_interval_s()  # re-read: can be flipped live
        if interval <= 0:
            return
        if _reprobe_stop.wait(interval):
            return
        with _state_lock:
            if _closed:
                return
        with _probe_lock:
            # Drop only the in-memory verdict: probe_device would otherwise
            # return the boot-time failure forever. The file cache (if
            # configured) still answers within its failed-verdict TTL, so a
            # fleet of nodes doesn't re-probe in lockstep.
            prev = _probe_cache
            _probe_cache = None
            _recovery_probes += 1
        result = probe_device(probe_timeout_s)
        if prev is not None and (result.cached or not _probe_cache_file()):
            # _probe_uncached saw prev=None (we cleared it) and the file-cache
            # diff in _store_probe_file only runs on real probes with a cache
            # file configured -- cover the remaining paths here.
            _note_transition(_transition_between(prev.platform, result))
        if not result.ok:
            continue
        with _state_lock:
            if _closed:
                return
            dev = _make_batching()
            codec_mod.set_default_codec(dev)
        return


def _start_recovery_reprobe(probe_timeout_s: float) -> None:
    global _reprobe_thread
    if _recovery_interval_s() <= 0:
        return
    with _state_lock:
        if _closed:
            return
        if _reprobe_thread is not None and _reprobe_thread.is_alive():
            return
        _reprobe_stop.clear()
        # mtpulint: disable=unjoined-thread -- lifecycle bounded by the
        # _reprobe_stop event (shutdown_data_plane sets it) and the
        # _state_lock/_closed fence; exits on first good verdict.
        t = threading.Thread(
            target=_recovery_loop, args=(probe_timeout_s,), daemon=True, name="codec-reprobe"
        )
        _reprobe_thread = t
        t.start()


def probe_summary() -> dict:
    """Probe state for the admin perf endpoint and metrics: verdict,
    transition history, and recovery-reprobe posture. Never probes."""
    st = probe_status()
    with _probe_lock:
        reprobes = _recovery_probes
    armed = _reprobe_thread is not None and _reprobe_thread.is_alive()
    return {
        "done": st is not None,
        "ok": bool(st.ok) if st is not None else False,
        "platform": st.platform if st is not None else None,
        "cached": bool(st.cached) if st is not None else False,
        "transition": probe_transition(),
        "transition_counts": probe_transition_counts(),
        "recovery": {
            "interval_s": _recovery_interval_s(),
            "armed": armed,
            "reprobes": reprobes,
        },
    }


def install_data_plane_codec(
    mode: str | None = None,
    probe_timeout_s: float | None = None,
    background: bool = False,
) -> codec_mod.BlockCodec:
    """Pick + install the process-wide codec; returns it.

    With background=True (server boot), auto mode installs the host codec
    immediately and upgrades the process default to the batching device
    codec from a daemon thread once the probe lands -- boot never blocks on
    a wedged device tunnel, and the object layer's lazy default-codec
    resolution makes the swap take effect on live traffic."""
    global _closed
    with _state_lock:
        _closed = False
    mode = (mode or os.environ.get("MINIO_TPU_CODEC", "auto")).lower()
    if probe_timeout_s is None:
        probe_timeout_s = float(os.environ.get("MINIO_TPU_DEVICE_PROBE_S", "60"))
    if mode == "host":
        codec: codec_mod.BlockCodec = codec_mod.HostCodec()
    elif mode == "device":
        codec = _make_batching()
    elif background:
        codec = codec_mod.HostCodec()
        codec_mod.set_default_codec(codec)

        def _bg(timeout=probe_timeout_s):
            if not probe_device(timeout).ok:
                _start_recovery_reprobe(timeout)
                return
            with _state_lock:
                if _closed:
                    return
                dev = _make_batching()
                codec_mod.set_default_codec(dev)

        # mtpulint: disable=unjoined-thread -- bounded one-shot probe whose
        # timeout caps its life; the _state_lock/_closed handshake above
        # already fences it against shutdown, which must not block on it.
        threading.Thread(target=_bg, daemon=True, name="codec-probe").start()
        return codec
    else:  # auto, synchronous: only pay device round trips for an accelerator
        if probe_device(probe_timeout_s).ok:
            codec = _make_batching()
        else:
            codec = codec_mod.HostCodec()
            _start_recovery_reprobe(probe_timeout_s)
    with _state_lock:
        if _closed:
            # shutdown_data_plane raced us: don't install after shutdown.
            close = getattr(codec, "close", None)
            if close is not None:
                close()
            return codec
        codec_mod.set_default_codec(codec)
    return codec


def shutdown_data_plane(codec: codec_mod.BlockCodec | None = None) -> None:
    """Close the batching codec (if installed); safe to call many times."""
    global _closed
    _reprobe_stop.set()
    with _state_lock:
        _closed = True
        targets = {id(codec): codec, id(codec_mod._default): codec_mod._default}
    for c in targets.values():
        close = getattr(c, "close", None)
        if close is not None:
            close()
