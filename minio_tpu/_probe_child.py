"""Device-probe child: bounded jax init with self-diagnosis.

Run as ``python -m minio_tpu._probe_child [timeout_s]`` in a fresh process.
Prints a machine-readable transcript on stdout/stderr that the parent
(runtime.probe_device) keeps even on timeout, so a wedged device init leaves
evidence instead of a bare "timeout" (the reference hard-fails boot self-tests
loudly, cmd/server-main.go:434-436; a silent wedge is the worst outcome).

What it prints before touching jax:
  * the env vars that steer PJRT plugin registration,
  * a TCP reachability check of the tunnel relay endpoints the plugin will
    dial (session :8082, stateless :8083 used by jax.devices()),
and then arms ``faulthandler.dump_traceback_later`` so that if jax wedges,
the exact blocked frame (e.g. xla_client.make_c_api_client) is dumped to
stderr ~85% into the parent's timeout budget, while the parent is still
capturing output.

On success prints ``PROBE_OK <platform> <device_kind>`` as the last stdout
line and exits 0.
"""

from __future__ import annotations

import faulthandler
import os
import socket
import sys
import time

RELAY_PORTS = (8082, 8083)


def _tcp_check(host: str, port: int, timeout: float = 3.0) -> str:
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return "open"
    except ConnectionRefusedError:
        return "refused"
    except (TimeoutError, socket.timeout):
        return "timeout"
    except OSError as e:
        return f"error:{e.errno}"


def main() -> int:
    t0 = time.time()
    timeout_s = float(sys.argv[1]) if len(sys.argv) > 1 else 60.0
    env_keys = sorted(
        k
        for k in os.environ
        if k.startswith(("JAX_", "PALLAS_AXON", "AXON_", "TPU_", "XLA_", "LIBTPU"))
    )
    print(
        "[probe] env: " + " ".join(f"{k}={os.environ[k]}" for k in env_keys),
        flush=True,
    )
    hosts = [
        h.strip()
        for h in os.environ.get("PALLAS_AXON_POOL_IPS", "").split(",")
        if h.strip()
    ]
    override = os.environ.get("AXON_POOL_SVC_OVERRIDE")
    if override and override not in hosts:
        hosts.append(override)
    for host in hosts or ["127.0.0.1"]:
        for port in RELAY_PORTS:
            print(f"[probe] relay {host}:{port} -> {_tcp_check(host, port)}", flush=True)

    # Dump the wedged stack while the parent is still listening.
    dump_at = max(5.0, timeout_s * 0.85)
    faulthandler.dump_traceback_later(dump_at, repeat=False, file=sys.stderr)

    import jax  # noqa: PLC0415 - after diagnostics on purpose

    print(f"[probe] import jax ok {time.time() - t0:.1f}s v{jax.__version__}", flush=True)
    devs = jax.devices()
    d = devs[0]
    print(f"[probe] devices ok {time.time() - t0:.1f}s n={len(devs)}", flush=True)
    # Prove the chip executes, not just enumerates: tiny u8 op round-trip.
    x = jax.numpy.ones((128, 128), dtype=jax.numpy.uint8)
    y = jax.jit(lambda a: a @ a)(x)
    y.block_until_ready()
    print(f"[probe] exec ok {time.time() - t0:.1f}s", flush=True)
    faulthandler.cancel_dump_traceback_later()
    kind = getattr(d, "device_kind", "?").replace(" ", "_")
    print(f"PROBE_OK {d.platform} {kind}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
