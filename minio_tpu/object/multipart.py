"""Multipart uploads on the erasure set.

Role of the reference's erasure-multipart.go: parts are erasure-coded and
staged under the system meta bucket
(.minio_tpu.sys/multipart/<bucket>/<object>/<uploadId>/), then atomically
assembled into the object on CompleteMultipartUpload by renaming the staged
shard files into the object's data dir and publishing a multi-part FileInfo
(parts carry per-part sizes so reads/heals can reframe each part's bitrot
stream).

Uses the same distribution as the final object (hash_order of bucket/object),
so each drive keeps the same shard row across parts and completion is pure
renames -- no re-coding.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid

from ..chaos import crash
from ..control import tracing
from ..control.degrade import GLOBAL_DEGRADE
from ..storage.types import ErasureInfo, FileInfo, ObjectPartInfo, now
from ..utils import deadline, errors
from ..utils.hashes import hash_order
from . import metadata as meta_mod
from .erasure import BLOCK_SIZE, META_BUCKET, ErasureObjects
from .types import ObjectInfo, PutObjectOptions

MIN_PART_SIZE = 5 * (1 << 20)  # S3 minimum (except last part)
MAX_PARTS = 10_000


def _upload_dir(bucket: str, object_name: str, upload_id: str) -> str:
    return f"multipart/{bucket}/{object_name}/{upload_id}"


class MultipartManager:
    def __init__(self, eo: ErasureObjects):
        self.eo = eo

    # -- initiate ------------------------------------------------------------

    def new_multipart_upload(
        self, bucket: str, object_name: str, opts: PutObjectOptions | None = None
    ) -> str:
        opts = opts or PutObjectOptions()
        self.eo.get_bucket_info(bucket)  # cached existence gate
        upload_id = str(uuid.uuid4())
        doc = json.dumps(
            {
                "bucket": bucket,
                "object": object_name,
                "created": now(),
                "content_type": opts.content_type,
                "user_defined": opts.user_defined,
                "versioned": opts.versioned,
                "storage_class": opts.storage_class,
            }
        ).encode()
        path = _upload_dir(bucket, object_name, upload_id) + "/upload.json"

        def write(d):
            if d is None:
                raise errors.DiskNotFound()
            d.write_all(META_BUCKET, path, doc)

        results = meta_mod.parallel_map(write, self.eo._online())
        n_ok = sum(1 for _, e in results if e is None)
        if n_ok < self.eo.drive_count // 2 + 1:
            raise errors.ErasureWriteQuorum(bucket, object_name, "initiate multipart")
        return upload_id

    def _geometry(self, meta_doc: dict) -> tuple[int, int]:
        """(k, m) for this upload, honoring its stored storage class (the
        single-PUT path applies the same RRS parity, erasure.py)."""
        n = self.eo.drive_count
        m = self.eo.parity
        if (meta_doc.get("storage_class") or "").upper() == "REDUCED_REDUNDANCY" and m > 0:
            m = max(self.eo.rrs_parity, 1)
        return n - m, m

    def _upload_meta(self, bucket: str, object_name: str, upload_id: str) -> dict:
        path = _upload_dir(bucket, object_name, upload_id) + "/upload.json"
        for d in self.eo._online():
            if d is None:
                continue
            try:
                return json.loads(d.read_all(META_BUCKET, path))
            except errors.DiskError:
                continue
        raise errors.InvalidUploadID(bucket, object_name, upload_id)

    # -- parts ---------------------------------------------------------------

    def put_object_part(
        self, bucket: str, object_name: str, upload_id: str, part_number: int, data
    ) -> ObjectPartInfo:
        with tracing.span(
            "object.PutObjectPart", "object",
            bucket=bucket, object=object_name, part=part_number,
        ):
            return self._put_object_part(
                bucket, object_name, upload_id, part_number, data
            )

    def _put_object_part(
        self, bucket: str, object_name: str, upload_id: str, part_number: int, data
    ) -> ObjectPartInfo:
        """Streaming part upload: `data` is bytes or a .read(n) stream.

        Blocks are grouped for the device codec and shard frames appended to
        per-drive staged part files as they are produced (bounded memory;
        erasure-multipart.go PutObjectPart streams through erasure.Encode the
        same way). The part stages under a tmp name and is published with a
        rename, so a re-upload of the same part number never leaves a
        half-written file behind."""
        from .erasure import (
            ShardStageWriter,
            _PipelinedMD5,
            _etag_update,
            _uniform_runs,
            data_windows,
            make_etag_md5,
        )

        if not (1 <= part_number <= MAX_PARTS):
            raise errors.InvalidArgument(bucket, object_name, "bad part number")
        meta_doc = self._upload_meta(bucket, object_name, upload_id)

        n = self.eo.drive_count
        k, m = self._geometry(meta_doc)
        distribution = hash_order(f"{bucket}/{object_name}", n)
        windows = data_windows(data)
        udir = _upload_dir(bucket, object_name, upload_id)
        # pid-scoped stage name: the recovery scan GCs `.tmp.<pid>.` stage
        # files only when their owner pid is dead (see storage/recovery.py).
        stage = f"part.{part_number}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        disks = self.eo._online()
        writer = ShardStageWriter(
            self.eo.codec, disks, distribution, k, m, lambda i: f"{udir}/{stage}"
        )
        ok = writer.ok
        write_quorum = k + 1 if k == m else k
        size = 0

        def cleanup() -> None:
            def rm(i):
                if disks[i] is None:
                    return
                try:
                    disks[i].delete(META_BUCKET, f"{udir}/{stage}")
                except errors.StorageError:
                    pass

            meta_mod.parallel_map(rm, list(range(n)))

        md5h = make_etag_md5()  # pipelined on multi-core (part etag stays md5)
        try:
            try:
                for win in windows:
                    # Deadline expiry aborts into cleanup() below -- stage
                    # files are deleted, nothing leaks into the upload dir.
                    try:
                        deadline.check("upload part")
                    except errors.DeadlineExceeded:
                        GLOBAL_DEGRADE.record_deadline_abort("multipart-put")
                        raise
                    blocks = win.blocks()
                    size += len(win)
                    for b in blocks:
                        _etag_update(md5h, b)
                    for run in _uniform_runs(blocks):
                        writer.append_group(run)
                    win.release()
                    if writer.alive() < write_quorum:
                        raise errors.ErasureWriteQuorum(
                            bucket, object_name, "upload part quorum lost mid-stream"
                        )
                writer.drain()
                writer.finalize()  # zero-byte parts still commit a shard file
                if writer.alive() < write_quorum:
                    raise errors.ErasureWriteQuorum(bucket, object_name, "upload part quorum")
            except BaseException:
                writer.abort()  # writes settle before cleanup deletes stage files
                if isinstance(md5h, _PipelinedMD5):
                    md5h.shutdown()
                cleanup()
                raise
        finally:
            closer = getattr(windows, "close", None)
            if closer is not None:
                closer()

        etag = md5h.hexdigest()
        mod_time = now()
        part_doc = json.dumps(
            {"number": part_number, "size": size, "etag": etag, "mod_time": mod_time}
        ).encode()

        # Shards staged on every drive under the tmp name; nothing published.
        crash.crash_point("multipart.part.staged")

        def publish(i):
            if not ok[i]:
                raise errors.DiskNotFound()
            disks[i].rename_file(
                META_BUCKET, f"{udir}/{stage}", META_BUCKET, f"{udir}/part.{part_number}"
            )
            # Part renamed into place but its .meta (which list_parts /
            # complete use to see the part) not yet written on this drive.
            crash.crash_point("multipart.part.published", disks[i].endpoint())
            disks[i].write_all(META_BUCKET, f"{udir}/part.{part_number}.meta", part_doc)

        # The rename-publish is the part's commit point (encode and
        # shard-fanout already ride ShardStageWriter.append_group).
        with tracing.span("commit", "object", drives=n):
            results = meta_mod.parallel_map(publish, list(range(n)))
            n_ok = sum(1 for _, e in results if e is None)
            if n_ok < write_quorum:
                cleanup()
                raise errors.ErasureWriteQuorum(bucket, object_name, "upload part quorum")
        return ObjectPartInfo(part_number, size, size, mod_time, etag)

    def list_parts(
        self, bucket: str, object_name: str, upload_id: str, part_marker: int = 0, max_parts: int = 1000
    ) -> list[ObjectPartInfo]:
        self._upload_meta(bucket, object_name, upload_id)
        udir = _upload_dir(bucket, object_name, upload_id)
        out: dict[int, ObjectPartInfo] = {}
        for d in self.eo._online():
            if d is None:
                continue
            try:
                names = d.list_dir(META_BUCKET, udir)
            except errors.DiskError:
                continue
            for nme in names:
                if nme.endswith(".meta"):
                    try:
                        doc = json.loads(d.read_all(META_BUCKET, f"{udir}/{nme}"))
                        num = doc["number"]
                        if num not in out:
                            out[num] = ObjectPartInfo(
                                num, doc["size"], doc["size"], doc.get("mod_time", 0.0), doc["etag"]
                            )
                    except (errors.DiskError, ValueError, KeyError):
                        continue
            break  # one good drive is enough for listing
        parts = [out[nk] for nk in sorted(out) if nk > part_marker]
        return parts[:max_parts]

    # -- complete / abort ----------------------------------------------------

    def complete_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str, parts: list[tuple[int, str]]
    ) -> ObjectInfo:
        with tracing.span(
            "object.CompleteMultipartUpload", "object",
            bucket=bucket, object=object_name, parts=len(parts),
        ):
            return self._complete_multipart_upload(
                bucket, object_name, upload_id, parts
            )

    def _complete_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str, parts: list[tuple[int, str]]
    ) -> ObjectInfo:
        meta_doc = self._upload_meta(bucket, object_name, upload_id)
        if not parts:
            raise errors.InvalidArgument(bucket, object_name, "no parts")
        uploaded = {p.number: p for p in self.list_parts(bucket, object_name, upload_id, 0, MAX_PARTS)}
        part_infos: list[ObjectPartInfo] = []
        prev = 0
        for idx, (num, etag) in enumerate(parts):
            if num <= prev:
                raise errors.InvalidArgument(bucket, object_name, "part order")
            prev = num
            got = uploaded.get(num)
            if got is None or got.etag != etag.strip('"'):
                raise errors.InvalidPart(bucket, object_name, f"part {num}")
            if idx < len(parts) - 1 and got.size < MIN_PART_SIZE:
                raise errors.InvalidArgument(
                    bucket, object_name, f"part {num} below minimum size"
                )
            part_infos.append(got)

        n = self.eo.drive_count
        k, m = self._geometry(meta_doc)
        distribution = hash_order(f"{bucket}/{object_name}", n)
        total_size = sum(p.size for p in part_infos)
        # S3 multipart etag: md5 of the concatenated binary part md5s + "-N".
        md5s = b"".join(bytes.fromhex(p.etag) for p in part_infos)
        etag = hashlib.md5(md5s).hexdigest() + f"-{len(part_infos)}"
        version_id = str(uuid.uuid4()) if meta_doc.get("versioned") else ""
        data_dir = str(uuid.uuid4())
        mod_time = now()
        udir = _upload_dir(bucket, object_name, upload_id)
        # pid-scoped commit staging, same GC contract as the PUT path.
        commit_id = f"{os.getpid()}.{uuid.uuid4()}"

        base_meta = {
            "etag": etag,
            "content-type": meta_doc.get("content_type", "application/octet-stream"),
            **meta_doc.get("user_defined", {}),
            **(
                {"x-internal-storage-class": "REDUCED_REDUNDANCY"}
                if (meta_doc.get("storage_class") or "").upper() == "REDUCED_REDUNDANCY"
                and self.eo.parity > 0
                else {}
            ),
        }

        def commit(args):
            i, disk = args
            if disk is None:
                raise errors.DiskNotFound()
            # Fires with j drives already fully committed (skip=j): the
            # partial-quorum completion the restart scan must resolve.
            crash.crash_point("multipart.complete.partial", disk.endpoint())
            row = distribution[i] - 1
            tmp = f"tmp/{commit_id}/{i}"
            # Renumber parts consecutively (S3 semantics: completed part list
            # order defines part numbers 1..N for reads).
            for new_num, p in enumerate(part_infos, start=1):
                if new_num > 1:
                    # Some parts moved out of the upload dir into the commit
                    # staging dir, the rest still in place, no xl.meta yet.
                    crash.crash_point("multipart.complete.mid-rename", disk.endpoint())
                disk.rename_file(
                    META_BUCKET, f"{udir}/part.{p.number}", META_BUCKET, f"{tmp}/part.{new_num}"
                )
            fi = FileInfo(
                volume=bucket,
                name=object_name,
                version_id=version_id,
                data_dir=data_dir,
                mod_time=mod_time,
                size=total_size,
                metadata=dict(base_meta),
                parts=[
                    ObjectPartInfo(new_num, p.size, p.size, mod_time, p.etag)
                    for new_num, p in enumerate(part_infos, start=1)
                ],
                erasure=ErasureInfo(
                    data_blocks=k,
                    parity_blocks=m,
                    block_size=BLOCK_SIZE,
                    index=row + 1,
                    distribution=list(distribution),
                ),
            )
            disk.rename_data(META_BUCKET, tmp, fi, bucket, object_name)

        with tracing.span("commit", "object", drives=n, parts=len(part_infos)):
            results = meta_mod.parallel_map(commit, list(enumerate(self.eo._online())))
            n_ok = sum(1 for _, e in results if e is None)
            write_quorum = k + 1 if k == m else k
            if n_ok < write_quorum:
                raise errors.ErasureWriteQuorum(bucket, object_name, "complete quorum")
        self.abort_multipart_upload(bucket, object_name, upload_id, missing_ok=True)
        oi = ObjectInfo(
            bucket=bucket,
            name=object_name,
            mod_time=mod_time,
            size=total_size,
            etag=etag,
            version_id=version_id,
            content_type=base_meta["content-type"],
            storage_class=(
                "REDUCED_REDUNDANCY"
                if base_meta.get("x-internal-storage-class") == "REDUCED_REDUNDANCY"
                else "STANDARD"
            ),
        )
        return oi

    def abort_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str, missing_ok: bool = False
    ) -> None:
        if not missing_ok:
            self._upload_meta(bucket, object_name, upload_id)
        udir = _upload_dir(bucket, object_name, upload_id)

        def rm(d):
            if d is None:
                return
            try:
                d.delete(META_BUCKET, udir, recursive=True)
            except errors.DiskError:
                pass

        meta_mod.parallel_map(rm, self.eo._online())

    def list_multipart_uploads(self, bucket: str, prefix: str = "") -> list[dict]:
        self.eo.get_bucket_info(bucket)
        out = []
        seen = set()
        for d in self.eo._online():
            if d is None:
                continue
            base = f"multipart/{bucket}"
            try:
                objects = self._walk_uploads(d, base)
            except errors.DiskError:
                continue
            for object_name, upload_id, doc in objects:
                if (object_name, upload_id) in seen or not object_name.startswith(prefix):
                    continue
                seen.add((object_name, upload_id))
                out.append(
                    {
                        "object": object_name,
                        "upload_id": upload_id,
                        "initiated": doc.get("created", 0.0),
                    }
                )
            break
        return sorted(out, key=lambda u: (u["object"], u["initiated"]))

    def _walk_uploads(self, disk, base: str):
        """Find (object, upload_id, meta) under multipart/<bucket>/."""
        results = []

        def recurse(path: str):
            try:
                names = disk.list_dir(META_BUCKET, path)
            except errors.DiskError:
                return
            for nme in names:
                if not nme.endswith("/"):
                    continue
                child = f"{path}/{nme[:-1]}"
                try:
                    disk.read_all(META_BUCKET, f"{child}/upload.json")
                    doc = json.loads(disk.read_all(META_BUCKET, f"{child}/upload.json"))
                    object_name = path[len(base) + 1 :]
                    results.append((object_name, nme[:-1], doc))
                except errors.DiskError:
                    recurse(child)

        recurse(base)
        return results
