"""Multipart uploads on the erasure set.

Role of the reference's erasure-multipart.go: parts are erasure-coded and
staged under the system meta bucket
(.minio_tpu.sys/multipart/<bucket>/<object>/<uploadId>/), then atomically
assembled into the object on CompleteMultipartUpload by renaming the staged
shard files into the object's data dir and publishing a multi-part FileInfo
(parts carry per-part sizes so reads/heals can reframe each part's bitrot
stream).

Uses the same distribution as the final object (hash_order of bucket/object),
so each drive keeps the same shard row across parts and completion is pure
renames -- no re-coding.
"""

from __future__ import annotations

import hashlib
import json
import uuid

from ..storage.types import ErasureInfo, FileInfo, ObjectPartInfo, now
from ..utils import errors
from ..utils.hashes import hash_order
from . import metadata as meta_mod
from .erasure import BLOCK_SIZE, META_BUCKET, ErasureObjects, _frame_shard
from .types import ObjectInfo, PutObjectOptions

MIN_PART_SIZE = 5 * (1 << 20)  # S3 minimum (except last part)
MAX_PARTS = 10_000


def _upload_dir(bucket: str, object_name: str, upload_id: str) -> str:
    return f"multipart/{bucket}/{object_name}/{upload_id}"


class MultipartManager:
    def __init__(self, eo: ErasureObjects):
        self.eo = eo

    # -- initiate ------------------------------------------------------------

    def new_multipart_upload(
        self, bucket: str, object_name: str, opts: PutObjectOptions | None = None
    ) -> str:
        opts = opts or PutObjectOptions()
        self.eo.get_bucket_info(bucket)
        upload_id = str(uuid.uuid4())
        doc = json.dumps(
            {
                "bucket": bucket,
                "object": object_name,
                "created": now(),
                "content_type": opts.content_type,
                "user_defined": opts.user_defined,
                "versioned": opts.versioned,
            }
        ).encode()
        path = _upload_dir(bucket, object_name, upload_id) + "/upload.json"

        def write(d):
            if d is None:
                raise errors.DiskNotFound()
            d.write_all(META_BUCKET, path, doc)

        results = meta_mod.parallel_map(write, self.eo._online())
        n_ok = sum(1 for _, e in results if e is None)
        if n_ok < self.eo.drive_count // 2 + 1:
            raise errors.ErasureWriteQuorum(bucket, object_name, "initiate multipart")
        return upload_id

    def _upload_meta(self, bucket: str, object_name: str, upload_id: str) -> dict:
        path = _upload_dir(bucket, object_name, upload_id) + "/upload.json"
        for d in self.eo._online():
            if d is None:
                continue
            try:
                return json.loads(d.read_all(META_BUCKET, path))
            except errors.DiskError:
                continue
        raise errors.InvalidUploadID(bucket, object_name, upload_id)

    # -- parts ---------------------------------------------------------------

    def put_object_part(
        self, bucket: str, object_name: str, upload_id: str, part_number: int, data: bytes
    ) -> ObjectPartInfo:
        if not (1 <= part_number <= MAX_PARTS):
            raise errors.InvalidArgument(bucket, object_name, "bad part number")
        self._upload_meta(bucket, object_name, upload_id)

        n = self.eo.drive_count
        m = self.eo.parity
        k = n - m
        distribution = hash_order(f"{bucket}/{object_name}", n)
        etag = hashlib.md5(data).hexdigest()

        blocks = [data[i : i + BLOCK_SIZE] for i in range(0, len(data), BLOCK_SIZE)]
        encoded = self.eo.codec.encode(blocks, k, m) if blocks else []
        shard_files = [
            _frame_shard([e[0][row] for e in encoded], [e[1][row] for e in encoded])
            for row in range(n)
        ]
        part_doc = json.dumps(
            {"number": part_number, "size": len(data), "etag": etag, "mod_time": now()}
        ).encode()
        udir = _upload_dir(bucket, object_name, upload_id)

        def write(args):
            i, disk = args
            if disk is None:
                raise errors.DiskNotFound()
            row = distribution[i] - 1
            disk.create_file(META_BUCKET, f"{udir}/part.{part_number}", shard_files[row])
            disk.write_all(META_BUCKET, f"{udir}/part.{part_number}.meta", part_doc)

        results = meta_mod.parallel_map(write, list(enumerate(self.eo._online())))
        n_ok = sum(1 for _, e in results if e is None)
        write_quorum = k + 1 if k == m else k
        if n_ok < write_quorum:
            raise errors.ErasureWriteQuorum(bucket, object_name, "upload part quorum")
        return ObjectPartInfo(part_number, len(data), len(data), now(), etag)

    def list_parts(
        self, bucket: str, object_name: str, upload_id: str, part_marker: int = 0, max_parts: int = 1000
    ) -> list[ObjectPartInfo]:
        self._upload_meta(bucket, object_name, upload_id)
        udir = _upload_dir(bucket, object_name, upload_id)
        out: dict[int, ObjectPartInfo] = {}
        for d in self.eo._online():
            if d is None:
                continue
            try:
                names = d.list_dir(META_BUCKET, udir)
            except errors.DiskError:
                continue
            for nme in names:
                if nme.endswith(".meta"):
                    try:
                        doc = json.loads(d.read_all(META_BUCKET, f"{udir}/{nme}"))
                        num = doc["number"]
                        if num not in out:
                            out[num] = ObjectPartInfo(
                                num, doc["size"], doc["size"], doc.get("mod_time", 0.0), doc["etag"]
                            )
                    except (errors.DiskError, ValueError, KeyError):
                        continue
            break  # one good drive is enough for listing
        parts = [out[nk] for nk in sorted(out) if nk > part_marker]
        return parts[:max_parts]

    # -- complete / abort ----------------------------------------------------

    def complete_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str, parts: list[tuple[int, str]]
    ) -> ObjectInfo:
        meta_doc = self._upload_meta(bucket, object_name, upload_id)
        if not parts:
            raise errors.InvalidArgument(bucket, object_name, "no parts")
        uploaded = {p.number: p for p in self.list_parts(bucket, object_name, upload_id, 0, MAX_PARTS)}
        part_infos: list[ObjectPartInfo] = []
        prev = 0
        for idx, (num, etag) in enumerate(parts):
            if num <= prev:
                raise errors.InvalidArgument(bucket, object_name, "part order")
            prev = num
            got = uploaded.get(num)
            if got is None or got.etag != etag.strip('"'):
                raise errors.InvalidPart(bucket, object_name, f"part {num}")
            if idx < len(parts) - 1 and got.size < MIN_PART_SIZE:
                raise errors.InvalidArgument(
                    bucket, object_name, f"part {num} below minimum size"
                )
            part_infos.append(got)

        n = self.eo.drive_count
        m = self.eo.parity
        k = n - m
        distribution = hash_order(f"{bucket}/{object_name}", n)
        total_size = sum(p.size for p in part_infos)
        # S3 multipart etag: md5 of the concatenated binary part md5s + "-N".
        md5s = b"".join(bytes.fromhex(p.etag) for p in part_infos)
        etag = hashlib.md5(md5s).hexdigest() + f"-{len(part_infos)}"
        version_id = str(uuid.uuid4()) if meta_doc.get("versioned") else ""
        data_dir = str(uuid.uuid4())
        mod_time = now()
        udir = _upload_dir(bucket, object_name, upload_id)
        commit_id = str(uuid.uuid4())

        base_meta = {
            "etag": etag,
            "content-type": meta_doc.get("content_type", "application/octet-stream"),
            **meta_doc.get("user_defined", {}),
        }

        def commit(args):
            i, disk = args
            if disk is None:
                raise errors.DiskNotFound()
            row = distribution[i] - 1
            tmp = f"tmp/{commit_id}/{i}"
            # Renumber parts consecutively (S3 semantics: completed part list
            # order defines part numbers 1..N for reads).
            for new_num, p in enumerate(part_infos, start=1):
                disk.rename_file(
                    META_BUCKET, f"{udir}/part.{p.number}", META_BUCKET, f"{tmp}/part.{new_num}"
                )
            fi = FileInfo(
                volume=bucket,
                name=object_name,
                version_id=version_id,
                data_dir=data_dir,
                mod_time=mod_time,
                size=total_size,
                metadata=dict(base_meta),
                parts=[
                    ObjectPartInfo(new_num, p.size, p.size, mod_time, p.etag)
                    for new_num, p in enumerate(part_infos, start=1)
                ],
                erasure=ErasureInfo(
                    data_blocks=k,
                    parity_blocks=m,
                    block_size=BLOCK_SIZE,
                    index=row + 1,
                    distribution=list(distribution),
                ),
            )
            disk.rename_data(META_BUCKET, tmp, fi, bucket, object_name)

        results = meta_mod.parallel_map(commit, list(enumerate(self.eo._online())))
        n_ok = sum(1 for _, e in results if e is None)
        write_quorum = k + 1 if k == m else k
        if n_ok < write_quorum:
            raise errors.ErasureWriteQuorum(bucket, object_name, "complete quorum")
        self.abort_multipart_upload(bucket, object_name, upload_id, missing_ok=True)
        oi = ObjectInfo(
            bucket=bucket,
            name=object_name,
            mod_time=mod_time,
            size=total_size,
            etag=etag,
            version_id=version_id,
            content_type=base_meta["content-type"],
        )
        return oi

    def abort_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str, missing_ok: bool = False
    ) -> None:
        if not missing_ok:
            self._upload_meta(bucket, object_name, upload_id)
        udir = _upload_dir(bucket, object_name, upload_id)

        def rm(d):
            if d is None:
                return
            try:
                d.delete(META_BUCKET, udir, recursive=True)
            except errors.DiskError:
                pass

        meta_mod.parallel_map(rm, self.eo._online())

    def list_multipart_uploads(self, bucket: str, prefix: str = "") -> list[dict]:
        self.eo.get_bucket_info(bucket)
        out = []
        seen = set()
        for d in self.eo._online():
            if d is None:
                continue
            base = f"multipart/{bucket}"
            try:
                objects = self._walk_uploads(d, base)
            except errors.DiskError:
                continue
            for object_name, upload_id, doc in objects:
                if (object_name, upload_id) in seen or not object_name.startswith(prefix):
                    continue
                seen.add((object_name, upload_id))
                out.append(
                    {
                        "object": object_name,
                        "upload_id": upload_id,
                        "initiated": doc.get("created", 0.0),
                    }
                )
            break
        return sorted(out, key=lambda u: (u["object"], u["initiated"]))

    def _walk_uploads(self, disk, base: str):
        """Find (object, upload_id, meta) under multipart/<bucket>/."""
        results = []

        def recurse(path: str):
            try:
                names = disk.list_dir(META_BUCKET, path)
            except errors.DiskError:
                return
            for nme in names:
                if not nme.endswith("/"):
                    continue
                child = f"{path}/{nme[:-1]}"
                try:
                    disk.read_all(META_BUCKET, f"{child}/upload.json")
                    doc = json.loads(disk.read_all(META_BUCKET, f"{child}/upload.json"))
                    object_name = path[len(base) + 1 :]
                    results.append((object_name, nme[:-1], doc))
                except errors.DiskError:
                    recurse(child)

        recurse(base)
        return results
