"""Object-layer public datatypes (ObjectInfo & friends).

Role of the reference's ObjectInfo/ListObjectsInfo/etc in
cmd/object-api-datatypes.go: what the API layer consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..storage.types import FileInfo


@dataclass
class ObjectInfo:
    bucket: str = ""
    name: str = ""
    mod_time: float = 0.0
    size: int = 0
    etag: str = ""
    version_id: str = ""
    is_latest: bool = True
    delete_marker: bool = False
    content_type: str = ""
    user_defined: dict[str, str] = field(default_factory=dict)
    parts: list = field(default_factory=list)
    num_versions: int = 0
    actual_size: int | None = None
    storage_class: str = "STANDARD"
    internal: dict[str, str] = field(default_factory=dict)
    inline: bool = False  # data embedded in xl.meta (no part files on disk)

    @classmethod
    def from_file_info(cls, fi: FileInfo, bucket: str, name: str) -> "ObjectInfo":
        meta = dict(fi.metadata)
        etag = meta.pop("etag", "")
        content_type = meta.pop("content-type", "application/octet-stream")
        user = {k: v for k, v in meta.items() if not k.startswith("x-internal-")}
        internal = {k: v for k, v in meta.items() if k.startswith("x-internal-")}
        storage_class = internal.get("x-internal-storage-class", "STANDARD")
        return cls(
            bucket=bucket,
            name=name,
            mod_time=fi.mod_time,
            size=fi.size,
            etag=etag,
            version_id=fi.version_id,
            is_latest=fi.is_latest,
            delete_marker=fi.deleted,
            content_type=content_type,
            user_defined=user,
            parts=list(fi.parts),
            num_versions=fi.num_versions,
            internal=internal,
            storage_class=storage_class,
            inline=not fi.data_dir,
        )


@dataclass
class BucketInfo:
    name: str
    created: float = 0.0
    versioning: bool = False


@dataclass
class ListObjectsInfo:
    is_truncated: bool = False
    next_marker: str = ""
    objects: list[ObjectInfo] = field(default_factory=list)
    prefixes: list[str] = field(default_factory=list)


@dataclass
class ListObjectVersionsInfo:
    is_truncated: bool = False
    next_key_marker: str = ""
    next_version_marker: str = ""
    objects: list[ObjectInfo] = field(default_factory=list)
    prefixes: list[str] = field(default_factory=list)


@dataclass
class PutObjectOptions:
    user_defined: dict[str, str] = field(default_factory=dict)
    versioned: bool = False
    version_id: str = ""
    content_type: str = "application/octet-stream"
    etag: str = ""  # override (transformed payloads keep the plaintext etag)
    # Legacy whole-file bitrot ("sha256" | "blake2b" | "highwayhash256"):
    # shard files hold raw bytes and one checksum per part lives in the
    # metadata (cmd/bitrot-whole.go). Empty = default interleaved streaming.
    bitrot_algorithm: str = ""
    # "" | "STANDARD" | "REDUCED_REDUNDANCY": RRS writes with the reduced
    # parity count (internal/config/storageclass RRS role, default EC:2).
    storage_class: str = ""


@dataclass
class GetObjectOptions:
    version_id: str = ""


@dataclass
class DeleteObjectOptions:
    version_id: str = ""
    versioned: bool = False


@dataclass
class HealResultItem:
    """Outcome of healing one object (madmin.HealResultItem analogue)."""

    bucket: str = ""
    object: str = ""
    version_id: str = ""
    disks_healed: int = 0
    parity_blocks: int = 0
    data_blocks: int = 0
    before_drive_state: list[str] = field(default_factory=list)
    after_drive_state: list[str] = field(default_factory=list)
