"""Coherent node-local in-memory hot-object cache (the read tier's L1).

Role: ROADMAP item 3's million-user read shape -- zipfian GETs of
mostly-small objects -- served from process memory instead of paying quorum
metadata reads plus shard IO per request. Stacked ABOVE the optional disk
CacheObjectLayer (dist/node.py), so the hierarchy is memory -> cache SSD ->
erasure set.

Coherence is two-layered, mirroring the reference's disk cache discipline
(cmd/disk-cache.go) tightened for memory speed:

  * Write-path invalidation: every mutating op through this layer drops the
    local entries and fans the invalidation to every peer (the same
    NotificationSys channel bucket metadata rides) BEFORE the ack returns,
    so a reader hitting any node after a completed PUT never sees the old
    bytes from cache.
  * ETag validation: every hit revalidates against the backend's
    get_object_info (a metadata quorum read -- no shard IO, no decode).
    A mismatch drops the entry and falls through to a miss. Backend down
    serves the (last-validated) entry stale, like the disk cache does.

Hot misses are singleflighted per (bucket, object, version, window): one
leader performs the backend read + fill while followers wait on its event
and then serve from the fresh entry -- a hot-set stampede costs one
backend read, not N.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from ..control import tracing
from ..control.perf import GLOBAL_PERF
from ..control.sanitizer import san_lock
from ..utils import errors
from .types import GetObjectOptions, ObjectInfo

# Streaming hits hand out views over the cached bytes in response-sized
# slices (one aiohttp write per slice; matches the erasure block size).
_HIT_CHUNK = 1 << 20


class MemCacheConfig:
    """Sizing + policy knobs (all env-driven; MTPU_MEMCACHE_MB=0 disables)."""

    def __init__(
        self,
        limit_bytes: int,
        max_entry_bytes: int | None = None,
        validate: bool = True,
    ):
        self.limit_bytes = limit_bytes
        # One entry may not monopolize the tier: default cap is a quarter of
        # the budget, at most 64 MiB.
        if max_entry_bytes is None:
            max_entry_bytes = min(64 << 20, max(limit_bytes // 4, 1))
        self.max_entry_bytes = max_entry_bytes
        self.validate = validate

    @classmethod
    def from_env(cls) -> "MemCacheConfig | None":
        mb = int(os.environ.get("MTPU_MEMCACHE_MB", "0") or "0")
        if mb <= 0:
            return None
        max_mb = os.environ.get("MTPU_MEMCACHE_OBJ_MAX_MB", "")
        return cls(
            limit_bytes=mb << 20,
            max_entry_bytes=(int(max_mb) << 20) if max_mb else None,
            validate=os.environ.get("MTPU_MEMCACHE_VALIDATE", "1") != "0",
        )


class _Entry:
    __slots__ = ("oi", "data", "filled_at")

    def __init__(self, oi: ObjectInfo, data: bytes):
        self.oi = oi
        self.data = data
        self.filled_at = time.monotonic()


class MemObjectCache:
    """Bounded-memory LRU of cache entries, keyed
    (bucket, object, version, window) with a (bucket, object) reverse index
    for O(entries-of-object) invalidation. Pure store: no backend calls, no
    IO under the lock -- peer invalidation handlers touch this directly."""

    def __init__(self, cfg: MemCacheConfig):
        self.cfg = cfg
        self._lock = san_lock("MemObjectCache._lock")
        self._lru: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._by_object: dict[tuple[str, str], set[tuple]] = {}
        self._bytes = 0
        # Counters (the metrics/report surface).
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.invalidations = 0
        self.singleflight_waits = 0

    # -- store ----------------------------------------------------------------

    def get(self, key: tuple) -> _Entry | None:
        with self._lock:
            ent = self._lru.get(key)
            if ent is not None:
                self._lru.move_to_end(key)
            return ent

    def put(self, key: tuple, oi: ObjectInfo, data: bytes) -> bool:
        size = len(data)
        if size > self.cfg.max_entry_bytes or size > self.cfg.limit_bytes:
            return False
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= len(old.data)
            self._lru[key] = _Entry(oi, data)
            self._by_object.setdefault((key[0], key[1]), set()).add(key)
            self._bytes += size
            self.fills += 1
            while self._bytes > self.cfg.limit_bytes and self._lru:
                evicted_key, ev = self._lru.popitem(last=False)
                self._bytes -= len(ev.data)
                self.evictions += 1
                self._unindex_locked(evicted_key)
        return True

    def _unindex_locked(self, key: tuple) -> None:
        keys = self._by_object.get((key[0], key[1]))
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_object[(key[0], key[1])]

    def drop(self, key: tuple) -> None:
        """Remove one stale entry (failed ETag validation)."""
        with self._lock:
            ent = self._lru.pop(key, None)
            if ent is not None:
                self._bytes -= len(ent.data)
                self._unindex_locked(key)

    def invalidate_object(self, bucket: str, object_name: str) -> int:
        """Drop every entry (all versions/windows) of one object."""
        with self._lock:
            keys = self._by_object.pop((bucket, object_name), None)
            if not keys:
                return 0
            n = 0
            for key in keys:
                ent = self._lru.pop(key, None)
                if ent is not None:
                    self._bytes -= len(ent.data)
                    n += 1
            self.invalidations += n
            return n

    def invalidate_bucket(self, bucket: str) -> int:
        with self._lock:
            objs = [bo for bo in self._by_object if bo[0] == bucket]
        n = 0
        for _, obj in objs:
            n += self.invalidate_object(bucket, obj)
        return n

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "limit_bytes": self.cfg.limit_bytes,
                "bytes": self._bytes,
                "entries": len(self._lru),
                "hits": self.hits,
                "misses": self.misses,
                "hit_ratio": round(self.hits / lookups, 4) if lookups else 0.0,
                "fills": self.fills,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "singleflight_waits": self.singleflight_waits,
            }


class MemCacheObjectLayer:
    """Transparent ObjectLayer wrapper serving hot reads from a
    MemObjectCache (the CacheObjectLayer interposition idiom, one tier up).

    `on_invalidate(bucket, object)` -- wired by dist/node.py to the peer
    fanout -- runs after every local mutation and BEFORE the ack, so remote
    memcaches are coherent by the time the client's write returns."""

    def __init__(
        self,
        backend,
        store: MemObjectCache,
        on_invalidate=None,
    ):
        self.backend = backend
        self.store = store
        self.on_invalidate = on_invalidate
        self._fl_lock = san_lock("MemCacheObjectLayer._fl_lock")
        self._flights: dict[tuple, threading.Event] = {}

    # Everything not overridden passes straight through to the backend.
    def __getattr__(self, name):
        return getattr(self.backend, name)

    # -- key/window shape -----------------------------------------------------

    def _window(self, offset: int, length: int) -> tuple | None:
        """Cacheable window for a read: () = whole object; (offset, length)
        = an exact hot range window; None = uncacheable shape."""
        if offset == 0 and length < 0:
            return ()
        if offset >= 0 and 0 < length <= self.store.cfg.max_entry_bytes:
            return (offset, length)
        return None

    # -- the cached read path -------------------------------------------------

    def get_object_info(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
    ):
        """Hot-path metadata: with per-hit validation off, a cached
        whole-object entry's ObjectInfo is authoritative (write-path
        invalidation drops it before any mutation acks), so HEAD and the
        GET handler's pre-stream probe skip the metadata quorum read.
        With validation on, cached metadata is exactly what must be
        re-checked -- always ask the backend."""
        if not self.store.cfg.validate:
            opts = opts or GetObjectOptions()
            version = getattr(opts, "version_id", "") or ""
            ent = self.store.get((bucket, object_name, version, ()))
            if ent is not None:
                return ent.oi
        return self.backend.get_object_info(bucket, object_name, opts)

    def get_object(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ):
        oi, stream = self.get_object_stream(bucket, object_name, opts, offset, length)
        buf = bytearray()
        for c in stream:
            buf += c  # mtpulint: disable=hot-path-copy -- buffered convenience; the stream path serves views
        return oi, bytes(buf)  # mtpulint: disable=hot-path-copy -- buffered convenience; the stream path serves views

    def get_object_stream(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ):
        opts = opts or GetObjectOptions()
        window = self._window(offset, length)
        if window is None:
            return self._backend_stream(bucket, object_name, opts, offset, length)
        version = getattr(opts, "version_id", "") or ""
        key = (bucket, object_name, version, window)

        t0 = time.perf_counter()
        c0 = time.thread_time()
        served = self._serve_hit(key, opts, offset, length)
        if served is not None:
            # Stage mark outside a span: hits are served on whatever thread
            # asked; the ledger bucket is the always-on attribution.
            GLOBAL_PERF.ledger.record(
                "object", "cache-hit", time.perf_counter() - t0,
                time.thread_time() - c0,
            )
            cur = tracing.current()
            if cur is not None:
                cur.set(memcache="hit")
            return served

        self.store.misses += 1
        return self._fill_or_follow(key, bucket, object_name, opts, offset, length)

    def _serve_hit(self, key, opts, offset: int, length: int):
        """Validated cache hit -> (oi, chunks iterator), else None."""
        ent = self.store.get(key)
        whole = None
        if ent is None and key[3] != ():
            # A whole-object entry serves any in-bounds window.
            whole = self.store.get((key[0], key[1], key[2], ()))
            if whole is None:
                return None
            ent = whole
        elif ent is None:
            return None

        if self.store.cfg.validate:
            try:
                info = self.backend.get_object_info(
                    key[0], key[1], GetObjectOptions(version_id=key[2])
                )
            except (errors.ObjectNotFound, errors.VersionNotFound):
                self.store.invalidate_object(key[0], key[1])
                raise
            except errors.StorageError:
                info = None  # backend down: serve stale (disk-cache discipline)
            if info is not None and info.etag != ent.oi.etag:
                self.store.drop(key if whole is None else (key[0], key[1], key[2], ()))
                return None

        self.store.hits += 1
        data = ent.data
        if whole is not None or key[3] == ():
            end = len(data) if length < 0 else min(offset + length, len(data))
            lo, hi = offset, max(end, offset)
        else:
            lo, hi = 0, len(data)
        mv = memoryview(data)

        def chunks():
            for off in range(lo, hi, _HIT_CHUNK):
                yield mv[off : min(off + _HIT_CHUNK, hi)]

        return ent.oi, chunks()

    def _backend_stream(self, bucket, object_name, opts, offset, length):
        fn = getattr(self.backend, "get_object_stream", None)
        if fn is not None:
            return fn(bucket, object_name, opts, offset, length)
        oi, data = self.backend.get_object(bucket, object_name, opts, offset, length)
        return oi, iter((data,))

    def _fill_or_follow(self, key, bucket, object_name, opts, offset, length):
        """Singleflight miss path: one leader reads + fills; followers wait
        on the leader's event and serve the fresh entry."""
        with self._fl_lock:
            evt = self._flights.get(key)
            leader = evt is None
            if leader:
                evt = threading.Event()
                self._flights[key] = evt
        if not leader:
            self.store.singleflight_waits += 1
            evt.wait(timeout=30.0)
            served = self._serve_hit(key, opts, offset, length)
            if served is not None:
                return served
            # Leader failed or the object was uncacheable: read it ourselves.
            return self._backend_stream(bucket, object_name, opts, offset, length)

        try:
            t0 = time.perf_counter()
            c0 = time.thread_time()
            try:
                oi = self.backend.get_object_info(bucket, object_name, opts)
            except errors.StorageError:
                return self._backend_stream(bucket, object_name, opts, offset, length)
            want = length if key[3] != () else oi.size
            if want > self.store.cfg.max_entry_bytes:
                # Too big for the tier: stream through uncached.
                return self._backend_stream(bucket, object_name, opts, offset, length)
            oi, data = self.backend.get_object(bucket, object_name, opts, offset, length)
            self.store.put(key, oi, data)
            GLOBAL_PERF.ledger.record(
                "object", "cache-fill", time.perf_counter() - t0,
                time.thread_time() - c0,
            )
            mv = memoryview(data)

            def chunks():
                for off in range(0, len(mv), _HIT_CHUNK):
                    yield mv[off : off + _HIT_CHUNK]

            return oi, chunks()
        finally:
            with self._fl_lock:
                self._flights.pop(key, None)
            evt.set()

    # -- invalidating writes --------------------------------------------------

    def _invalidate(self, bucket: str, object_name: str) -> None:
        """Local drop + peer fanout, synchronously, before the caller's ack."""
        self.store.invalidate_object(bucket, object_name)
        if self.on_invalidate is not None:
            self.on_invalidate(bucket, object_name)

    def put_object(self, bucket, object_name, data, opts=None):
        out = self.backend.put_object(bucket, object_name, data, opts)
        self._invalidate(bucket, object_name)
        return out

    def delete_object(self, bucket, object_name, opts=None):
        out = self.backend.delete_object(bucket, object_name, opts)
        self._invalidate(bucket, object_name)
        return out

    def put_object_metadata(self, bucket, object_name, version_id="", updates=None, removes=None):
        out = self.backend.put_object_metadata(
            bucket, object_name, version_id, updates, removes
        )
        self._invalidate(bucket, object_name)
        return out

    def complete_multipart_upload(self, bucket, object_name, upload_id, parts):
        out = self.backend.complete_multipart_upload(
            bucket, object_name, upload_id, parts
        )
        self._invalidate(bucket, object_name)
        return out

    def delete_objects(self, bucket, items):
        out = self.backend.delete_objects(bucket, items)
        for item in items:
            name = item[0] if isinstance(item, (tuple, list)) else item
            self._invalidate(bucket, name)
        return out

    def delete_bucket(self, bucket: str, force: bool = False):
        out = self.backend.delete_bucket(bucket, force)
        self.store.invalidate_bucket(bucket)
        if self.on_invalidate is not None:
            self.on_invalidate(bucket, "")
        return out

    def stats(self) -> dict:
        return self.store.stats()
