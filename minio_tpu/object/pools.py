"""Server pools: the top-level ObjectLayer.

Role of the reference's erasureServerPools (cmd/erasure-server-pool.go):
multiple independent pools of erasure sets behind one namespace. New objects
go to the pool with the most free space (:222-288); reads/deletes probe the
pool that actually holds the object (:289-372); buckets and listings span all
pools. This is the object the API layer holds (its `ObjectAPI()`).
"""

from __future__ import annotations

import heapq

from ..storage.interface import StorageAPI
from ..utils import errors
from . import codec as codec_mod
from . import metadata as meta_mod
from .sets import ErasureSets
from .types import (
    BucketInfo,
    DeleteObjectOptions,
    GetObjectOptions,
    HealResultItem,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    ObjectInfo,
    PutObjectOptions,
)


class ServerPools:
    # The API front streams request/response bodies through this layer
    # (put_object accepts a .read(n) stream; get_object_stream exists).
    supports_streaming = True

    def __init__(self, pools: list[ErasureSets]):
        if not pools:
            raise ValueError("need at least one pool")
        self.pools = pools

    # -- convenience constructors ---------------------------------------------

    @classmethod
    def single(
        cls,
        disks: list[StorageAPI],
        set_drive_count: int | None = None,
        parity: int | None = None,
        codec: codec_mod.BlockCodec | None = None,
    ) -> "ServerPools":
        count = set_drive_count or len(disks)
        return cls([ErasureSets(list(disks), count, parity=parity, codec=codec)])

    # -- pool selection --------------------------------------------------------

    def _pool_with_space(self) -> ErasureSets:
        best, best_free = self.pools[0], -1
        for p in self.pools:
            free = 0
            for d in p.disks:
                if d is None:
                    continue
                try:
                    free += d.disk_info().free
                except errors.DiskError:
                    continue
            if free > best_free:
                best, best_free = p, free
        return best

    def _pool_holding(self, bucket: str, object_name: str, version_id: str = "") -> ErasureSets:
        if len(self.pools) == 1:
            return self.pools[0]
        newest: tuple[float, ErasureSets] | None = None
        for p in self.pools:
            try:
                oi = p.get_object_info(bucket, object_name, GetObjectOptions(version_id))
                if newest is None or oi.mod_time > newest[0]:
                    newest = (oi.mod_time, p)
            except errors.ObjectError:
                continue
        if newest is None:
            raise errors.ObjectNotFound(bucket, object_name)
        return newest[1]

    # -- buckets ---------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        _validate_bucket_name(bucket)
        results = meta_mod.parallel_map(lambda p: p.make_bucket(bucket), self.pools)
        for _, e in results:
            if e is not None:
                raise e

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.pools[0].get_bucket_info(bucket)

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self.get_bucket_info(bucket)
            return True
        except errors.BucketNotFound:
            return False

    def invalidate_bucket_cache(self, bucket: str = "") -> None:
        for p in self.pools:
            p.invalidate_bucket_cache(bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        # Refuse unless empty across every pool (unless forced).
        if not force:
            for p in self.pools:
                listing = p.list_objects(bucket, max_keys=1)
                if listing.objects or listing.prefixes:
                    raise errors.BucketNotEmpty(bucket)
        results = meta_mod.parallel_map(lambda p: p.delete_bucket(bucket, True), self.pools)
        for _, e in results:
            if e is not None:
                raise e

    def list_buckets(self) -> list[BucketInfo]:
        return self.pools[0].list_buckets()

    # -- objects ---------------------------------------------------------------

    def put_object(
        self, bucket: str, object_name: str, data: bytes, opts: PutObjectOptions | None = None
    ) -> ObjectInfo:
        _validate_object_name(bucket, object_name)
        # Overwrites must land in the pool that already holds the object.
        try:
            pool = self._pool_holding(bucket, object_name)
        except errors.ObjectError:
            pool = self._pool_with_space()
        return pool.put_object(bucket, object_name, data, opts)

    def get_object(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> tuple[ObjectInfo, bytes]:
        opts = opts or GetObjectOptions()
        last: Exception = errors.ObjectNotFound(bucket, object_name)
        for p in self.pools:
            try:
                return p.get_object(bucket, object_name, opts, offset, length)
            except (errors.ObjectNotFound, errors.VersionNotFound) as e:
                last = e
        raise last

    def get_object_stream(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ):
        """Streaming get: (ObjectInfo, iterator of decoded chunks)."""
        opts = opts or GetObjectOptions()
        last: Exception = errors.ObjectNotFound(bucket, object_name)
        for p in self.pools:
            try:
                return p.get_object_stream(bucket, object_name, opts, offset, length)
            except (errors.ObjectNotFound, errors.VersionNotFound) as e:
                last = e
        raise last

    def get_object_info(
        self, bucket: str, object_name: str, opts: GetObjectOptions | None = None
    ) -> ObjectInfo:
        opts = opts or GetObjectOptions()
        last: Exception = errors.ObjectNotFound(bucket, object_name)
        for p in self.pools:
            try:
                return p.get_object_info(bucket, object_name, opts)
            except (errors.ObjectNotFound, errors.VersionNotFound) as e:
                last = e
        raise last

    def put_object_metadata(
        self, bucket, object_name, version_id: str = "", updates=None, removes=None
    ) -> ObjectInfo:
        pool = self._pool_holding(bucket, object_name, version_id)
        return pool.put_object_metadata(bucket, object_name, version_id, updates, removes)

    def transition_object(
        self,
        bucket,
        object_name,
        version_id: str,
        tier: str,
        remote_name: str,
        expected_etag: str = "",
        expected_mtime: float = 0.0,
    ) -> ObjectInfo:
        pool = self._pool_holding(bucket, object_name, version_id)
        return pool.transition_object(
            bucket, object_name, version_id, tier, remote_name, expected_etag, expected_mtime
        )

    def delete_object(
        self, bucket: str, object_name: str, opts: DeleteObjectOptions | None = None
    ) -> ObjectInfo:
        opts = opts or DeleteObjectOptions()
        if opts.versioned and not opts.version_id:
            # Delete marker goes where the object lives (or first pool).
            try:
                pool = self._pool_holding(bucket, object_name)
            except errors.ObjectError:
                pool = self.pools[0]
            return pool.delete_object(bucket, object_name, opts)
        last: Exception | None = None
        for p in self.pools:
            try:
                return p.delete_object(bucket, object_name, opts)
            except (errors.ObjectNotFound, errors.VersionNotFound) as e:
                last = e
        if last and len(self.pools) > 1:
            raise last
        if last:
            raise last
        return ObjectInfo(bucket=bucket, name=object_name)

    def delete_objects(
        self, bucket: str, objects: list[tuple[str, str]], versioned: bool = False
    ) -> list[tuple[ObjectInfo | None, Exception | None]]:
        """Bulk delete: [(name, version_id)] -> per-entry result."""

        def rm(item):
            name, vid = item
            return self.delete_object(
                bucket, name, DeleteObjectOptions(version_id=vid, versioned=versioned)
            )

        return meta_mod.parallel_map(rm, objects)

    # -- listing ---------------------------------------------------------------

    def list_objects(
        self,
        bucket: str,
        prefix: str = "",
        marker: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
    ) -> ListObjectsInfo:
        if len(self.pools) == 1:
            return self.pools[0].list_objects(bucket, prefix, marker, delimiter, max_keys)
        # Merge per-pool listings (each sorted).
        merged = ListObjectsInfo()
        streams = [
            p.list_objects(bucket, prefix, marker, delimiter, max_keys) for p in self.pools
        ]
        names: dict[str, ObjectInfo] = {}
        for s in streams:
            for o in s.objects:
                if o.name not in names or o.mod_time > names[o.name].mod_time:
                    names[o.name] = o
        prefixes = sorted({cp for s in streams for cp in s.prefixes})
        ordered = sorted(names)
        for name in ordered[:max_keys]:
            merged.objects.append(names[name])
        if len(ordered) > max_keys or any(s.is_truncated for s in streams):
            merged.is_truncated = True
            if merged.objects:
                merged.next_marker = merged.objects[-1].name
        merged.prefixes = prefixes
        return merged

    def list_object_versions(
        self,
        bucket: str,
        prefix: str = "",
        key_marker: str = "",
        version_marker: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
    ) -> ListObjectVersionsInfo:
        if len(self.pools) == 1:
            return self.pools[0].list_object_versions(
                bucket, prefix, key_marker, version_marker, delimiter, max_keys
            )
        out = ListObjectVersionsInfo()
        for p in self.pools:
            part = p.list_object_versions(
                bucket, prefix, key_marker, version_marker, delimiter, max_keys
            )
            out.objects.extend(part.objects)
            out.prefixes = sorted(set(out.prefixes) | set(part.prefixes))
        out.objects.sort(key=lambda o: (o.name, -o.mod_time))
        if len(out.objects) > max_keys:
            out.objects = out.objects[:max_keys]
            out.is_truncated = True
            out.next_key_marker = out.objects[-1].name
            out.next_version_marker = out.objects[-1].version_id
        return out

    # -- multipart --------------------------------------------------------------

    def new_multipart_upload(self, bucket, object_name, opts: PutObjectOptions | None = None) -> str:
        _validate_object_name(bucket, object_name)
        try:
            pool = self._pool_holding(bucket, object_name)
        except errors.ObjectError:
            pool = self._pool_with_space()
        return pool.new_multipart_upload(bucket, object_name, opts)

    def _pool_with_upload(self, bucket: str, object_name: str, upload_id: str):
        last: Exception | None = None
        for p in self.pools:
            try:
                p.list_parts(bucket, object_name, upload_id, 0, 1)
                return p
            except errors.ObjectError as e:
                last = e
        raise last or errors.InvalidUploadID(bucket, object_name, upload_id)

    def put_object_part(self, bucket, object_name, upload_id, part_number, data):
        return self._pool_with_upload(bucket, object_name, upload_id).put_object_part(
            bucket, object_name, upload_id, part_number, data
        )

    def list_parts(self, bucket, object_name, upload_id, part_marker=0, max_parts=1000):
        return self._pool_with_upload(bucket, object_name, upload_id).list_parts(
            bucket, object_name, upload_id, part_marker, max_parts
        )

    def complete_multipart_upload(self, bucket, object_name, upload_id, parts):
        return self._pool_with_upload(bucket, object_name, upload_id).complete_multipart_upload(
            bucket, object_name, upload_id, parts
        )

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self._pool_with_upload(bucket, object_name, upload_id).abort_multipart_upload(
            bucket, object_name, upload_id
        )

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for p in self.pools:
            out.extend(p.list_multipart_uploads(bucket, prefix))
        return sorted(out, key=lambda u: (u["object"], u["initiated"]))

    # -- healing ---------------------------------------------------------------

    def heal_object(
        self, bucket: str, object_name: str, version_id: str = "", dry_run: bool = False
    ) -> HealResultItem:
        last: Exception | None = None
        for p in self.pools:
            try:
                return p.heal_object(bucket, object_name, version_id, dry_run)
            except (errors.ObjectError, errors.DiskError) as e:
                last = e
        raise last or errors.ObjectNotFound(bucket, object_name)

    def heal_bucket(self, bucket: str) -> None:
        """Recreate the bucket volume on drives that miss it."""
        for p in self.pools:
            for s in p.sets:
                for d in s.disks:
                    if d is None:
                        continue
                    try:
                        d.stat_vol(bucket)
                    except errors.VolumeNotFound:
                        try:
                            d.make_vol(bucket)
                        except errors.DiskError:
                            pass
                    except errors.DiskError:
                        pass


def _validate_bucket_name(bucket: str) -> None:
    """S3 bucket naming rules (subset the reference enforces)."""
    if not (3 <= len(bucket) <= 63):
        raise errors.BucketNameInvalid(bucket)
    if bucket.startswith(".") or bucket.endswith(".") or bucket.startswith("-"):
        raise errors.BucketNameInvalid(bucket)
    ok = set("abcdefghijklmnopqrstuvwxyz0123456789.-")
    if not all(c in ok for c in bucket):
        raise errors.BucketNameInvalid(bucket)


def _validate_object_name(bucket: str, object_name: str) -> None:
    if not object_name or len(object_name) > 1024:
        raise errors.ObjectNameInvalid(bucket, object_name)
    if object_name.startswith("/") or "\\" in object_name:
        raise errors.ObjectNameInvalid(bucket, object_name)
    if any(part in (".", "..") for part in object_name.split("/")):
        raise errors.ObjectNameInvalid(bucket, object_name)
