"""Server pools: the top-level ObjectLayer.

Role of the reference's erasureServerPools (cmd/erasure-server-pool.go):
multiple independent pools of erasure sets behind one namespace. New objects
go to the pool with the most free space (:222-288); reads/deletes probe the
pool that actually holds the object (:289-372); buckets and listings span all
pools. This is the object the API layer holds (its `ObjectAPI()`).

Pools carry a lifecycle status (the decommission states of
cmd/erasure-server-pool-decom.go): ACTIVE pools take new writes; SUSPENDED
pools exist cluster-wide but do not place yet (the first phase of a two-phase
attach, object/poolmgr.py); DRAINING pools serve reads while their objects
migrate out but never receive placements; DECOMMISSIONED pools are empty and
skipped entirely. Placement (`_pool_with_space`) considers only ACTIVE pools;
existence probes (`_pool_holding`, listings, multipart, heal) consider all
non-decommissioned pools.
"""

from __future__ import annotations

import heapq

from ..storage.interface import StorageAPI
from ..utils import errors
from . import codec as codec_mod
from . import metadata as meta_mod
from .sets import ErasureSets
from .types import (
    BucketInfo,
    DeleteObjectOptions,
    GetObjectOptions,
    HealResultItem,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    ObjectInfo,
    PutObjectOptions,
)

# Pool lifecycle statuses (poolMeta decommission states, reference
# cmd/erasure-server-pool-decom.go; transitions owned by object/poolmgr.py).
POOL_ACTIVE = "active"
POOL_SUSPENDED = "suspended"
POOL_DRAINING = "draining"
POOL_DECOMMISSIONED = "decommissioned"


class ServerPools:
    # The API front streams request/response bodies through this layer
    # (put_object accepts a .read(n) stream; get_object_stream exists).
    supports_streaming = True

    def __init__(self, pools: list[ErasureSets]):
        if not pools:
            raise ValueError("need at least one pool")
        self.pools = pools
        self.statuses: list[str] = [POOL_ACTIVE] * len(pools)

    # -- convenience constructors ---------------------------------------------

    @classmethod
    def single(
        cls,
        disks: list[StorageAPI],
        set_drive_count: int | None = None,
        parity: int | None = None,
        codec: codec_mod.BlockCodec | None = None,
    ) -> "ServerPools":
        count = set_drive_count or len(disks)
        return cls([ErasureSets(list(disks), count, parity=parity, codec=codec)])

    # -- pool lifecycle --------------------------------------------------------

    def add_pool(self, sets: ErasureSets, status: str = POOL_SUSPENDED) -> int:
        """Append a pool at runtime (attach-pool expansion). Returns its
        index. Added SUSPENDED by default: object/poolmgr.py flips it
        ACTIVE only after the pool-config epoch has fanned out."""
        self.pools.append(sets)
        self.statuses.append(status)
        return len(self.pools) - 1

    def set_pool_status(self, pool_index: int, status: str) -> None:
        self.statuses[pool_index] = status

    def _probe_pools(self) -> list[tuple[int, ErasureSets]]:
        """Pools that may hold data: everything not decommissioned."""
        return [
            (i, p) for i, p in enumerate(self.pools)
            if self.statuses[i] != POOL_DECOMMISSIONED
        ]

    # -- pool selection --------------------------------------------------------

    def _pool_with_space(self) -> ErasureSets:
        """Placement target: the ACTIVE pool with the most free bytes,
        ties broken by lowest pool index -- deterministic, so every node
        running the same pool config places identically. Suspended /
        draining / decommissioned pools never receive new writes."""
        best: ErasureSets | None = None
        best_key: tuple[int, int] | None = None
        for i, p in enumerate(self.pools):
            if self.statuses[i] != POOL_ACTIVE:
                continue
            free = 0
            for d in p.disks:
                if d is None:
                    continue
                try:
                    free += d.disk_info().free
                except errors.DiskError:
                    continue
            key = (-free, i)
            if best_key is None or key < best_key:
                best, best_key = p, key
        if best is None:
            raise errors.DiskFull("no active pool available for writes")
        return best

    def _pool_holding_index(
        self, bucket: str, object_name: str, version_id: str = ""
    ) -> int:
        """Index of the pool holding the newest copy. Probes run in
        parallel across candidate pools; during a migration window (the
        object momentarily present in two pools) the newest mod_time wins,
        lowest pool index on an exact tie."""
        if len(self.pools) == 1:
            return 0
        cands = [
            i for i, st in enumerate(self.statuses)
            if st != POOL_DECOMMISSIONED
        ]
        if len(cands) == 1:
            # Negative-lookup fast path: decommissioned pools are empty by
            # invariant, so a single live pool needs no existence probe.
            return cands[0]

        def probe(i: int) -> ObjectInfo:
            return self.pools[i].get_object_info(
                bucket, object_name, GetObjectOptions(version_id)
            )

        best: int | None = None
        best_key: tuple[float, int] | None = None
        last: Exception | None = None
        for i, (oi, err) in zip(cands, meta_mod.parallel_map(probe, cands)):
            if err is not None:
                if isinstance(err, errors.ObjectError):
                    last = err
                    continue
                raise err
            key = (oi.mod_time, -i)
            if best_key is None or key > best_key:
                best, best_key = i, key
        if best is None:
            raise last or errors.ObjectNotFound(bucket, object_name)
        return best

    def _pool_holding(self, bucket: str, object_name: str, version_id: str = "") -> ErasureSets:
        return self.pools[self._pool_holding_index(bucket, object_name, version_id)]

    # -- buckets ---------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        _validate_bucket_name(bucket)
        results = meta_mod.parallel_map(lambda p: p.make_bucket(bucket), self.pools)
        for _, e in results:
            if e is not None:
                raise e

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.pools[0].get_bucket_info(bucket)

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self.get_bucket_info(bucket)
            return True
        except errors.BucketNotFound:
            return False

    def invalidate_bucket_cache(self, bucket: str = "") -> None:
        for p in self.pools:
            p.invalidate_bucket_cache(bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        # Refuse unless empty across every pool (unless forced).
        if not force:
            for p in self.pools:
                listing = p.list_objects(bucket, max_keys=1)
                if listing.objects or listing.prefixes:
                    raise errors.BucketNotEmpty(bucket)
        results = meta_mod.parallel_map(lambda p: p.delete_bucket(bucket, True), self.pools)
        for _, e in results:
            if e is not None:
                raise e

    def list_buckets(self) -> list[BucketInfo]:
        return self.pools[0].list_buckets()

    # -- objects ---------------------------------------------------------------

    def put_object(
        self, bucket: str, object_name: str, data: bytes, opts: PutObjectOptions | None = None
    ) -> ObjectInfo:
        _validate_object_name(bucket, object_name)
        # Overwrites must land in the pool that already holds the object --
        # unless that pool stopped taking writes (draining/suspended), in
        # which case the overwrite places fresh and the drain removes the
        # old copy.
        pool = None
        try:
            idx = self._pool_holding_index(bucket, object_name)
            if self.statuses[idx] == POOL_ACTIVE:
                pool = self.pools[idx]
        except errors.ObjectError:
            pass
        if pool is None:
            pool = self._pool_with_space()
        return pool.put_object(bucket, object_name, data, opts)

    def get_object(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> tuple[ObjectInfo, bytes]:
        opts = opts or GetObjectOptions()
        # Resolve to the pool with the NEWEST copy (not first-found): during
        # a drain/rebalance move window the object briefly exists in two
        # pools, and first-found could serve the stale source copy.
        i = self._pool_holding_index(bucket, object_name, opts.version_id)
        return self.pools[i].get_object(bucket, object_name, opts, offset, length)

    def get_object_stream(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ):
        """Streaming get: (ObjectInfo, iterator of decoded chunks)."""
        opts = opts or GetObjectOptions()
        i = self._pool_holding_index(bucket, object_name, opts.version_id)
        return self.pools[i].get_object_stream(bucket, object_name, opts, offset, length)

    def get_object_info(
        self, bucket: str, object_name: str, opts: GetObjectOptions | None = None
    ) -> ObjectInfo:
        opts = opts or GetObjectOptions()
        i = self._pool_holding_index(bucket, object_name, opts.version_id)
        return self.pools[i].get_object_info(bucket, object_name, opts)

    def put_object_metadata(
        self, bucket, object_name, version_id: str = "", updates=None, removes=None
    ) -> ObjectInfo:
        pool = self._pool_holding(bucket, object_name, version_id)
        return pool.put_object_metadata(bucket, object_name, version_id, updates, removes)

    def transition_object(
        self,
        bucket,
        object_name,
        version_id: str,
        tier: str,
        remote_name: str,
        expected_etag: str = "",
        expected_mtime: float = 0.0,
    ) -> ObjectInfo:
        pool = self._pool_holding(bucket, object_name, version_id)
        return pool.transition_object(
            bucket, object_name, version_id, tier, remote_name, expected_etag, expected_mtime
        )

    def delete_object(
        self, bucket: str, object_name: str, opts: DeleteObjectOptions | None = None
    ) -> ObjectInfo:
        opts = opts or DeleteObjectOptions()
        if opts.versioned and not opts.version_id:
            # Delete marker goes where the object lives (or a write pool).
            try:
                pool = self._pool_holding(bucket, object_name)
            except errors.ObjectError:
                pool = self._pool_with_space()
            return pool.delete_object(bucket, object_name, opts)
        # Physical delete sweeps EVERY live pool: during a migration window
        # the object exists in two pools, and removing only the first-found
        # copy would let the other pool resurrect it.
        last: Exception | None = None
        result: ObjectInfo | None = None
        for _i, p in self._probe_pools():
            try:
                result = p.delete_object(bucket, object_name, opts)
            except (errors.ObjectNotFound, errors.VersionNotFound) as e:
                last = e
        if result is not None:
            return result
        if last:
            raise last
        return ObjectInfo(bucket=bucket, name=object_name)

    def delete_objects(
        self, bucket: str, objects: list[tuple[str, str]], versioned: bool = False
    ) -> list[tuple[ObjectInfo | None, Exception | None]]:
        """Bulk delete: [(name, version_id)] -> per-entry result."""

        def rm(item):
            name, vid = item
            return self.delete_object(
                bucket, name, DeleteObjectOptions(version_id=vid, versioned=versioned)
            )

        return meta_mod.parallel_map(rm, objects)

    # -- listing ---------------------------------------------------------------

    def list_objects(
        self,
        bucket: str,
        prefix: str = "",
        marker: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
    ) -> ListObjectsInfo:
        probes = self._probe_pools()
        if len(probes) == 1:
            return probes[0][1].list_objects(bucket, prefix, marker, delimiter, max_keys)
        # Merge per-pool listings (each sorted).
        merged = ListObjectsInfo()
        streams = [
            p.list_objects(bucket, prefix, marker, delimiter, max_keys)
            for _i, p in probes
        ]
        names: dict[str, ObjectInfo] = {}
        for s in streams:
            for o in s.objects:
                if o.name not in names or o.mod_time > names[o.name].mod_time:
                    names[o.name] = o
        prefixes = sorted({cp for s in streams for cp in s.prefixes})
        ordered = sorted(names)
        for name in ordered[:max_keys]:
            merged.objects.append(names[name])
        if len(ordered) > max_keys or any(s.is_truncated for s in streams):
            merged.is_truncated = True
            if merged.objects:
                merged.next_marker = merged.objects[-1].name
        merged.prefixes = prefixes
        return merged

    def list_object_versions(
        self,
        bucket: str,
        prefix: str = "",
        key_marker: str = "",
        version_marker: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
    ) -> ListObjectVersionsInfo:
        probes = self._probe_pools()
        if len(probes) == 1:
            return probes[0][1].list_object_versions(
                bucket, prefix, key_marker, version_marker, delimiter, max_keys
            )
        out = ListObjectVersionsInfo()
        for _i, p in probes:
            part = p.list_object_versions(
                bucket, prefix, key_marker, version_marker, delimiter, max_keys
            )
            out.objects.extend(part.objects)
            out.prefixes = sorted(set(out.prefixes) | set(part.prefixes))
        out.objects.sort(key=lambda o: (o.name, -o.mod_time))
        if len(out.objects) > max_keys:
            out.objects = out.objects[:max_keys]
            out.is_truncated = True
            out.next_key_marker = out.objects[-1].name
            out.next_version_marker = out.objects[-1].version_id
        return out

    # -- multipart --------------------------------------------------------------

    def new_multipart_upload(self, bucket, object_name, opts: PutObjectOptions | None = None) -> str:
        _validate_object_name(bucket, object_name)
        pool = None
        try:
            idx = self._pool_holding_index(bucket, object_name)
            if self.statuses[idx] == POOL_ACTIVE:
                pool = self.pools[idx]
        except errors.ObjectError:
            pass
        if pool is None:
            pool = self._pool_with_space()
        return pool.new_multipart_upload(bucket, object_name, opts)

    def _pool_with_upload(self, bucket: str, object_name: str, upload_id: str):
        last: Exception | None = None
        for _i, p in self._probe_pools():
            try:
                p.list_parts(bucket, object_name, upload_id, 0, 1)
                return p
            except errors.ObjectError as e:
                last = e
        raise last or errors.InvalidUploadID(bucket, object_name, upload_id)

    def put_object_part(self, bucket, object_name, upload_id, part_number, data):
        return self._pool_with_upload(bucket, object_name, upload_id).put_object_part(
            bucket, object_name, upload_id, part_number, data
        )

    def list_parts(self, bucket, object_name, upload_id, part_marker=0, max_parts=1000):
        return self._pool_with_upload(bucket, object_name, upload_id).list_parts(
            bucket, object_name, upload_id, part_marker, max_parts
        )

    def complete_multipart_upload(self, bucket, object_name, upload_id, parts):
        return self._pool_with_upload(bucket, object_name, upload_id).complete_multipart_upload(
            bucket, object_name, upload_id, parts
        )

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self._pool_with_upload(bucket, object_name, upload_id).abort_multipart_upload(
            bucket, object_name, upload_id
        )

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for _i, p in self._probe_pools():
            out.extend(p.list_multipart_uploads(bucket, prefix))
        return sorted(out, key=lambda u: (u["object"], u["initiated"]))

    # -- healing ---------------------------------------------------------------

    def heal_object(
        self, bucket: str, object_name: str, version_id: str = "", dry_run: bool = False
    ) -> HealResultItem:
        last: Exception | None = None
        for _i, p in self._probe_pools():
            try:
                return p.heal_object(bucket, object_name, version_id, dry_run)
            except (errors.ObjectError, errors.DiskError) as e:
                last = e
        raise last or errors.ObjectNotFound(bucket, object_name)

    def heal_bucket(self, bucket: str) -> None:
        """Recreate the bucket volume on drives that miss it."""
        for p in self.pools:
            for s in p.sets:
                for d in s.disks:
                    if d is None:
                        continue
                    try:
                        d.stat_vol(bucket)
                    except errors.VolumeNotFound:
                        try:
                            d.make_vol(bucket)
                        except errors.DiskError:
                            pass
                    except errors.DiskError:
                        pass


def _validate_bucket_name(bucket: str) -> None:
    """S3 bucket naming rules (subset the reference enforces)."""
    if not (3 <= len(bucket) <= 63):
        raise errors.BucketNameInvalid(bucket)
    if bucket.startswith(".") or bucket.endswith(".") or bucket.startswith("-"):
        raise errors.BucketNameInvalid(bucket)
    ok = set("abcdefghijklmnopqrstuvwxyz0123456789.-")
    if not all(c in ok for c in bucket):
        raise errors.BucketNameInvalid(bucket)


def _validate_object_name(bucket: str, object_name: str) -> None:
    if not object_name or len(object_name) > 1024:
        raise errors.ObjectNameInvalid(bucket, object_name)
    if object_name.startswith("/") or "\\" in object_name:
        raise errors.ObjectNameInvalid(bucket, object_name)
    if any(part in (".", "..") for part in object_name.split("/")):
        raise errors.ObjectNameInvalid(bucket, object_name)
