"""Gateway backends: ObjectLayer adapters over other stores.

Role of the reference's cmd/gateway/{s3,nas,...} (6K LoC): serve the full
S3 front (auth, IAM, policies, events — everything the handler stack adds)
while delegating object storage to another system.

  * S3Gateway — proxies to a remote S3-compatible endpoint with SigV4
    (cmd/gateway/s3/gateway-s3.go role).
  * NASGateway — the FS backend pointed at a shared mount
    (cmd/gateway/nas/gateway-nas.go is exactly this over fs-v1).

Azure/GCS/HDFS adapters are not built: their SDKs are absent in this
environment and their wire protocols are proprietary; the S3 adapter is
the reference's own recommended migration path off the others (they were
deprecated upstream).
"""

from __future__ import annotations

import urllib.parse
import xml.etree.ElementTree as ET

from ..utils import errors
from .fs import FSObjectLayer
from .types import (
    BucketInfo,
    DeleteObjectOptions,
    GetObjectOptions,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    ObjectInfo,
    PutObjectOptions,
)

S3_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


class NASGateway(FSObjectLayer):
    """gateway nas: plain-file layer over a shared mount."""


class S3Gateway:
    """gateway s3: every ObjectLayer call becomes a signed S3 request to the
    backing endpoint."""

    def __init__(self, endpoint: str, access_key: str, secret_key: str,
                 region: str = "us-east-1"):
        # mtpulint: disable=raw-transport -- gateway talks to an EXTERNAL
        # S3 endpoint; internode deadline propagation does not apply here.
        import requests

        from ..api.auth import Credentials, sign_request

        self._sign = sign_request
        self.endpoint = endpoint.rstrip("/")
        self.creds = Credentials(access_key, secret_key)
        self.region = region
        self.host = urllib.parse.urlparse(self.endpoint).netloc
        # mtpulint: disable=raw-transport -- external backend session
        self.session = requests.Session()
        self.pools = [self]
        self.ns_lock = None
        # System metadata (bucket-metadata/config blobs) stays LOCAL: the
        # backing store is someone else's bucket namespace; the reference's
        # s3 gateway likewise keeps minio.sys state out of the backend.
        self._sys: dict[str, bytes] = {}

    # -- signed wire ---------------------------------------------------------

    def _request(self, method, path, query=None, body=b"", headers=None):
        query = query or []
        headers = dict(headers or {})
        url = self.endpoint + urllib.parse.quote(path)
        if query:
            url += "?" + urllib.parse.urlencode(query)
        headers["host"] = self.host
        signed = self._sign(
            self.creds, method, path, query, headers, body, region=self.region
        )
        signed.pop("host", None)
        return self.session.request(method, url, data=body, headers=signed, timeout=30)

    @staticmethod
    def _err(r, bucket: str = "", object_name: str = ""):
        if r.status_code == 404:
            if object_name:
                raise errors.ObjectNotFound(bucket, object_name)
            raise errors.BucketNotFound(bucket)
        if r.status_code == 409:
            raise errors.BucketExists(bucket)
        raise errors.StorageError(f"backend S3: HTTP {r.status_code}: {r.text[:200]}")

    # -- buckets -------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        r = self._request("PUT", f"/{bucket}")
        if r.status_code != 200:
            self._err(r, bucket)

    def bucket_exists(self, bucket: str) -> bool:
        return self._request("HEAD", f"/{bucket}").status_code == 200

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        if not self.bucket_exists(bucket):
            raise errors.BucketNotFound(bucket)
        return BucketInfo(name=bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        r = self._request("DELETE", f"/{bucket}")
        if r.status_code not in (200, 204):
            if r.status_code == 409:
                raise errors.BucketNotEmpty(bucket)
            self._err(r, bucket)

    def list_buckets(self) -> list[BucketInfo]:
        r = self._request("GET", "/")
        if r.status_code != 200:
            self._err(r)
        out = []
        for b in ET.fromstring(r.content).iter(f"{S3_NS}Bucket"):
            out.append(BucketInfo(name=b.findtext(f"{S3_NS}Name") or ""))
        return out

    # -- objects -------------------------------------------------------------

    def put_object(
        self, bucket: str, object_name: str, data: bytes,
        opts: PutObjectOptions | None = None,
    ) -> ObjectInfo:
        opts = opts or PutObjectOptions()
        if bucket.startswith("."):
            self._sys[f"{bucket}/{object_name}"] = bytes(data)
            return ObjectInfo(bucket=bucket, name=object_name, size=len(data))
        headers = {"content-type": opts.content_type}
        for k, v in opts.user_defined.items():
            if k.startswith("x-amz-meta-") or not k.startswith("x-"):
                headers[k if k.startswith("x-amz-meta-") else f"x-amz-meta-{k}"] = v
        r = self._request("PUT", f"/{bucket}/{object_name}", body=data, headers=headers)
        if r.status_code != 200:
            self._err(r, bucket, object_name)
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=len(data),
            etag=r.headers.get("ETag", "").strip('"'),
            version_id=r.headers.get("x-amz-version-id", ""),
        )

    def _info_from_headers(self, bucket, object_name, r) -> ObjectInfo:
        user = {
            k.lower(): v for k, v in r.headers.items() if k.lower().startswith("x-amz-meta-")
        }
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=int(r.headers.get("Content-Length", "0") or 0),
            etag=r.headers.get("ETag", "").strip('"'),
            content_type=r.headers.get("Content-Type", "application/octet-stream"),
            version_id=r.headers.get("x-amz-version-id", ""),
            user_defined=user,
        )

    def get_object_info(
        self, bucket: str, object_name: str, opts: GetObjectOptions | None = None
    ) -> ObjectInfo:
        opts = opts or GetObjectOptions()
        q = [("versionId", opts.version_id)] if opts.version_id else []
        r = self._request("HEAD", f"/{bucket}/{object_name}", query=q)
        if r.status_code != 200:
            self._err(r, bucket, object_name)
        return self._info_from_headers(bucket, object_name, r)

    def get_object(
        self, bucket: str, object_name: str,
        opts: GetObjectOptions | None = None, offset: int = 0, length: int = -1,
    ) -> tuple[ObjectInfo, bytes]:
        if bucket.startswith("."):
            key = f"{bucket}/{object_name}"
            if key not in self._sys:
                raise errors.ObjectNotFound(bucket, object_name)
            data = self._sys[key]
            return ObjectInfo(bucket=bucket, name=object_name, size=len(data)), data
        opts = opts or GetObjectOptions()
        q = [("versionId", opts.version_id)] if opts.version_id else []
        headers = {}
        if (offset, length) != (0, -1):
            end = "" if length < 0 else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._request("GET", f"/{bucket}/{object_name}", query=q, headers=headers)
        if r.status_code not in (200, 206):
            self._err(r, bucket, object_name)
        return self._info_from_headers(bucket, object_name, r), r.content

    def put_object_metadata(
        self, bucket, object_name, version_id: str = "", updates=None, removes=None
    ) -> ObjectInfo:
        # S3 metadata replace = self-copy with REPLACE directive.
        oi = self.get_object_info(bucket, object_name)
        meta = dict(oi.user_defined)
        for k in removes or []:
            meta.pop(k, None)
        meta.update(updates or {})
        headers = {
            "x-amz-copy-source": f"/{bucket}/{object_name}",
            "x-amz-metadata-directive": "REPLACE",
            **meta,
        }
        r = self._request("PUT", f"/{bucket}/{object_name}", headers=headers)
        if r.status_code != 200:
            self._err(r, bucket, object_name)
        return self.get_object_info(bucket, object_name)

    def delete_object(
        self, bucket: str, object_name: str, opts: DeleteObjectOptions | None = None
    ) -> ObjectInfo:
        if bucket.startswith("."):
            self._sys.pop(f"{bucket}/{object_name}", None)
            return ObjectInfo(bucket=bucket, name=object_name)
        opts = opts or DeleteObjectOptions()
        q = [("versionId", opts.version_id)] if opts.version_id else []
        r = self._request("DELETE", f"/{bucket}/{object_name}", query=q)
        if r.status_code not in (200, 204):
            self._err(r, bucket, object_name)
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            delete_marker=r.headers.get("x-amz-delete-marker", "") == "true",
            version_id=r.headers.get("x-amz-version-id", ""),
        )

    def delete_objects(self, bucket: str, objects, versioned: bool = False):
        out = []
        for name, vid in objects:
            try:
                out.append(
                    (self.delete_object(bucket, name, DeleteObjectOptions(version_id=vid)), None)
                )
            except errors.StorageError as e:
                out.append((None, e))
        return out

    # -- listing -------------------------------------------------------------

    def list_objects(
        self, bucket: str, prefix: str = "", marker: str = "",
        delimiter: str = "", max_keys: int = 1000,
    ) -> ListObjectsInfo:
        q = [("list-type", "2"), ("prefix", prefix), ("max-keys", str(max_keys))]
        if delimiter:
            q.append(("delimiter", delimiter))
        if marker:
            q.append(("start-after", marker))
        r = self._request("GET", f"/{bucket}", query=q)
        if r.status_code != 200:
            self._err(r, bucket)
        root = ET.fromstring(r.content)
        res = ListObjectsInfo(
            is_truncated=(root.findtext(f"{S3_NS}IsTruncated") == "true"),
        )
        for c in root.findall(f"{S3_NS}Contents"):
            res.objects.append(
                ObjectInfo(
                    bucket=bucket,
                    name=c.findtext(f"{S3_NS}Key") or "",
                    size=int(c.findtext(f"{S3_NS}Size") or 0),
                    etag=(c.findtext(f"{S3_NS}ETag") or "").strip('"'),
                )
            )
        for p in root.findall(f"{S3_NS}CommonPrefixes"):
            res.prefixes.append(p.findtext(f"{S3_NS}Prefix") or "")
        if res.objects:
            res.next_marker = res.objects[-1].name
        return res

    def list_object_versions(
        self, bucket: str, prefix: str = "", key_marker: str = "",
        version_marker: str = "", delimiter: str = "", max_keys: int = 1000,
    ) -> ListObjectVersionsInfo:
        listing = self.list_objects(bucket, prefix, key_marker, delimiter, max_keys)
        return ListObjectVersionsInfo(
            is_truncated=listing.is_truncated,
            next_key_marker=listing.next_marker,
            objects=listing.objects,
            prefixes=listing.prefixes,
        )

    # -- multipart (proxied straight through) ---------------------------------

    def new_multipart_upload(
        self, bucket: str, object_name: str, opts: PutObjectOptions | None = None
    ) -> str:
        r = self._request("POST", f"/{bucket}/{object_name}", query=[("uploads", "")])
        if r.status_code != 200:
            self._err(r, bucket, object_name)
        return ET.fromstring(r.content).findtext(f"{S3_NS}UploadId") or ""

    def put_object_part(self, bucket, object_name, upload_id, part_number, data):
        from ..storage.types import ObjectPartInfo

        r = self._request(
            "PUT",
            f"/{bucket}/{object_name}",
            query=[("partNumber", str(part_number)), ("uploadId", upload_id)],
            body=data,
        )
        if r.status_code != 200:
            self._err(r, bucket, object_name)
        return ObjectPartInfo(
            part_number, len(data), len(data), 0.0, r.headers.get("ETag", "").strip('"')
        )

    def list_parts(self, bucket, object_name, upload_id, part_marker=0, max_parts=1000):
        from ..storage.types import ObjectPartInfo

        r = self._request(
            "GET", f"/{bucket}/{object_name}", query=[("uploadId", upload_id)]
        )
        if r.status_code != 200:
            self._err(r, bucket, object_name)
        out = []
        for p in ET.fromstring(r.content).findall(f"{S3_NS}Part"):
            out.append(
                ObjectPartInfo(
                    int(p.findtext(f"{S3_NS}PartNumber") or 0),
                    int(p.findtext(f"{S3_NS}Size") or 0),
                    int(p.findtext(f"{S3_NS}Size") or 0),
                    0.0,
                    (p.findtext(f"{S3_NS}ETag") or "").strip('"'),
                )
            )
        return [p for p in out if p.number > part_marker][:max_parts]

    def complete_multipart_upload(self, bucket, object_name, upload_id, parts):
        body = (
            "<CompleteMultipartUpload>"
            + "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{etag}</ETag></Part>"
                for n, etag in parts
            )
            + "</CompleteMultipartUpload>"
        ).encode()
        r = self._request(
            "POST", f"/{bucket}/{object_name}", query=[("uploadId", upload_id)], body=body
        )
        if r.status_code != 200:
            self._err(r, bucket, object_name)
        return self.get_object_info(bucket, object_name)

    def abort_multipart_upload(self, bucket, object_name, upload_id) -> None:
        r = self._request(
            "DELETE", f"/{bucket}/{object_name}", query=[("uploadId", upload_id)]
        )
        if r.status_code not in (200, 204):
            self._err(r, bucket, object_name)

    def list_multipart_uploads(self, bucket: str, prefix: str = "") -> list[dict]:
        r = self._request("GET", f"/{bucket}", query=[("uploads", ""), ("prefix", prefix)])
        if r.status_code != 200:
            self._err(r, bucket)
        out = []
        for u in ET.fromstring(r.content).findall(f"{S3_NS}Upload"):
            out.append(
                {
                    "upload_id": u.findtext(f"{S3_NS}UploadId") or "",
                    "object": u.findtext(f"{S3_NS}Key") or "",
                    "initiated": 0.0,
                }
            )
        return out

    # -- heal: delegated store owns durability --------------------------------

    def heal_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    def heal_object(self, bucket, object_name, version_id="", dry_run=False):
        from .types import HealResultItem

        self.get_object_info(bucket, object_name)
        return HealResultItem(bucket=bucket, object=object_name)
