"""Erasure sets: many independent K+M sets behind one namespace.

Role of the reference's erasureSets (cmd/erasure-sets.go): drives are grouped
into sets of a fixed size; each object lives entirely in one set, chosen by
SipHash of the object name keyed by the deployment id
(cmd/erasure-sets.go:747-784). Bucket operations span all sets; listing
merges per-set sorted walk streams.
"""

from __future__ import annotations

import uuid as uuid_mod

from ..storage.format import DriveFormat
from ..storage.interface import StorageAPI
from ..storage.local import XL_META_FILE
from ..storage.types import FileInfo
from ..storage.xlmeta import XLMeta
from ..utils import errors
from ..utils.hashes import crc_hash_mod, sip_hash_mod
from . import codec as codec_mod
from . import metacache as metacache_mod
from . import metadata as meta_mod
from .erasure import ErasureObjects
from .types import (
    BucketInfo,
    DeleteObjectOptions,
    GetObjectOptions,
    HealResultItem,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    ObjectInfo,
    PutObjectOptions,
)


class ErasureSets:
    """All sets of one pool."""

    def __init__(
        self,
        disks: list[StorageAPI | None],
        set_drive_count: int,
        deployment_id: str = "",
        distribution_algo: str = "SIPMOD+PARITY",
        parity: int | None = None,
        codec: codec_mod.BlockCodec | None = None,
        pool_index: int = 0,
        rrs_parity: int | None = None,
    ):
        if len(disks) % set_drive_count:
            raise ValueError("drive count must be a multiple of set size")
        self.set_drive_count = set_drive_count
        self.deployment_id = deployment_id or str(uuid_mod.uuid4())
        self.distribution_algo = distribution_algo
        self.disks = disks
        self.sets: list[ErasureObjects] = []
        for s in range(len(disks) // set_drive_count):
            sub = disks[s * set_drive_count : (s + 1) * set_drive_count]
            self.sets.append(
                ErasureObjects(
                    sub, parity=parity, codec=codec, set_index=s,
                    pool_index=pool_index, rrs_parity=rrs_parity,
                )
            )
        self.metacache = metacache_mod.MetacacheManager(
            self._walk_merged, persist=self._persist_cache, load=self._load_cache
        )

    @classmethod
    def from_drives(
        cls,
        drives: list[StorageAPI],
        fmt: DriveFormat,
        parity: int | None = None,
        codec: codec_mod.BlockCodec | None = None,
        pool_index: int = 0,
        rrs_parity: int | None = None,
    ) -> "ErasureSets":
        """Arrange drives according to a quorum format (newErasureSets,
        cmd/erasure-sets.go:353): position = where the drive's id appears."""
        n_sets = len(fmt.sets)
        count = len(fmt.sets[0])
        arranged: list[StorageAPI | None] = [None] * (n_sets * count)
        for d in drives:
            try:
                s, i = fmt.find_disk(d.disk_id())
            except errors.DiskIDMismatch:
                continue
            arranged[s * count + i] = d
        obj = cls(
            arranged,
            count,
            deployment_id=fmt.deployment_id,
            distribution_algo=fmt.distribution_algo,
            parity=parity,
            codec=codec,
            pool_index=pool_index,
            rrs_parity=rrs_parity,
        )
        return obj

    # -- routing -------------------------------------------------------------

    def _dep_id_bytes(self) -> bytes:
        try:
            return uuid_mod.UUID(self.deployment_id).bytes
        except ValueError:
            return (self.deployment_id.encode() + b"\0" * 16)[:16]

    def get_set_index(self, object_name: str) -> int:
        if self.distribution_algo.startswith("CRCMOD"):
            return crc_hash_mod(object_name, len(self.sets))
        return sip_hash_mod(object_name, len(self.sets), self._dep_id_bytes())

    def get_hashed_set(self, object_name: str) -> ErasureObjects:
        return self.sets[self.get_set_index(object_name)]

    # -- buckets (span all sets) ----------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        results = meta_mod.parallel_map(lambda s: s.make_bucket(bucket), self.sets)
        errs = [e for _, e in results]
        for e in errs:
            if isinstance(e, errors.BucketExists):
                raise e
        err = next((e for e in errs if e is not None), None)
        if err:
            raise err

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        return self.sets[0].get_bucket_info(bucket)

    def invalidate_bucket_cache(self, bucket: str = "") -> None:
        for s in self.sets:
            s.invalidate_bucket_cache(bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self.metacache.invalidate(bucket)
        results = meta_mod.parallel_map(lambda s: s.delete_bucket(bucket, force), self.sets)
        errs = [e for _, e in results]
        for e in errs:
            if isinstance(e, errors.BucketNotEmpty):
                raise e
        err = next((e for e in errs if e is not None), None)
        if err:
            raise err

    def list_buckets(self) -> list[BucketInfo]:
        return self.sets[0].list_buckets()

    # -- objects (route to one set) -------------------------------------------

    def put_object(self, bucket, object_name, data, opts: PutObjectOptions | None = None):
        try:
            return self.get_hashed_set(object_name).put_object(bucket, object_name, data, opts)
        finally:
            self.metacache.invalidate(bucket)

    def get_object(self, bucket, object_name, opts: GetObjectOptions | None = None, offset=0, length=-1):
        return self.get_hashed_set(object_name).get_object(bucket, object_name, opts, offset, length)

    def get_object_stream(
        self, bucket, object_name, opts: GetObjectOptions | None = None, offset=0, length=-1
    ):
        return self.get_hashed_set(object_name).get_object_stream(
            bucket, object_name, opts, offset, length
        )

    def get_object_info(self, bucket, object_name, opts: GetObjectOptions | None = None):
        return self.get_hashed_set(object_name).get_object_info(bucket, object_name, opts)

    def put_object_metadata(self, bucket, object_name, version_id="", updates=None, removes=None):
        try:
            return self.get_hashed_set(object_name).put_object_metadata(
                bucket, object_name, version_id, updates, removes
            )
        finally:
            self.metacache.invalidate(bucket)

    def transition_object(
        self, bucket, object_name, version_id, tier, remote_name,
        expected_etag="", expected_mtime=0.0,
    ):
        try:
            return self.get_hashed_set(object_name).transition_object(
                bucket, object_name, version_id, tier, remote_name,
                expected_etag, expected_mtime,
            )
        finally:
            self.metacache.invalidate(bucket)

    def delete_object(self, bucket, object_name, opts: DeleteObjectOptions | None = None):
        try:
            return self.get_hashed_set(object_name).delete_object(bucket, object_name, opts)
        finally:
            self.metacache.invalidate(bucket)

    def heal_object(self, bucket, object_name, version_id="", dry_run=False) -> HealResultItem:
        return self.get_hashed_set(object_name).heal_object(bucket, object_name, version_id, dry_run)

    # -- multipart (route to one set) ------------------------------------------

    def new_multipart_upload(self, bucket, object_name, opts: PutObjectOptions | None = None) -> str:
        return self.get_hashed_set(object_name).multipart.new_multipart_upload(bucket, object_name, opts)

    def put_object_part(self, bucket, object_name, upload_id, part_number, data):
        return self.get_hashed_set(object_name).multipart.put_object_part(
            bucket, object_name, upload_id, part_number, data
        )

    def list_parts(self, bucket, object_name, upload_id, part_marker=0, max_parts=1000):
        return self.get_hashed_set(object_name).multipart.list_parts(
            bucket, object_name, upload_id, part_marker, max_parts
        )

    def complete_multipart_upload(self, bucket, object_name, upload_id, parts):
        try:
            return self.get_hashed_set(object_name).multipart.complete_multipart_upload(
                bucket, object_name, upload_id, parts
            )
        finally:
            self.metacache.invalidate(bucket)

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self.get_hashed_set(object_name).multipart.abort_multipart_upload(
            bucket, object_name, upload_id
        )

    def list_multipart_uploads(self, bucket, prefix=""):
        out = []
        for s in self.sets:
            out.extend(s.multipart.list_multipart_uploads(bucket, prefix))
        return sorted(out, key=lambda u: (u["object"], u["initiated"]))

    # -- listing (metacache over merged sorted per-drive walks) ---------------

    def _persist_cache(self, path: str, blob: bytes) -> None:
        """Write a metacache image to the first online drives (best effort,
        the putMetacacheObject role, cmd/metacache-set.go write-back)."""
        written = 0
        for d in self.sets[0].disks:
            if d is None or not d.is_online():
                continue
            d.create_file(metacache_mod.META_BUCKET, path, blob)
            written += 1
            if written >= 2:
                return
        if written == 0:
            raise errors.DiskNotFound()

    def _load_cache(self, path: str) -> bytes:
        for d in self.sets[0].disks:
            if d is None or not d.is_online():
                continue
            try:
                return d.read_file(metacache_mod.META_BUCKET, path)
            except errors.DiskError:
                continue
        raise errors.FileNotFound(metacache_mod.META_BUCKET, path)

    def _walk_merged(self, bucket: str, prefix: str = ""):
        """Yield (name, xl_meta_bytes) sorted by name, deduped across drives
        with a majority pick on the raw metadata (listPathRaw + quorum
        resolve, cmd/metacache-set.go:783, metacache-entries.go)."""
        per_name: dict[str, dict[bytes, int]] = {}
        base = prefix if prefix.endswith("/") else ""

        def collect(s: ErasureObjects):
            found: dict[str, dict[bytes, int]] = {}
            for d in s.disks:
                if d is None or not d.is_online():
                    continue
                try:
                    for name, raw in d.walk_dir(bucket, base=base.rstrip("/")):
                        if not name.startswith(prefix):
                            continue
                        found.setdefault(name, {})
                        found[name][raw] = found[name].get(raw, 0) + 1
                except errors.VolumeNotFound:
                    raise
                except errors.DiskError:
                    continue
            return found

        results = meta_mod.parallel_map(collect, self.sets)
        vol_missing = sum(1 for _, e in results if isinstance(e, errors.VolumeNotFound))
        if vol_missing == len(self.sets):
            raise errors.BucketNotFound(bucket)
        for found, err in results:
            if found is None:
                continue
            for name, variants in found.items():
                per_name.setdefault(name, {})
                for raw, cnt in variants.items():
                    per_name[name][raw] = per_name[name].get(raw, 0) + cnt
        for name in sorted(per_name):
            variants = per_name[name]
            raw = max(variants, key=lambda r: variants[r])
            yield name, raw

    def list_objects(
        self,
        bucket: str,
        prefix: str = "",
        marker: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
    ) -> ListObjectsInfo:
        self.get_bucket_info(bucket)
        max_keys = max(0, min(max_keys, 1000))
        out = ListObjectsInfo()
        if max_keys == 0:
            # S3 answers max-keys=0 with an empty, non-truncated result; a
            # truncated one would carry an empty next_marker and strand pagers.
            return out
        prefixes: set[str] = set()
        # next_marker is the LAST RETURNED item (S3 V1 semantics): an object
        # key, or a common prefix -- in which case resumption must skip the
        # whole subtree rolled up into it.
        last_item = ""
        for name, raw in self.metacache.entries_from(bucket, prefix, marker):
            if marker and (
                name <= marker
                or (delimiter and marker.endswith(delimiter) and name.startswith(marker))
            ):
                continue
            if delimiter:
                rest = name[len(prefix) :]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp not in prefixes:
                        if len(out.objects) + len(prefixes) >= max_keys:
                            out.is_truncated = True
                            out.next_marker = last_item
                            break
                        prefixes.add(cp)
                        last_item = cp
                    continue
            try:
                meta = XLMeta.from_bytes(raw)
                fi = meta.file_info("")
            except errors.StorageError:
                continue
            if fi.deleted:
                continue
            if len(out.objects) + len(prefixes) >= max_keys:
                out.is_truncated = True
                out.next_marker = last_item
                break
            out.objects.append(ObjectInfo.from_file_info(fi, bucket, name))
            last_item = name
        out.prefixes = sorted(prefixes)
        return out

    def list_object_versions(
        self,
        bucket: str,
        prefix: str = "",
        key_marker: str = "",
        version_marker: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
    ) -> ListObjectVersionsInfo:
        self.get_bucket_info(bucket)
        max_keys = max(0, min(max_keys, 1000))
        out = ListObjectVersionsInfo()
        if max_keys == 0:
            return out
        prefixes: set[str] = set()
        done = False
        for name, raw in self.metacache.entries_from(bucket, prefix, ""):
            if done:
                break
            if key_marker and name < key_marker:
                continue
            if delimiter:
                rest = name[len(prefix) :]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    prefixes.add(cp)
                    continue
            try:
                meta = XLMeta.from_bytes(raw)
            except errors.StorageError:
                continue
            # Resuming inside the marker object: versions are ordered newest
            # first, so skip every version up to AND INCLUDING version_marker
            # (S3 version-id-marker semantics), not just the marker itself.
            skipping = bool(key_marker and name == key_marker)
            if (
                skipping
                and version_marker
                and not any(v.version_id == version_marker for v in meta.versions)
            ):
                # Marker version was deleted between pages: emit everything
                # rather than silently dropping the key's remaining versions
                # (duplicates are recoverable client-side; losses are not).
                skipping = False
            for fi in meta.versions:
                if skipping:
                    if not version_marker:
                        skipping = False  # key_marker alone: whole object done
                        break
                    if fi.version_id == version_marker:
                        skipping = False
                    continue
                if len(out.objects) >= max_keys:
                    out.is_truncated = True
                    out.next_key_marker = out.objects[-1].name
                    out.next_version_marker = out.objects[-1].version_id
                    done = True
                    break
                fi.is_latest = fi is meta.versions[0]
                oi = ObjectInfo.from_file_info(fi, bucket, name)
                out.objects.append(oi)
        out.prefixes = sorted(prefixes)
        return out
