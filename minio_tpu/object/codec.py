"""Block codec service: the seam between the object layer and the math.

The object layer hands 1 MiB blocks to a BlockCodec and gets back shard bytes
plus bitrot digests. Implementations:

  * HostCodec  -- numpy GF tables + numpy HighwayHash; the low-latency
    fallback (the reference's always-on CPU SIMD analogue).
  * DeviceCodec -- single-shot JAX encode+hash on the accelerator; right for
    large objects / heals where one call carries many blocks.
  * The cross-upload batching scheduler (parallel/batching.py) wraps
    DeviceCodec to aggregate blocks from concurrent requests into one device
    program -- the BASELINE.json north-star design.

All implementations produce bit-identical outputs (tests pin this), so the
object layer can switch freely per call size.
"""

from __future__ import annotations

import abc

import numpy as np

from ..ops import highwayhash as hh
from ..ops import rs_matrix, rs_ref


class BlockCodec(abc.ABC):
    """Encode/decode service for erasure blocks."""

    @abc.abstractmethod
    def encode(
        self, blocks: list[bytes], k: int, m: int
    ) -> list[tuple[list[bytes], list[bytes]]]:
        """For each input block: ([K+M shard chunks], [K+M digests])."""

    @abc.abstractmethod
    def reconstruct(
        self, shards: list[bytes | None], k: int, m: int, want: tuple[int, ...]
    ) -> list[bytes]:
        """Rebuild the `want` shard rows from available shards (None = lost)."""


def _split_block(block: bytes, k: int) -> np.ndarray:
    return rs_matrix.split(np.frombuffer(block, dtype=np.uint8), k)


class HostCodec(BlockCodec):
    """Host CPU codec: C++/AVX2 kernels (native/minio_native.cpp) when the
    toolchain built them, numpy table lookups otherwise. Bit-identical either
    way (tests pin both against the reference golden vectors)."""

    def __init__(self, use_native: bool | None = None):
        from ..ops import native

        self._native = native if (use_native is None and native.available()) or use_native else None

    def _encode_one(self, shards: np.ndarray, m: int) -> np.ndarray:
        k = shards.shape[0]
        if self._native is not None:
            parity = self._native.rs_encode(shards, rs_matrix.parity_matrix(k, m))
            return np.concatenate([shards, parity], axis=0)
        return rs_ref.encode(shards, m)

    def _digests(self, shards: np.ndarray) -> np.ndarray:
        if self._native is not None:
            return self._native.hh256_batch(shards, hh.MAGIC_KEY)
        return hh.hash256_batch(shards)

    def encode(self, blocks, k, m):
        out = []
        for block in blocks:
            shards = self._encode_one(_split_block(block, k), m)  # [K+M, S]
            digests = self._digests(shards)
            out.append(
                (
                    [shards[i].tobytes() for i in range(k + m)],
                    [digests[i].tobytes() for i in range(k + m)],
                )
            )
        return out

    def reconstruct(self, shards, k, m, want):
        arrs: list[np.ndarray | None] = [
            np.frombuffer(s, dtype=np.uint8) if s is not None else None for s in shards
        ]
        if self._native is not None and any(s is not None for s in shards):
            present = tuple(s is not None for s in arrs)
            survivors = np.stack([a for a in arrs if a is not None][:k], axis=0)
            coeffs = rs_matrix.reconstruct_rows(k, m, present, tuple(want))
            rebuilt = self._native.rs_apply(survivors, coeffs)
            return [rebuilt[i].tobytes() for i in range(len(want))]
        rebuilt = rs_ref.reconstruct(arrs, k, m, data_only=False)
        return [rebuilt[i].tobytes() for i in want]


class DeviceCodec(BlockCodec):
    """JAX device codec: one fused encode+hash program per call.

    Blocks in one call are padded to the longest shard size and batched into
    a single [B, K, S] tensor, so a large PutObject or heal already amortizes
    transfer/launch across its own blocks. Cross-request amortization is the
    batching scheduler's job (parallel/batching.py).
    """

    def __init__(self):
        self._host = HostCodec()

    def encode(self, blocks, k, m):
        from ..ops import rs as rs_dev

        if not blocks:
            return []
        sizes = [rs_matrix.shard_size(len(b), k) for b in blocks]
        s_max = max(sizes)
        batch = np.zeros((len(blocks), k, s_max), dtype=np.uint8)
        for i, block in enumerate(blocks):
            batch[i, :, : sizes[i]] = _split_block(block, k)
        codec = rs_dev.RSCodec(k, m)
        all_shards = np.asarray(codec.encode_all(batch))  # [B, K+M, S]
        out = []
        for i in range(len(blocks)):
            s = sizes[i]
            shards_i = all_shards[i, :, :s]
            # Padded-batch digests are only valid when every block shares the
            # padded length; hash at true length instead (host-vectorized
            # when lengths are uniform this never triggers; see batching).
            digests = hh.hash256_batch(np.ascontiguousarray(shards_i))
            out.append(
                (
                    [shards_i[j].tobytes() for j in range(k + m)],
                    [digests[j].tobytes() for j in range(k + m)],
                )
            )
        return out

    def reconstruct(self, shards, k, m, want):
        return self._host.reconstruct(shards, k, m, want)


_default: BlockCodec | None = None


def default_codec() -> BlockCodec:
    """Process-wide codec. Host for now; the server runtime installs the
    batching device codec at startup (see parallel/batching.py)."""
    global _default
    if _default is None:
        _default = HostCodec()
    return _default


def set_default_codec(codec: BlockCodec) -> None:
    global _default
    _default = codec
