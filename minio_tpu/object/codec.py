"""Block codec service: the seam between the object layer and the math.

The object layer hands 1 MiB blocks to a BlockCodec and gets back shard bytes
plus bitrot digests. Implementations:

  * HostCodec  -- numpy GF tables + numpy HighwayHash; the low-latency
    fallback (the reference's always-on CPU SIMD analogue).
  * DeviceCodec -- single-shot JAX encode+hash on the accelerator; right for
    large objects / heals where one call carries many blocks.
  * The cross-upload batching scheduler (parallel/batching.py) wraps
    DeviceCodec to aggregate blocks from concurrent requests into one device
    program -- the BASELINE.json north-star design.

All implementations produce bit-identical outputs (tests pin this), so the
object layer can switch freely per call size.
"""

from __future__ import annotations

import abc

import numpy as np

from ..control import tracing
from ..ops import highwayhash as hh
from ..ops import rs_matrix, rs_ref


class BlockCodec(abc.ABC):
    """Encode/decode service for erasure blocks."""

    @abc.abstractmethod
    def encode(
        self, blocks: list[bytes], k: int, m: int
    ) -> list[tuple[list[bytes], list[bytes]]]:
        """For each input block: ([K+M shard chunks], [K+M digests])."""

    @abc.abstractmethod
    def reconstruct(
        self, shards: list[bytes | None], k: int, m: int, want: tuple[int, ...]
    ) -> list[bytes]:
        """Rebuild the `want` shard rows from available shards (None = lost)."""

    def reconstruct_batch(
        self,
        rows_batch: list[list[bytes | None]],
        k: int,
        m: int,
        want: tuple[int, ...],
        with_digests: bool = False,
    ) -> list[tuple[list[bytes], list[bytes] | None]]:
        """Rebuild `want` rows for MANY blocks sharing one present-mask.

        The batched analogue of `reconstruct` -- degraded GETs and heal
        rebuild whole windows of blocks with the same shards lost
        (reference per-block loop: cmd/erasure-decode.go:206,
        erasure-lowlevel-heal.go:31), so device codecs override this to run
        one [B, K, S] program instead of B round trips. Returns, per block,
        (rebuilt chunks, their bitrot digests or None when not requested).
        """
        from ..ops import bitrot

        out: list[tuple[list[bytes], list[bytes] | None]] = []
        for rows in rows_batch:
            chunks = self.reconstruct(rows, k, m, want)
            digests = [bitrot.digest_of(c) for c in chunks] if with_digests else None
            out.append((chunks, digests))
        return out

    def digests_batch(self, chunks: list[bytes]) -> list[bytes]:
        """Bitrot digests of many shard chunks (deep-scan / heal verify).

        Host codecs use the vectorized native hash; the batching device
        codec routes uniform full-chunk batches through the device
        verify_digests pipeline (the scanner's deep-scan consumer)."""
        from ..ops import bitrot

        return bitrot.digests_of_batch(chunks)

    def encode_frames(self, blocks: list[bytes], k: int, m: int) -> "list[bytes | memoryview]":
        """Per shard ROW: concatenated H(chunk)||chunk frames across blocks.

        This is the byte image appended to each drive's staged shard file
        (streaming-bitrot layout, cmd/bitrot-streaming.go:43-65). The default
        builds frames from encode()'s chunks+digests; HostCodec overrides
        with a single C hash+frame call per row. Rows are bytes-LIKE
        (buffer protocol): consumers write them to files/HTTP bodies and
        must not assume hashability or msgpack support."""
        encoded = self.encode(blocks, k, m)
        rows: list[bytes] = []
        for row in range(k + m):
            parts: list[bytes] = []
            for chunks, digests in encoded:
                parts.append(digests[row])
                parts.append(chunks[row])
            rows.append(b"".join(parts))
        return rows

    def encode_group(self, blocks: list, k: int, m: int) -> "EncodedGroup":
        """Scatter form of encode_frames: per-row IOVEC LISTS instead of
        joined row images, so the fan-out hands each drive its whole group
        as views (one os.writev) and never materializes row bytes. The
        concatenation of a row's iovecs is byte-identical to
        encode_frames()[row]. Also carries the data-row digest stream the
        fast etag hashes (block-major, rows 0..k-1)."""
        encoded = self.encode(blocks, k, m)
        iovecs: list[list] = []
        for row in range(k + m):
            vecs: list = []
            for chunks, digests in encoded:
                vecs.append(digests[row])
                vecs.append(chunks[row])
            iovecs.append(vecs)
        digest_stream = b"".join(
            digests[row] for chunks, digests in encoded for row in range(k)
        )
        return EncodedGroup(iovecs, digest_stream)


class EncodedGroup:
    """One encoded window, scatter layout.

    iovecs[row] is the buffer sequence whose concatenation is that drive's
    staged-file frame image for the group (digest||chunk per block). The
    views alias storage allocated per call and kept alive by the iovecs
    themselves, never the caller's input window -- so the PUT pipeline can
    recycle its pooled read buffer and encode group g+1 while group g's
    writes are still in flight. digest_stream is the concatenated data-row
    digests feeding the streaming etag."""

    __slots__ = ("iovecs", "digest_stream")

    def __init__(self, iovecs: list[list], digest_stream: bytes):
        self.iovecs = iovecs
        self.digest_stream = digest_stream

    def row_nbytes(self, row: int) -> int:
        return sum(len(v) for v in self.iovecs[row])


def _split_block(block: bytes, k: int) -> np.ndarray:
    return rs_matrix.split(np.frombuffer(block, dtype=np.uint8), k)


class HostCodec(BlockCodec):
    """Host CPU codec: C++/AVX2 kernels (native/minio_native.cpp) when the
    toolchain built them, numpy table lookups otherwise. Bit-identical either
    way (tests pin both against the reference golden vectors)."""

    def __init__(self, use_native: bool | None = None):
        from ..ops import native

        self._native = native if (use_native is None and native.available()) or use_native else None

    def _encode_one(self, shards: np.ndarray, m: int) -> np.ndarray:
        k = shards.shape[0]
        if self._native is not None:
            parity = self._native.rs_encode(shards, rs_matrix.parity_matrix(k, m))
            return np.concatenate([shards, parity], axis=0)
        return rs_ref.encode(shards, m)

    def _digests(self, shards: np.ndarray) -> np.ndarray:
        if self._native is not None:
            return self._native.hh256_batch(shards, hh.MAGIC_KEY)
        return hh.hash256_batch(shards)

    def encode(self, blocks, k, m):
        with tracing.span(
            "erasure.encode", "erasure", blocks=len(blocks), k=k, m=m, host=True
        ):
            out = []
            for block in blocks:
                shards = self._encode_one(_split_block(block, k), m)  # [K+M, S]
                digests = self._digests(shards)
                out.append(
                    (
                        [shards[i].tobytes() for i in range(k + m)],
                        [digests[i].tobytes() for i in range(k + m)],
                    )
                )
            return out

    def encode_frames(self, blocks, k, m):
        """Uniform block groups: split + parity are written straight into one
        [G, K+M, S] buffer (rs_encode's `out` view), then ONE strided
        hh256_frame C call per shard row hashes + interleaves in native code
        (native/minio_native.cpp:326) -- no per-shard Python loop, no
        np.stack / per-row ascontiguousarray copies of the group. Rows come
        back as memoryviews (buffer-protocol consumers only: drive appends /
        HTTP bodies)."""
        if (
            self._native is None
            or not blocks
            or len({len(b) for b in blocks}) != 1
            or len(blocks[0]) == 0  # split() rejects empty -- keep paths identical
        ):
            return super().encode_frames(blocks, k, m)
        with tracing.span(
            "erasure.encode_frames", "erasure", blocks=len(blocks), k=k, m=m, host=True
        ):
            pm = np.ascontiguousarray(rs_matrix.parity_matrix(k, m))
            s = rs_matrix.shard_size(len(blocks[0]), k)
            stacked = np.empty((len(blocks), k + m, s), dtype=np.uint8)
            for i, block in enumerate(blocks):
                flat = stacked[i, :k].reshape(-1)
                flat[: len(block)] = np.frombuffer(block, dtype=np.uint8)
                flat[len(block):] = 0  # zero-pad the tail shard (Split semantics)
                self._native.rs_encode(stacked[i, :k], pm, out=stacked[i, k:])
            return self._native.hh256_frame_rows(stacked, hh.MAGIC_KEY)

    def encode_group(self, blocks, k, m):
        """Native scatter path: one [G, K+M, S] buffer takes split + parity
        (rs_encode `out` views), ONE batched hash call digests every shard
        chunk ([G*(K+M), S] view -- ~6x cheaper than the per-row interleave
        in hh256_frame_rows, which also copies every chunk into joined row
        images), and the iovecs are views over that buffer: nothing is
        rejoined. Irregular groups (mixed sizes / no native kernels) fall
        back to the encode()-based default."""
        if (
            self._native is None
            or not blocks
            or len({len(b) for b in blocks}) != 1
            or len(blocks[0]) == 0
        ):
            return super().encode_group(blocks, k, m)
        with tracing.span(
            "erasure.encode_group", "erasure", blocks=len(blocks), k=k, m=m, host=True
        ):
            pm = np.ascontiguousarray(rs_matrix.parity_matrix(k, m))
            g = len(blocks)
            t = k + m
            s = rs_matrix.shard_size(len(blocks[0]), k)
            stacked = np.empty((g, t, s), dtype=np.uint8)
            for i, block in enumerate(blocks):
                flat = stacked[i, :k].reshape(-1)
                flat[: len(block)] = np.frombuffer(block, dtype=np.uint8)
                flat[len(block):] = 0
                self._native.rs_encode(stacked[i, :k], pm, out=stacked[i, k:])
            digests = self._native.hh256_batch(
                stacked.reshape(g * t, s), hh.MAGIC_KEY
            ).reshape(g, t, 32)
            iovecs = [
                [v for i in range(g) for v in (memoryview(digests[i, row]), memoryview(stacked[i, row]))]
                for row in range(t)
            ]
            return EncodedGroup(iovecs, digests[:, :k, :].tobytes())

    def reconstruct(self, shards, k, m, want):
        arrs: list[np.ndarray | None] = [
            np.frombuffer(s, dtype=np.uint8) if s is not None else None for s in shards
        ]
        if self._native is not None and any(s is not None for s in shards):
            present = tuple(s is not None for s in arrs)
            survivors = np.stack([a for a in arrs if a is not None][:k], axis=0)
            coeffs = rs_matrix.reconstruct_rows(k, m, present, tuple(want))
            rebuilt = self._native.rs_apply(survivors, coeffs)
            return [rebuilt[i].tobytes() for i in range(len(want))]
        rebuilt = rs_ref.reconstruct(arrs, k, m, data_only=False)
        return [rebuilt[i].tobytes() for i in want]

    def reconstruct_batch(self, rows_batch, k, m, want, with_digests=False):
        """Uniform windows rebuild with ONE matrix inversion and ONE C call:
        GF(2^8) is per-byte, so B blocks sharing a loss pattern concatenate
        along the byte axis into a [K, B*S] slab (the per-block default was
        256 inversions + 256 kernel calls per 256-block heal). Digests of
        the rebuilt rows batch into one hash call too."""
        plan = uniform_recon_plan(rows_batch, k) if len(rows_batch) > 1 else None
        if plan is None or self._native is None:
            return super().reconstruct_batch(rows_batch, k, m, want, with_digests)
        with tracing.span(
            "erasure.reconstruct", "erasure", blocks=len(rows_batch), k=k, m=m, host=True
        ):
            return self._reconstruct_batch_slab(
                rows_batch, k, m, want, with_digests, plan
            )

    def _reconstruct_batch_slab(self, rows_batch, k, m, want, with_digests, plan):
        present, surv, s = plan
        b = len(rows_batch)
        survivors = np.empty((k, b * s), dtype=np.uint8)
        for bi, rows in enumerate(rows_batch):
            for ki, j in enumerate(surv):
                survivors[ki, bi * s : (bi + 1) * s] = np.frombuffer(rows[j], dtype=np.uint8)
        coeffs = np.ascontiguousarray(rs_matrix.reconstruct_rows(k, m, present, tuple(want)))
        rebuilt = self._native.rs_apply(survivors, coeffs)  # [len(want), B*S]
        w = len(want)
        digests_np = None
        if with_digests:
            # [W, B*S] -> [W*B, S] chunk rows (row-major view), one hash call.
            digests_np = self._digests(rebuilt.reshape(w * b, s)).reshape(w, b, 32)
        out = []
        for bi in range(b):
            chunks = [rebuilt[wi, bi * s : (bi + 1) * s].tobytes() for wi in range(w)]
            digs = (
                [digests_np[wi, bi].tobytes() for wi in range(w)]
                if digests_np is not None
                else None
            )
            out.append((chunks, digs))
        return out


_RECON_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def bucket_batch(n: int) -> int:
    """Pad a batch count to a small fixed set of sizes so each (pattern,
    geometry) costs at most len(_RECON_BUCKETS) XLA compilations."""
    for b in _RECON_BUCKETS:
        if n <= b:
            return b
    return _RECON_BUCKETS[-1]


def run_device_reconstruct(
    pipe,
    rows_batch: list[list[bytes | None]],
    k: int,
    want: tuple[int, ...],
    surv: list[int],
    chunk_size: int,
    with_digests: bool,
) -> list[tuple[list[bytes], list[bytes] | None]]:
    """Assemble a uniform rows_batch into one padded [B, K, S] device
    reconstruct program and unpack per-block results (shared by DeviceCodec
    and the batching codec -- the served decode/heal path)."""
    b_real = len(rows_batch)
    b_pad = max(bucket_batch(b_real), b_real)  # never allocate under b_real
    present = tuple(r is not None for r in rows_batch[0])
    arr = np.zeros((b_pad, k, chunk_size), dtype=np.uint8)
    for bi, rows in enumerate(rows_batch):
        for ki, j in enumerate(surv):
            arr[bi, ki] = np.frombuffer(rows[j], dtype=np.uint8)  # type: ignore[arg-type]
    rebuilt, digests = pipe.reconstruct(arr, present, tuple(want), with_digests=with_digests)
    rebuilt_np = np.asarray(rebuilt)
    digests_np = np.asarray(digests) if with_digests else None
    return [
        (
            [rebuilt_np[bi, wi].tobytes() for wi in range(len(want))],
            (
                [digests_np[bi, wi].tobytes() for wi in range(len(want))]
                if digests_np is not None
                else None
            ),
        )
        for bi in range(b_real)
    ]


def uniform_recon_plan(
    rows_batch: list[list[bytes | None]], k: int
) -> tuple[tuple[bool, ...], list[int], int] | None:
    """Device-eligibility check for a batched reconstruct.

    Returns (present mask, first-K surviving row indices, chunk size) when
    every block in the batch lost the same shards and all surviving chunks
    share one length -- the shape a single [B, K, S] device program needs.
    None means the batch is irregular (mixed tails/patterns): host path.
    """
    present = tuple(r is not None for r in rows_batch[0])
    if sum(present) < k:
        return None
    sizes: set[int] = set()
    for rows in rows_batch:
        if tuple(r is not None for r in rows) != present:
            return None
        sizes.update(len(r) for r in rows if r is not None)
    if len(sizes) != 1:
        return None
    surv = [i for i, p in enumerate(present) if p][:k]
    return present, surv, sizes.pop()


class DeviceCodec(BlockCodec):
    """JAX device codec: one fused encode+hash program per call.

    Blocks in one call are padded to the longest shard size and batched into
    a single [B, K, S] tensor, so a large PutObject or heal already amortizes
    transfer/launch across its own blocks. Cross-request amortization is the
    batching scheduler's job (parallel/batching.py).
    """

    def __init__(self):
        self._host = HostCodec()
        self._pipelines: dict[tuple[int, int], object] = {}

    def _pipe(self, k: int, m: int):
        from ..models.pipeline import ErasurePipeline, Geometry

        key = (k, m)
        if key not in self._pipelines:
            self._pipelines[key] = ErasurePipeline(Geometry(k, m))
        return self._pipelines[key]

    def encode(self, blocks, k, m):
        from ..ops import rs as rs_dev

        if not blocks:
            return []
        sizes = [rs_matrix.shard_size(len(b), k) for b in blocks]
        s_max = max(sizes)
        batch = np.zeros((len(blocks), k, s_max), dtype=np.uint8)
        for i, block in enumerate(blocks):
            batch[i, :, : sizes[i]] = _split_block(block, k)
        codec = rs_dev.RSCodec(k, m)
        all_shards = np.asarray(codec.encode_all(batch))  # [B, K+M, S]
        out = []
        for i in range(len(blocks)):
            s = sizes[i]
            shards_i = all_shards[i, :, :s]
            # Padded-batch digests are only valid when every block shares the
            # padded length; hash at true length instead, via the host
            # codec's kernel (AVX2 when built -- the numpy oracle here would
            # silently cost ~10x on every mixed-size device batch).
            digests = self._host._digests(np.ascontiguousarray(shards_i))
            out.append(
                (
                    [shards_i[j].tobytes() for j in range(k + m)],
                    [digests[j].tobytes() for j in range(k + m)],
                )
            )
        return out

    def reconstruct(self, shards, k, m, want):
        return self._host.reconstruct(shards, k, m, want)

    def reconstruct_batch(self, rows_batch, k, m, want, with_digests=False):
        """Uniform multi-block rebuilds run as one device program (the served
        decode/heal path, cmd/erasure-lowlevel-heal.go:31); singles and
        irregular batches take the low-latency host codec."""
        plan = uniform_recon_plan(rows_batch, k) if len(rows_batch) > 1 else None
        if plan is None:
            return super().reconstruct_batch(rows_batch, k, m, want, with_digests)
        _, surv, s = plan
        return run_device_reconstruct(
            self._pipe(k, m), rows_batch, k, tuple(want), surv, s, with_digests
        )


_default: BlockCodec | None = None


def default_codec() -> BlockCodec:
    """Process-wide codec. Host for now; the server runtime installs the
    batching device codec at startup (see parallel/batching.py)."""
    global _default
    if _default is None:
        _default = HostCodec()
    return _default


def set_default_codec(codec: BlockCodec) -> None:
    global _default
    _default = codec
