"""Disk cache: SSD read-cache interposed before the object layer.

Role of the reference's CacheObjectLayer (cmd/disk-cache.go:82,
disk-cache-backend.go, format-disk-cache.go): GETs are served from local
cache drives once an object has been requested `after` times; cached
entries carry their own metadata (`cache.json` analogue) and are validated
against the backend's ETag when the backend is online, served stale when it
is offline; an LRU garbage collector trims the cache between high/low
watermarks; PUT/DELETE invalidate. Objects are spread across cache drives
by name hash (disk-cache.go consistent drive pick).

TPU framing: the cache is pure host-side IO — it exists to keep hot GETs
off the erasure decode path entirely (no device work at all on a hit).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time

from ..utils import errors
from .types import GetObjectOptions, ObjectInfo
from ..control.sanitizer import san_lock, san_rlock

CACHE_DATA = "part.1"
CACHE_META = "cache.json"
CACHE_ENV_DRIVES = "MTPU_CACHE_DRIVES"
CACHE_ENV_AFTER = "MTPU_CACHE_AFTER"
CACHE_ENV_QUOTA = "MTPU_CACHE_QUOTA"
CACHE_ENV_EXCLUDE = "MTPU_CACHE_EXCLUDE"


class CacheConfig:
    """cache subsystem config (internal/config/cache equivalent)."""

    def __init__(
        self,
        drives: list[str],
        after: int = 0,
        quota_bytes: int = 0,
        watermark_low: float = 0.7,
        watermark_high: float = 0.8,
        exclude: list[str] | None = None,
    ):
        self.drives = drives
        # Cache an object only after it was requested `after` times
        # (MINIO_CACHE_AFTER); 0 = first GET caches.
        self.after = after
        # Hard byte budget per cache drive (stands in for the reference's
        # percentage-of-filesystem quota, which needs statvfs of a dedicated
        # cache disk; a byte budget is exact for shared test filesystems).
        self.quota_bytes = quota_bytes or 1 << 30
        self.watermark_low = watermark_low
        self.watermark_high = watermark_high
        self.exclude = exclude or []

    @classmethod
    def from_env(cls, env=os.environ) -> "CacheConfig | None":
        raw = env.get(CACHE_ENV_DRIVES, "")
        if not raw:
            return None
        return cls(
            drives=[d for d in raw.split(",") if d],
            after=int(env.get(CACHE_ENV_AFTER, "0") or 0),
            quota_bytes=int(env.get(CACHE_ENV_QUOTA, "0") or 0),
            exclude=[p for p in env.get(CACHE_ENV_EXCLUDE, "").split(",") if p],
        )


class _CacheDrive:
    """One cache directory: entries + usage accounting + LRU GC."""

    def __init__(self, root: str, cfg: CacheConfig):
        self.root = root
        self.cfg = cfg
        self._lock = san_lock("_CacheDrive._lock")
        os.makedirs(root, exist_ok=True)
        # Format marker (format-disk-cache.go role): refuse directories that
        # belong to a different subsystem.
        marker = os.path.join(root, "format.cache.json")
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                json.dump({"version": 1, "format": "cache"}, f)

    def _entry_dir(self, bucket: str, obj: str, rng: str = "") -> str:
        key = f"{bucket}/{obj}"
        h = hashlib.sha256(key.encode()).hexdigest()
        return os.path.join(self.root, h[:2], h + (("_" + rng) if rng else ""))

    # -- read ---------------------------------------------------------------

    def lookup(self, bucket: str, obj: str, rng: str = "") -> tuple[dict, bytes] | None:
        d = self._entry_dir(bucket, obj, rng)
        try:
            with open(os.path.join(d, CACHE_META)) as f:
                meta = json.load(f)
            with open(os.path.join(d, CACHE_DATA), "rb") as f:
                data = f.read()
        except (OSError, ValueError):
            return None
        meta["atime"] = time.time()
        meta["hits"] = meta.get("hits", 0) + 1
        self._write_meta(d, meta)
        return meta, data

    def peek(self, bucket: str, obj: str, rng: str = "") -> dict | None:
        try:
            with open(os.path.join(self._entry_dir(bucket, obj, rng), CACHE_META)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_meta(self, d: str, meta: dict) -> None:
        tmp = os.path.join(d, CACHE_META + ".tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(meta, f)
            # mtpulint: disable=unsynced-commit -- cache entries are
            # best-effort and rebuilt from the backend on miss; a torn meta
            # file just reads as a miss, so an fsync here buys nothing.
            os.replace(tmp, os.path.join(d, CACHE_META))
        except OSError:
            pass

    # -- write --------------------------------------------------------------

    def save(self, bucket: str, obj: str, oi: ObjectInfo, data: bytes, rng: str = "") -> None:
        if len(data) > self.cfg.quota_bytes:
            return
        d = self._entry_dir(bucket, obj, rng)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, CACHE_DATA), "wb") as f:
            f.write(data)
        self._write_meta(
            d,
            {
                "bucket": bucket,
                "object": obj,
                "range": rng,
                "etag": oi.etag,
                "version_id": oi.version_id,
                "mod_time": oi.mod_time,
                "size": len(data),
                "content_type": oi.content_type,
                "user_defined": dict(oi.user_defined),
                # Transform state (SSE-S3/compression markers) MUST survive:
                # the handler's decrypt/decompress path keys off internal.
                "internal": dict(oi.internal),
                "actual_size": oi.actual_size,
                "cached_at": time.time(),
                "atime": time.time(),
                "hits": 1,
            },
        )
        self.maybe_gc()

    def invalidate(self, bucket: str, obj: str) -> None:
        base = self._entry_dir(bucket, obj)
        parent = os.path.dirname(base)
        prefix = os.path.basename(base)
        try:
            for name in os.listdir(parent):
                if name == prefix or name.startswith(prefix + "_"):
                    shutil.rmtree(os.path.join(parent, name), ignore_errors=True)
        except OSError:
            pass

    # -- GC (disk-cache-backend.go LRU watermarks) ---------------------------

    def usage(self) -> int:
        total = 0
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in filenames:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass
        return total

    def maybe_gc(self) -> None:
        with self._lock:
            if self.usage() <= self.cfg.quota_bytes * self.cfg.watermark_high:
                return
            entries = []
            for sub in os.listdir(self.root):
                subp = os.path.join(self.root, sub)
                if not os.path.isdir(subp):
                    continue
                for ent in os.listdir(subp):
                    d = os.path.join(subp, ent)
                    try:
                        with open(os.path.join(d, CACHE_META)) as f:
                            meta = json.load(f)
                        entries.append((meta.get("atime", 0), meta.get("size", 0), d))
                    except (OSError, ValueError):
                        shutil.rmtree(d, ignore_errors=True)
            entries.sort()  # least-recently-used first
            used = sum(size for _, size, _ in entries)
            target = self.cfg.quota_bytes * self.cfg.watermark_low
            for _atime, size, d in entries:
                if used <= target:
                    break
                shutil.rmtree(d, ignore_errors=True)
                used -= size


class CacheObjectLayer:
    """Transparent read-cache wrapper around an ObjectLayer
    (cmd/disk-cache.go CacheObjectLayer; interposed at the handler layer in
    the reference, object-handlers.go:1722-1724)."""

    def __init__(self, backend, cfg: CacheConfig):
        self.backend = backend
        self.cfg = cfg
        self.drives = [_CacheDrive(d, cfg) for d in cfg.drives]
        # Pending-cache hit counters for the `after` threshold.
        self._hit_counts: dict[str, int] = {}
        self._hits = 0
        self._misses = 0

    # Everything not overridden passes straight through to the backend.
    def __getattr__(self, name):
        return getattr(self.backend, name)

    # -- drive pick ----------------------------------------------------------

    def _drive(self, bucket: str, obj: str) -> _CacheDrive | None:
        if not self.drives:
            return None
        key = hashlib.sha256(f"{bucket}/{obj}".encode()).digest()
        return self.drives[int.from_bytes(key[:4], "big") % len(self.drives)]

    def _excluded(self, bucket: str, obj: str) -> bool:
        target = f"{bucket}/{obj}"
        for pat in self.cfg.exclude:
            pat = pat.strip("/")
            if pat and (target.startswith(pat) or bucket == pat):
                return True
        return False

    def _should_cache(self, bucket: str, obj: str) -> bool:
        if self.cfg.after <= 0:
            return True
        key = f"{bucket}/{obj}"
        n = self._hit_counts.get(key, 0) + 1
        self._hit_counts[key] = n
        if n >= self.cfg.after:
            del self._hit_counts[key]
            return True
        return False

    # -- the cached read path -------------------------------------------------

    def get_object(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ):
        opts = opts or GetObjectOptions()
        drive = self._drive(bucket, object_name)
        # Versioned reads and excluded prefixes bypass the cache entirely
        # (the reference caches only latest-version reads).
        if (
            drive is None
            or getattr(opts, "version_id", "") != ""
            or self._excluded(bucket, object_name)
        ):
            return self.backend.get_object(bucket, object_name, opts, offset, length)

        rng = f"{offset}-{length}" if (offset, length) != (0, -1) else ""
        cached = drive.lookup(bucket, object_name, rng) or (
            # A whole-object entry can serve any range.
            drive.lookup(bucket, object_name) if rng else None
        )

        # Validate against the backend when it answers; serve stale when the
        # whole backend is unreachable (disk-cache.go backend-down serving).
        try:
            info = self.backend.get_object_info(bucket, object_name, opts)
            backend_online = True
        except (errors.ObjectNotFound, errors.VersionNotFound):
            if drive is not None:
                drive.invalidate(bucket, object_name)
            raise
        except errors.StorageError:
            backend_online = False
            info = None

        if cached is not None:
            meta, data = cached
            if not backend_online or (info is not None and info.etag == meta["etag"]):
                self._hits += 1
                oi = ObjectInfo(
                    bucket=bucket,
                    name=object_name,
                    etag=meta["etag"],
                    version_id=meta.get("version_id", ""),
                    mod_time=meta["mod_time"],
                    size=info.size if info is not None else meta["size"],
                    content_type=meta.get("content_type", "application/octet-stream"),
                    user_defined=dict(meta.get("user_defined", {})),
                    internal=dict(meta.get("internal", {})),
                    actual_size=meta.get("actual_size"),
                )
                if meta.get("range", ""):
                    return oi, data
                if rng:
                    end = len(data) if length < 0 else min(offset + length, len(data))
                    return oi, data[offset:end]
                return oi, data
            drive.invalidate(bucket, object_name)  # stale entry

        self._misses += 1
        oi, data = self.backend.get_object(bucket, object_name, opts, offset, length)
        if self._should_cache(bucket, object_name):
            try:
                drive.save(bucket, object_name, oi, data, rng)
            except OSError:
                pass  # cache write failure never fails the read
        return oi, data

    # -- invalidating writes ---------------------------------------------------

    def _invalidate(self, bucket: str, object_name: str) -> None:
        d = self._drive(bucket, object_name)
        if d is not None:
            d.invalidate(bucket, object_name)

    def put_object(self, bucket, object_name, data, opts=None):
        self._invalidate(bucket, object_name)
        return self.backend.put_object(bucket, object_name, data, opts)

    def delete_object(self, bucket, object_name, opts=None):
        self._invalidate(bucket, object_name)
        return self.backend.delete_object(bucket, object_name, opts)

    def put_object_metadata(self, bucket, object_name, version_id="", updates=None, removes=None):
        self._invalidate(bucket, object_name)
        return self.backend.put_object_metadata(
            bucket, object_name, version_id, updates, removes
        )

    def complete_multipart_upload(self, bucket, object_name, upload_id, parts):
        self._invalidate(bucket, object_name)
        return self.backend.complete_multipart_upload(bucket, object_name, upload_id, parts)

    def delete_objects(self, bucket, items):
        for item in items:
            name = item[0] if isinstance(item, (tuple, list)) else item
            self._invalidate(bucket, name)
        return self.backend.delete_objects(bucket, items)

    def delete_bucket(self, bucket: str, force: bool = False):
        out = self.backend.delete_bucket(bucket, force)
        for d in self.drives:
            # Bucket-wide invalidation: entries are keyed by name hash, so a
            # full sweep is required; GC metadata carries the bucket name.
            for sub in list(os.listdir(d.root)):
                subp = os.path.join(d.root, sub)
                if not os.path.isdir(subp):
                    continue
                for ent in list(os.listdir(subp)):
                    ed = os.path.join(subp, ent)
                    try:
                        with open(os.path.join(ed, CACHE_META)) as f:
                            if json.load(f).get("bucket") == bucket:
                                shutil.rmtree(ed, ignore_errors=True)
                    except (OSError, ValueError):
                        continue
        return out

    # -- stats (cache metrics surface) ----------------------------------------

    def stats(self) -> dict:
        return {
            "hits": self._hits,
            "misses": self._misses,
            "drives": [
                {"path": d.root, "usage": d.usage(), "quota": self.cfg.quota_bytes}
                for d in self.drives
            ],
        }
