"""Erasure object layer: one set of N drives storing K+M-coded objects.

Role of the reference's erasureObjects (cmd/erasure-object.go, erasure.go):
the object semantics above per-drive storage -- quorum writes with atomic
rename commit (putObject :752-1021), quorum metadata reads + shard decode
(getObjectWithFileInfo :223-357), versioned deletes with markers, and
decode+re-encode healing (erasure-healing.go:257).

Differences from the reference worth noting (TPU-first design):
  * Erasure math + bitrot hashing run through a BlockCodec (object/codec.py)
    so whole objects/heals hit the device as one batched program instead of
    a per-block library call.
  * Shard files are read/written whole per part on the host side -- the
    interleaved bitrot frames are parsed in memory (block streaming with
    bounded memory is the multipart layer's job).
"""

from __future__ import annotations

import concurrent.futures as _cf
import contextvars
import hashlib
import io
import os
import queue as _queue
import sys
import threading
import time
import uuid
from collections import deque
from typing import Iterator

from ..chaos import crash
from ..control import tracing
from ..control.degrade import GLOBAL_DEGRADE
from ..control.perf import GLOBAL_PERF
from ..control.profiler import COPIED, GLOBAL_PROFILER, MOVED
from ..ops import bitrot as bitrot_mod
from ..utils import deadline
from ..storage.interface import StorageAPI
from ..storage.types import ErasureInfo, FileInfo, ObjectPartInfo, now
from ..storage.xlmeta import SMALL_FILE_THRESHOLD
from ..utils import errors
from ..utils import bufpool
from ..utils import iopool
from ..utils.hashes import hash_order
from . import codec as codec_mod
from . import metadata as meta_mod
from .types import (
    BucketInfo,
    DeleteObjectOptions,
    GetObjectOptions,
    HealResultItem,
    ObjectInfo,
    PutObjectOptions,
)

BLOCK_SIZE = 1 << 20  # blockSizeV2 (cmd/object-api-common.go:40)
META_BUCKET = ".minio_tpu.sys"
DIGEST_LEN = 32
# Blocks per codec call on the streaming path: the put/get working set is
# O(GROUP_BLOCKS x BLOCK_SIZE), not O(objectSize), while each group is still
# a device-batchable [G, K, S] tensor (the reference streams one 1 MiB block
# at a time, erasure-encode.go:73-109; grouping keeps the TPU batch win).
GROUP_BLOCKS = 16

# Hedged-read policy: a shard read that has run longer than
# max(HEDGE_FLOOR, HEDGE_MULT x median completed duration) is presumed
# straggling and a hedge read is armed on the best unread slot -- the
# any-k-of-n freedom of the erasure code turned into tail-latency insurance
# (the regenerating-codes reading discipline, arXiv:1412.3022). The floor
# keeps microsecond-fast local windows from hedging on scheduler noise.
HEDGE_FLOOR = 0.05
HEDGE_MULT = 3.0
_HEDGE_POLL = 0.01  # gather loop wakeup for hedge decisions, seconds


def _rank_read_slots(by_shard: list, k: int) -> list[int]:
    """Order online shard slots for reading: ALL data slots before any
    parity slot, then lowest read_file latency EWMA (MeteredDrive's
    tracker, surfaced through the drive stack), stable by slot index.
    Slots whose drive is missing or breaker-gated offline are excluded
    entirely.

    The class must dominate the EWMA: every parity primary costs a row
    reconstruct (the decode stage + a COPIED hop a healthy read otherwise
    never pays), so a few-ms EWMA edge never buys a parity slot into the
    quorum. A genuinely slow data drive is the hedge machinery's job --
    its spare (EWMA-ranked below) decodes only when actually needed."""
    scored: list[tuple[int, float, int]] = []
    for j, d in enumerate(by_shard):
        if d is None or not d.is_online():
            continue
        ewma = 0.0
        lat_fn = getattr(d, "api_latencies", None)
        if lat_fn is not None:
            try:
                lat = lat_fn()
                row = lat.get("read_file_into") or lat.get("read_file")
                if row:
                    ewma = float(row["ewma_ms"])
            except (KeyError, TypeError, ValueError):  # ranking is advisory
                ewma = 0.0
        scored.append((0 if j < k else 1, ewma, j))
    scored.sort()
    return [j for _, _, j in scored]


# -- zero-copy window pipeline -------------------------------------------------
#
# The PUT path stages data in WINDOW_BYTES (= one codec group) windows:
# buffer-like payloads are sliced as memoryviews in place, reader payloads
# land ONCE into pooled bytearrays (utils/bufpool.py) via readinto, and
# every downstream hop -- block split, codec staging, shard fan-out --
# operates on views over that storage. The old _iter_blocks staging loop
# re-materialized every block as fresh bytes (the erasure-stage `copied`
# column this PR flips to `moved`).

WINDOW_BYTES = GROUP_BLOCKS * BLOCK_SIZE


def _quiet_release(*views) -> None:
    """Best-effort memoryview invalidation before pooled storage recycles.

    A stale view over a recycled buffer silently reads another request's
    bytes (bufsan: view-outlives-buffer), so owners invalidate their
    exports at release. A view something re-exported (a live
    np.frombuffer, a nested memoryview) refuses release() -- that one is
    left alive for the runtime sanitizer to flag rather than crashing
    the data path."""
    for v in views:
        if isinstance(v, memoryview):
            try:
                v.release()
            except ValueError:
                pass


class _Window:
    """One pipeline window: a memoryview over the caller's buffer or over a
    pooled bytearray; release() recycles the latter."""

    __slots__ = ("view", "_pb", "_blocks")

    def __init__(self, view: memoryview, pb=None):
        self.view = view
        self._pb = pb
        self._blocks: list[memoryview] | None = None

    def __len__(self) -> int:
        return len(self.view)

    def blocks(self) -> list[memoryview]:
        v = self.view
        out = [v[off : off + BLOCK_SIZE] for off in range(0, len(v), BLOCK_SIZE)]
        if self._pb is not None:
            self._blocks = out
        return out

    def release(self) -> None:
        if self._pb is not None:
            # Invalidate this window's exports BEFORE the storage returns
            # to the pool -- the encoder copied what it needed, so a view
            # that survives past here is a lifetime bug, not a reader.
            _quiet_release(*(self._blocks or ()), self.view)
            self._blocks = None
            self._pb.release()
            self._pb = None


def _uniform_runs(blocks: list) -> list[list]:
    """Split a window's blocks into uniform-size runs so every run takes the
    codec's native scatter path (a short tail block becomes its own
    single-block group; the digest stream is per-block, so grouping never
    changes the etag)."""
    if len(blocks) > 1 and len(blocks[-1]) != len(blocks[0]):
        return [blocks[:-1], blocks[-1:]]
    return [blocks]


def _fill_window(reader, view: memoryview) -> int:
    """Fill `view` from the reader; a short count means EOF.

    readinto readers land payload straight into the window (the reader
    records its own landing hop: socket-read / sigv4-chunk-parse); the
    legacy read() fallback copies each chunk in and says so."""
    n = len(view)
    pos = 0
    ri = getattr(reader, "readinto", None)
    if ri is not None:
        while pos < n:
            got = ri(view[pos:])
            if not got:
                break
            pos += got
        if pos:
            GLOBAL_PROFILER.copy.record("erasure-stage", MOVED, pos)
        return pos
    while pos < n:
        chunk = reader.read(n - pos)
        if not chunk:
            break
        view[pos : pos + len(chunk)] = chunk
        pos += len(chunk)
    if pos:
        GLOBAL_PROFILER.copy.record("erasure-stage", COPIED, pos)
    return pos


def _buffer_windows(data) -> Iterator[_Window]:
    """Windows over an in-memory payload: pure views, no staging at all."""
    mv = memoryview(data)
    for off in range(0, len(mv), WINDOW_BYTES):
        win = mv[off : off + WINDOW_BYTES]
        GLOBAL_PROFILER.copy.record("erasure-stage", MOVED, len(win))
        yield _Window(win)


def _stream_windows(reader, pool, pb, filled: int) -> Iterator[_Window]:
    """Windows over a reader, starting from an already-filled first buffer.

    Ownership: each yielded _Window owns its pooled buffer (consumer
    releases); a buffer the generator still holds when it exits -- EOF or
    close() -- is released here, so abandoned PUTs leak nothing. The fill
    view is named so a reader failure can invalidate it before the
    finally recycles the storage (the traceback pins this frame)."""
    mv = None
    try:
        while True:
            win = _Window(pb.view(0, filled), pb)
            pb = None
            yield win
            if filled < WINDOW_BYTES:
                return  # EOF landed inside the last fill
            pb = pool.acquire()
            mv = pb.view()
            filled = _fill_window(reader, mv)
            _quiet_release(mv)
            mv = None
            if filled == 0:
                return  # payload was an exact window multiple
    finally:
        if pb is not None:
            _quiet_release(mv)
            if sys.exc_info()[0] is not None:
                # Reader raised mid-fill: its traceback may pin slices of
                # the fill view in frames this code cannot reach.
                pb.discard()
            else:
                pb.release()


class _ReadaheadWindows:
    """Pipelined PUT read stage: a 'put-stager' thread fills window g+1
    while the caller encodes / fans out window g (the write mirror of the
    GET readahead). Depth = MTPU_PUT_READAHEAD windows in flight."""

    def __init__(self, src, depth: int):
        self._src = src
        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, name="put-stager", daemon=True)
        self._t.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            for win in self._src:
                if not self._put(("win", win)):
                    win.release()  # consumer gone; recycle, stop reading
                    return
        # mtpulint: disable=swallowed-except -- stored, re-raised at __next__
        except BaseException as e:  # noqa: BLE001 - surfaced to the PUT loop
            self._put(("err", e))
            return
        self._put(("end", None))

    def __iter__(self) -> "_ReadaheadWindows":
        return self

    def __next__(self) -> _Window:
        kind, val = self._q.get()
        if kind == "win":
            return val
        if kind == "err":
            raise val
        raise StopIteration

    def close(self) -> None:
        """Stop the stager, recycle queued windows, join the thread."""
        self._stop.set()
        try:
            while True:
                kind, val = self._q.get_nowait()
                if kind == "win":
                    val.release()
        except _queue.Empty:
            pass
        self._t.join(timeout=10)
        closer = getattr(self._src, "close", None)
        if closer is not None:
            closer()


def _wrap_readahead(src):
    depth = int(os.environ.get("MTPU_PUT_READAHEAD", "1"))
    return _ReadaheadWindows(src, depth) if depth > 0 else src


class _WindowBufs:
    """Pooled-buffer registry for one GET window.

    Shard reads land in pooled buffers whose views outlive the reading
    thread (hedged stragglers finish after the gather loop exits); the
    registry owns every buffer a window's reads produce and releases them
    all once the window's chunks have been consumed. add() after close()
    returns False -- a straggler that completes late still owns its
    buffer and must recycle it after dropping its own views (its result
    is discarded anyway)."""

    __slots__ = ("_lock", "_bufs", "_closed")

    def __init__(self):
        self._lock = threading.Lock()
        self._bufs: list = []
        self._closed = False

    def add(self, pb) -> bool:
        with self._lock:
            if not self._closed:
                self._bufs.append(pb)
                return True
        return False

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            bufs, self._bufs = self._bufs, []
        for pb in bufs:
            # The stream contract lets the consumer keep yielded chunk
            # views past the stream itself: a buffer still exported here
            # is demoted to a discard (allocator-owned, never repooled)
            # instead of recycling under the holder's feet.
            pb.release_or_discard()


def _block_pieces(rows, chunk: int, s: int, e: int):
    """Yield row views covering block bytes [s, e) -- the zero-copy
    replacement for _join_block_rows on the streaming path. Block byte x
    lives in data row x // chunk at offset x % chunk (shard rows are
    uniformly `chunk` bytes; the tail row's padding sits past e)."""
    j0, j1 = s // chunk, (e - 1) // chunk
    for j in range(j0, j1 + 1):
        a = s - j * chunk if j == j0 else 0
        b = e - j * chunk if j == j1 else chunk
        r = rows[j]
        yield r if (a == 0 and b == len(r)) else r[a:b]


class _GetStager:
    """Pipelined GET read stage: a 'get-stager' thread runs window g+1's
    shard reads + bitrot verify while the caller writes window g to the
    response (the read twin of _ReadaheadWindows). Items are
    (chunks, close) units; close() recycles the window's pooled buffers
    and MUST be called by whoever consumes (or drops) the unit.

    The source generator runs under a copy of the caller's context:
    tracing spans stay parented to the request and the deadline budget
    keeps applying inside the stager thread."""

    def __init__(self, src, depth: int):
        self._src = src
        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        ctx = contextvars.copy_context()
        self._t = threading.Thread(
            target=ctx.run, args=(self._run,), name="get-stager", daemon=True
        )
        self._t.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            for unit in self._src:
                if not self._put(("unit", unit)):
                    unit[1]()  # consumer gone; recycle the window's buffers
                    return
        # mtpulint: disable=swallowed-except -- stored, re-raised at __next__
        except BaseException as e:  # noqa: BLE001 - surfaced to the GET loop
            self._put(("err", e))
            return
        self._put(("end", None))

    def __iter__(self) -> "_GetStager":
        return self

    def __next__(self):
        kind, val = self._q.get()
        if kind == "unit":
            return val
        if kind == "err":
            raise val
        raise StopIteration

    def close(self) -> None:
        """Stop the stager, recycle queued windows, join the thread."""
        self._stop.set()
        try:
            while True:
                kind, val = self._q.get_nowait()
                if kind == "unit":
                    val[1]()
        except _queue.Empty:
            pass
        self._t.join(timeout=10)
        closer = getattr(self._src, "close", None)
        if closer is not None:
            closer()


def data_windows(data) -> "Iterator[_Window]":
    """bytes-like | .read()/.readinto() stream -> window iterator (the
    multipart entry point; put_object opens the stream itself so it can
    peek the first window for the inline-threshold decision)."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        return _buffer_windows(data)
    if hasattr(data, "read") or hasattr(data, "readinto"):
        pool = bufpool.window_pool()
        pb = pool.acquire()
        mv = pb.view()
        try:
            filled = _fill_window(data, mv)
        except BaseException:
            # The propagating traceback pins the reader's frames, which may
            # hold slices of `mv` this code cannot reach -- discard the
            # storage instead of recycling it (bufsan: view-outlives-buffer).
            _quiet_release(mv)
            pb.discard()
            raise
        _quiet_release(mv)
        return _wrap_readahead(_stream_windows(data, pool, pb, filled))
    raise TypeError(f"put data must be bytes or a reader, got {type(data)!r}")


class _PipelinedMD5:
    """ETag MD5 computed on a side thread, overlapping the encode+hash C
    calls (both release the GIL): on multi-core hosts the ~0.6 GiB/s MD5
    disappears from the PUT critical path; the reference gets the same
    overlap from its io.Pipe'd hash.Reader stage (object-api-utils.go)."""

    def __init__(self):
        import queue as _q

        self._h = hashlib.md5()
        self._q: "_q.Queue[bytes | None]" = _q.Queue(maxsize=32)
        self._error: BaseException | None = None
        self._t = threading.Thread(target=self._run, daemon=True, name="etag-md5")
        self._t.start()

    def _run(self) -> None:
        while True:
            b = self._q.get()
            if b is None:
                return
            try:
                self._h.update(b)
            # mtpulint: disable=swallowed-except -- stored, re-raised below
            except BaseException as e:  # noqa: BLE001 - surfaced to the PUT
                # Keep draining so the producer never blocks on a full
                # queue; the error re-raises at the next update/hexdigest
                # (a dead worker silently truncating the ETag would persist
                # a wrong digest with a 200).
                self._error = e

    def update(self, block: bytes) -> None:
        if self._error is not None:
            raise self._error
        self._q.put(block)

    def shutdown(self) -> None:
        """Stop the worker without a digest (failed put)."""
        if self._t.is_alive():
            self._q.put(None)
            self._t.join()

    def hexdigest(self) -> str:
        self.shutdown()
        if self._error is not None:
            raise self._error
        return self._h.hexdigest()


def make_etag_md5():
    """Pipelined MD5 when a second core can actually run it (affinity-aware);
    plain hashlib on one core where the handoff queue is pure overhead."""
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    return _PipelinedMD5() if cores > 1 else hashlib.md5()


def _etag_update(h, view) -> None:
    """Feed a window block to the etag hasher. The pipelined hasher's queue
    holds blocks PAST the window's release, so it gets a private copy; the
    synchronous hasher consumes the view in place."""
    if isinstance(h, _PipelinedMD5):
        h.update(bytes(view))  # mtpulint: disable=hot-path-copy -- hashed on a side thread after the pooled window is recycled
    else:
        h.update(view)


def use_fast_etag(opts) -> bool:
    """Streaming PUTs default to the digest-stream etag (free: the bitrot
    digests are already computed per group). MTPU_FAST_ETAG=0 restores the
    content-md5 etag; a client-declared Content-MD5 (opts.etag) always
    wins so the header contract stays exact."""
    return (
        not opts.etag
        and not opts.bitrot_algorithm
        and os.environ.get("MTPU_FAST_ETAG", "1") != "0"
    )


def fast_etag(data, k: int, m: int, codec=None) -> str:
    """Expected streaming-path etag for `data` (tests and tooling compute
    it independently): md5 over the concatenated per-block data-row bitrot
    digests, in block order -- the same stream the PUT pipeline hashes for
    free. Grouping never affects it (the stream is per-block), and every
    codec produces bit-identical digests, so the etag is deterministic."""
    codec = codec or codec_mod.default_codec()
    h = hashlib.md5()
    mv = memoryview(data)
    for off in range(0, len(mv), BLOCK_SIZE):
        h.update(codec.encode_group([mv[off : off + BLOCK_SIZE]], k, m).digest_stream)
    return h.hexdigest()


class ShardStageWriter:
    """Grouped-encode + per-drive staged shard appends with quorum tracking.

    The streaming-write engine shared by put_object and multipart part
    uploads: each GROUP_BLOCKS batch of 1 MiB blocks goes through the codec
    as one scatter-encode call, and each drive gets its whole group frame as
    ONE gathered append (append_iov) submitted on that drive's I/O lane
    (utils/iopool.py) -- writes overlap the next group's read+encode, with
    per-drive FIFO keeping the staged file's append order. Failed drives are
    dropped as their writes are harvested; the caller checks `alive()`
    against its write quorum and MUST call drain() (success) or abort()
    (failure) so no write is in flight when it commits or deletes tmp files.
    (The reference's parallelWriter + Encode loop, erasure-encode.go:29-109.)
    """

    def __init__(self, codec, disks, distribution, k: int, m: int, stage_path, algo=None):
        """stage_path(i) -> staged shard-file path under META_BUCKET.

        `algo`: a non-streaming BitrotAlgorithm writes the LEGACY whole-file
        layout (raw shard bytes + one running checksum per row,
        cmd/bitrot-whole.go:30); None/streaming writes interleaved frames.
        """
        self.codec = codec
        self.disks = disks
        self.distribution = distribution
        self.k, self.m = k, m
        self.stage_path = stage_path
        self.ok = [d is not None for d in disks]
        self.algo = algo if algo is not None and not algo.streaming else None
        self._hashers = (
            [self.algo.new() for _ in range(k + m)] if self.algo is not None else None
        )
        self._appended = False
        self._lanes = iopool.shard_writer_pool()
        self._pending: deque = deque()  # deque[list[(drive index, Future)]]
        # In-flight group bound: memory stays O(inflight x group frames)
        # while write g-1 overlaps encode g.
        self._inflight = max(1, int(os.environ.get("MTPU_PUT_INFLIGHT", "2")))

    def finalize(self) -> None:
        """Ensure staged shard files exist before commit. Appends create
        files on demand (open "ab"), so this only does IO for zero-byte
        payloads — which must still commit a real, empty shard file. The
        old eager create() cost every PUT a 16-task fan-out up front."""
        if self._appended:
            return

        def mk(i):
            if not self.ok[i]:
                return
            self.disks[i].create_file(META_BUCKET, self.stage_path(i), b"")

        for i, (_, e) in enumerate(meta_mod.parallel_map(mk, range(len(self.disks)))):
            if e is not None:
                self.ok[i] = False

    def _collect(self, futs) -> None:
        for i, f in futs:
            try:
                f.result()
            except Exception:  # mtpulint: disable=swallowed-except -- drive marked failed; the quorum check raises
                self.ok[i] = False

    def _reap(self) -> None:
        """Harvest groups whose writes have all landed, without blocking."""
        while self._pending and all(f.done() for _, f in self._pending[0]):
            self._collect(self._pending.popleft())

    def append_group(self, group: list) -> bytes | None:
        """Encode one uniform group and submit each drive's gathered append.

        Returns the group's data-row digest stream (the fast-etag input) on
        the streaming layout, None on the legacy whole-file layout. Writes
        are asynchronous: a drive failure surfaces in ok[] at the next
        harvest (or drain()), exactly like the reference's parallelWriter
        noticing a broken disk one buffer later."""
        if not group:
            return None
        # Stage marks feed the always-on perf ledger: "encode" is the codec
        # call, "shard-fanout" the blocking part of the staged appends -- the
        # two halves of where a streaming PUT's group time goes.
        with tracing.span("encode", "object", blocks=len(group)):
            if self._hashers is None:
                eg = self.codec.encode_group(group, self.k, self.m)
            else:
                # Whole-file layout: raw chunks, one running digest per row.
                encoded = self.codec.encode(group, self.k, self.m)
                row_frames = []
                for row in range(self.k + self.m):
                    chunks = [e[0][row] for e in encoded]
                    for c in chunks:
                        self._hashers[row].update(c)
                    row_frames.append(b"".join(chunks))  # mtpulint: disable=hot-path-copy -- legacy whole-file layout appends one contiguous frame
        self._appended = True

        if self._hashers is not None:
            def wr(i):
                if not self.ok[i]:
                    return
                row = self.distribution[i] - 1
                self.disks[i].append_file(META_BUCKET, self.stage_path(i), row_frames[row])

            GLOBAL_PROFILER.copy.record(
                "shard-fanout", MOVED, sum(len(f) for f in row_frames)
            )
            with tracing.span("shard-fanout", "object", drives=len(self.disks)):
                for i, (_, e) in enumerate(meta_mod.parallel_map(wr, range(len(self.disks)))):
                    if e is not None:
                        self.ok[i] = False
            return None

        # Copy-ledger hop: each drive receives its whole group frame as
        # iovec VIEWS over the encoder's buffer -- the fan-out moves bytes
        # without joining or re-staging them.
        GLOBAL_PROFILER.copy.record(
            "shard-fanout", MOVED, sum(eg.row_nbytes(r) for r in range(self.k + self.m))
        )
        self._reap()
        while len(self._pending) >= self._inflight:
            with tracing.span("shard-fanout", "object", drives=len(self.disks)):
                self._collect(self._pending.popleft())
        futs = []
        for i, d in enumerate(self.disks):
            if not self.ok[i]:
                continue
            row = self.distribution[i] - 1
            futs.append(
                (
                    i,
                    self._lanes.submit(
                        d.endpoint(), d.append_iov, META_BUCKET, self.stage_path(i), eg.iovecs[row]
                    ),
                )
            )
        self._pending.append(futs)
        return eg.digest_stream

    def drain(self) -> None:
        """Block until every in-flight group write has landed; ok[] is final
        after this returns. Callers drain before commit AND before deleting
        staged files (a late write racing a tmp cleanup would resurrect the
        file)."""
        if not self._pending:
            return
        with tracing.span("shard-fanout", "object", drives=len(self.disks)):
            while self._pending:
                self._collect(self._pending.popleft())

    def abort(self) -> None:
        self.drain()

    def alive(self) -> int:
        return sum(self.ok)

    def whole_checksums(self) -> list[bytes] | None:
        """Per-row whole-file digests (legacy layout only)."""
        if self._hashers is None:
            return None
        return [h.digest() for h in self._hashers]

_NS_LOCK_SINGLETON = None


def _process_ns_lock():
    """Shared per-process namespace lock (single-node default)."""
    global _NS_LOCK_SINGLETON
    if _NS_LOCK_SINGLETON is None:
        from ..dist.locks import NamespaceLock

        _NS_LOCK_SINGLETON = NamespaceLock()
    return _NS_LOCK_SINGLETON


def default_parity(drive_count: int) -> int:
    """Drive-count-based default parity (getDefaultParityBlocks,
    cmd/format-erasure.go:873)."""
    if drive_count == 1:
        return 0
    if drive_count <= 3:
        return 1
    if drive_count <= 5:
        return 2
    if drive_count <= 7:
        return 3
    return 4


def _join_block_rows(rows, k: int, need: int) -> bytes:
    """Join the first k shard rows into EXACTLY `need` bytes of block data.

    Shards pad the tail (k*chunk >= block length), so joining whole rows
    and slicing afterward re-copied every block; trimming the tail rows
    first makes the join itself produce the block."""
    pieces: list = []
    for j in range(k):
        r = rows[j]
        take = min(len(r), need)
        pieces.append(r if take == len(r) else memoryview(r)[:take])
        need -= take
        if need <= 0:
            break
    return b"".join(pieces)  # mtpulint: disable=hot-path-copy -- GET assembles the decoded block for the response


def _whole_layout(metas) -> bool:
    """Majority vote across drive metas on the whole-file-bitrot layout.

    The quorum FileInfo representative is an arbitrary matching drive, and
    erasure.checksums is per-drive (excluded from the quorum key) -- one
    drive with a lost or spurious checksums list must not flip the decoder
    for a healthy object."""
    votes = [bool(m.erasure.checksums) for m in metas if m is not None]
    return bool(votes) and sum(votes) * 2 > len(votes)


def _whole_sum_matches(meta: FileInfo, part_number: int, blob: bytes) -> bool:
    """Verify a raw whole-file-bitrot row blob against the per-part checksum
    in the drive's own metadata (cmd/bitrot-whole.go:62 wholeBitrotReader
    semantics). Shared by the GET and heal paths."""
    ent = next(
        (c for c in meta.erasure.checksums if c.get("part") == part_number), None
    )
    if ent is None:
        return False
    try:
        algo = bitrot_mod.BitrotAlgorithm(ent.get("algo", ""))
        want = bytes.fromhex(ent.get("hash", ""))
    except ValueError:
        return False
    return bitrot_mod.digest_of(blob, algo) == want


def _frame_shard(chunks: list[bytes], digests: list[bytes]) -> bytes:
    """Interleave digest||chunk frames (streaming bitrot file layout)."""
    parts: list[bytes] = []
    for d, c in zip(digests, chunks):
        parts.append(d)
        parts.append(c)
    return b"".join(parts)  # mtpulint: disable=hot-path-copy -- heal rebuilds a contiguous shard frame


def _parse_frames(
    blob: bytes, chunk_sizes: list[int]
) -> list[tuple[memoryview, memoryview]]:
    """Split a shard file image back into (digest, chunk) frames.

    Frames are zero-copy memoryview slices of the blob -- a GET window
    used to copy every digest+chunk out of the image before verifying;
    consumers (join / np.frombuffer / == bytes) all take buffers."""
    out = []
    pos = 0
    mv = memoryview(blob)
    for sz in chunk_sizes:
        d = mv[pos : pos + DIGEST_LEN]
        c = mv[pos + DIGEST_LEN : pos + DIGEST_LEN + sz]
        if len(d) != DIGEST_LEN or len(c) != sz:
            raise errors.FileCorrupt("short shard file")
        out.append((d, c))
        pos += DIGEST_LEN + sz
    return out


def _verify_frames(blob, chunk_sizes: list[int], parsed) -> list[bool]:
    """Bitrot-verify every frame of one shard row window.

    The uniform-size prefix (all blocks except a possible short tail) is ONE
    native C call straight over the raw image -- no Python slicing, pairs of
    chunks interleaved on the vector unit (native/minio_native.cpp
    hh256_verify_frames); the tail and the no-native fallback verify via the
    batched digest path."""
    from ..ops import native
    from ..ops.highwayhash import MAGIC_KEY

    n = len(chunk_sizes)
    if n == 0:
        return []
    same = n if n < 2 or chunk_sizes[-1] == chunk_sizes[0] else n - 1
    if native.verify_frames_available():
        flags = list(native.hh256_verify_frames(blob, chunk_sizes[0], same, MAGIC_KEY) != 0)
        for i in range(same, n):
            d, c = parsed[i]
            flags.append(bitrot_mod.digest_of(bytes(c)) == d)  # mtpulint: disable=hot-path-copy -- bitrot hasher needs contiguous bytes
        return flags
    digs = bitrot_mod.digests_of_batch([bytes(c) for _, c in parsed])  # mtpulint: disable=hot-path-copy -- bitrot hasher needs contiguous bytes
    return [digs[i] == parsed[i][0] for i in range(n)]


def _shard_chunk_sizes(total_size: int, k: int) -> list[int]:
    """Per-block shard chunk sizes for an object of total_size bytes."""
    sizes = []
    full_blocks, last = divmod(total_size, BLOCK_SIZE)
    shard = -(-BLOCK_SIZE // k)
    sizes.extend([shard] * full_blocks)
    if last:
        sizes.append(-(-last // k))
    return sizes


class ErasureObjects:
    """One erasure set: object operations over a fixed list of drives."""

    def __init__(
        self,
        disks: list[StorageAPI | None],
        parity: int | None = None,
        codec: codec_mod.BlockCodec | None = None,
        set_index: int = 0,
        pool_index: int = 0,
        ns_lock=None,
        rrs_parity: int | None = None,
    ):
        self.disks = disks
        self.set_index = set_index
        self.pool_index = pool_index
        self.parity = default_parity(len(disks)) if parity is None else parity
        # REDUCED_REDUNDANCY parity (storageclass RRS, default EC:2), never
        # above the standard class.
        self.rrs_parity = min(
            self.parity, 2 if rrs_parity is None else rrs_parity
        )
        # None = resolve the process-wide codec lazily per call, so a codec
        # installed at boot (runtime.install_data_plane_codec) serves layers
        # built before it landed.
        self._codec = codec
        # Partial-write hook: called (bucket, object, version_id) when a put
        # met quorum but missed some drives, so the node can queue an async
        # repair (the reference's addPartial -> MRF feed,
        # cmd/erasure-object.go:1430). Node.build points it at MRFQueue.add.
        self.on_partial = None
        # Namespace lock: serializes writers per object. Defaults to a
        # process-local locker; Node.build swaps in the dsync quorum lockers
        # (reference: NSLock via dsync, cmd/erasure-object.go:933-941).
        self.ns_lock = ns_lock if ns_lock is not None else _process_ns_lock()
        # Bucket-info cache: every object op starts with a bucket check that
        # fanned a stat_vol to all drives — ~12 ms/request of the PUT fixed
        # cost on a 1-core host. Positive entries only, short TTL (the
        # reference keeps buckets in an always-warm metadata cache,
        # cmd/bucket-metadata-sys.go); deletes invalidate locally, remote
        # deletes are seen within the TTL window.
        self._bucket_cache: dict[str, tuple[float, BucketInfo]] = {}
        self._bucket_cache_ttl = float(os.environ.get("MINIO_TPU_BUCKET_CACHE_TTL", "2.0"))

    # ------------------------------------------------------------------ util

    @property
    def codec(self) -> codec_mod.BlockCodec:
        return self._codec if self._codec is not None else codec_mod.default_codec()

    @property
    def multipart(self):
        """Lazy multipart manager (object/multipart.py)."""
        if not hasattr(self, "_multipart"):
            from .multipart import MultipartManager

            self._multipart = MultipartManager(self)
        return self._multipart

    @property
    def drive_count(self) -> int:
        return len(self.disks)

    def _data_blocks(self) -> int:
        return self.drive_count - self.parity

    def _online(self) -> list[StorageAPI | None]:
        return [d if d is not None and d.is_online() else None for d in self.disks]

    # ---------------------------------------------------------------- bucket

    def make_bucket(self, bucket: str) -> None:
        def mk(d):
            if d is None:
                raise errors.DiskNotFound()
            d.make_vol(bucket)

        results = meta_mod.parallel_map(mk, self._online())
        errs = [e for _, e in results]
        n_ok = sum(1 for e in errs if e is None)
        n_exists = sum(1 for e in errs if isinstance(e, errors.VolumeExists))
        quorum = self.drive_count // 2 + 1
        if n_exists > n_ok:
            raise errors.BucketExists(bucket)
        if n_ok + n_exists < quorum:
            raise errors.ErasureWriteQuorum(bucket)

    def _check_bucket(self, bucket: str) -> None:
        """Bucket-existence gate for hot object paths (raises BucketNotFound;
        result discarded — the cached get_bucket_info does the work)."""
        self.get_bucket_info(bucket)

    def invalidate_bucket_cache(self, bucket: str = "") -> None:
        """Drop cached bucket info (all buckets when name is empty) — the
        peer-invalidation hook for cross-node bucket deletes."""
        if bucket:
            self._bucket_cache.pop(bucket, None)
        else:
            self._bucket_cache.clear()

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        cached = self._bucket_cache.get(bucket)
        if cached is not None and cached[0] > time.monotonic():
            return cached[1]

        def stat(d):
            if d is None:
                raise errors.DiskNotFound()
            return d.stat_vol(bucket)

        results = meta_mod.parallel_map(stat, self._online())
        vols = [r for r, _ in results if r is not None]
        errs = [e for _, e in results]
        if not vols:
            count, err = errors.reduce_errs(errs)
            if isinstance(err, errors.VolumeNotFound):
                raise errors.BucketNotFound(bucket)
            raise err or errors.BucketNotFound(bucket)
        info = BucketInfo(name=bucket, created=min(v.created for v in vols))
        self._bucket_cache[bucket] = (time.monotonic() + self._bucket_cache_ttl, info)
        return info

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        # Invalidate before AND after the fan-out: a concurrent check racing
        # the rm could re-cache a still-present volume mid-delete.
        self._bucket_cache.pop(bucket, None)

        def rm(d):
            if d is None:
                raise errors.DiskNotFound()
            d.delete_vol(bucket, force=force)

        results = meta_mod.parallel_map(rm, self._online())
        self._bucket_cache.pop(bucket, None)
        errs = [e for _, e in results]
        n_ok = sum(1 for e in errs if e is None)
        n_missing = sum(1 for e in errs if isinstance(e, errors.VolumeNotFound))
        if any(isinstance(e, errors.VolumeNotEmpty) for e in errs):
            raise errors.BucketNotEmpty(bucket)
        if n_missing > n_ok:
            raise errors.BucketNotFound(bucket)
        quorum = self.drive_count // 2 + 1
        if n_ok + n_missing < quorum:
            raise errors.ErasureWriteQuorum(bucket)

    def list_buckets(self) -> list[BucketInfo]:
        """Aggregate bucket listing across ALL online drives (the reference
        merges per-drive ListVols, cmd/erasure-sets.go ListBuckets), instead
        of trusting whichever drive answers first: a drive that missed a
        MakeBucket (or kept a deleted one) must not define the namespace.
        A bucket counts if at least half the responding drives hold it."""

        def vols(d):
            if d is None:
                raise errors.DiskNotFound()
            return d.list_vols()

        results = meta_mod.parallel_map(vols, self._online())
        seen: dict[str, tuple[float, int]] = {}  # name -> (earliest ctime, count)
        responders = 0
        for vol_list, err in results:
            if err is not None or vol_list is None:
                continue
            responders += 1
            for v in vol_list:
                if v.name.startswith("."):
                    continue
                created, count = seen.get(v.name, (v.created, 0))
                seen[v.name] = (min(created, v.created), count + 1)
        if responders == 0:
            return []
        quorum = max(1, (responders + 1) // 2)
        return sorted(
            (
                BucketInfo(name, created)
                for name, (created, count) in seen.items()
                if count >= quorum
            ),
            key=lambda b: b.name,
        )

    # ------------------------------------------------------------------- put

    def put_object(
        self, bucket: str, object_name: str, data, opts: PutObjectOptions | None = None
    ) -> ObjectInfo:
        """Streaming erasure put: `data` is bytes or a .read(n) stream.

        Blocks are encoded + hashed in GROUP_BLOCKS batches and the shard
        frames appended to per-drive staged files as they are produced, so
        memory stays O(GROUP_BLOCKS x BLOCK_SIZE) regardless of object size
        (the reference's per-1MiB-block loop, erasure-encode.go:73-109, with
        the blocks grouped into device batches). Objects smaller than the
        inline threshold take the one-shot xl.meta-inline path."""
        opts = opts or PutObjectOptions()
        self._check_bucket(bucket)  # raises BucketNotFound

        n = self.drive_count
        m = self.parity
        if (opts.storage_class or "").upper() == "REDUCED_REDUNDANCY" and self.parity > 0:
            m = max(self.rrs_parity, 1)
            opts.user_defined = {**opts.user_defined, "x-internal-storage-class": "REDUCED_REDUNDANCY"}
        k = n - m
        distribution = hash_order(f"{bucket}/{object_name}", n)
        version_id = opts.version_id or (str(uuid.uuid4()) if opts.versioned else "")
        mod_time = now()

        # Validate the bitrot algorithm up front; naming the default
        # streaming algorithm explicitly is the default layout, not legacy.
        wants_whole = False
        if opts.bitrot_algorithm:
            try:
                wants_whole = not bitrot_mod.BitrotAlgorithm(opts.bitrot_algorithm).streaming
            except ValueError:
                raise errors.InvalidArgument(
                    bucket, object_name,
                    f"unknown bitrot algorithm {opts.bitrot_algorithm!r}",
                ) from None

        with tracing.span(
            "object.PutObject", "object", bucket=bucket, object=object_name
        ) as sp:
            # Whole-file bitrot objects always take the streaming (shard-file)
            # path: the legacy layout has no inline representation. Buffer
            # payloads are windowed as views in place; readers land once
            # into a pooled window -- peeked here for the inline decision.
            if isinstance(data, (bytes, bytearray, memoryview)):
                if len(data) < SMALL_FILE_THRESHOLD and not wants_whole:
                    oi = self._put_inline(
                        bucket, object_name, data, opts, k, m, distribution, version_id, mod_time
                    )
                else:
                    oi = self._put_streaming(
                        bucket, object_name, _buffer_windows(data), opts, k, m,
                        distribution, version_id, mod_time,
                    )
            elif hasattr(data, "read") or hasattr(data, "readinto"):
                pool = bufpool.window_pool()
                pb = pool.acquire()
                mv = pb.view()
                try:
                    filled = _fill_window(data, mv)
                except BaseException:
                    # The propagating traceback pins the reader's frames,
                    # which may hold slices of `mv` this code cannot reach
                    # -- discard the storage instead of recycling it.
                    _quiet_release(mv)
                    pb.discard()
                    raise
                _quiet_release(mv)
                if filled < SMALL_FILE_THRESHOLD and not wants_whole:
                    head = bytes(pb.view(0, filled))  # mtpulint: disable=hot-path-copy -- sub-threshold inline blob outlives the pooled window
                    pb.release()
                    oi = self._put_inline(
                        bucket, object_name, head, opts, k, m, distribution, version_id, mod_time
                    )
                else:
                    windows = _wrap_readahead(_stream_windows(data, pool, pb, filled))
                    oi = self._put_streaming(
                        bucket, object_name, windows, opts, k, m,
                        distribution, version_id, mod_time,
                    )
            else:
                raise TypeError(
                    f"put_object data must be bytes or a reader, got {type(data)!r}"
                )
            sp.set(size=oi.size)
            return oi

    def _make_put_fi(
        self,
        bucket: str,
        object_name: str,
        shard_row: int,
        *,
        k: int,
        m: int,
        size: int,
        distribution,
        version_id: str,
        mod_time: float,
        data_dir: str,
        base_meta: dict,
        inline_blob: bytes = b"",
        checksums: list[dict] | None = None,
    ) -> FileInfo:
        return FileInfo(
            volume=bucket,
            name=object_name,
            version_id=version_id,
            data_dir=data_dir,
            mod_time=mod_time,
            size=size,
            metadata=dict(base_meta),
            parts=[ObjectPartInfo(1, size, actual_size=size, mod_time=mod_time)],
            erasure=ErasureInfo(
                data_blocks=k,
                parity_blocks=m,
                block_size=BLOCK_SIZE,
                index=shard_row + 1,
                distribution=list(distribution),
                checksums=list(checksums or []),
            ),
            inline_data=inline_blob,
        )

    def _put_inline(
        self, bucket, object_name, data: bytes, opts, k, m, distribution, version_id, mod_time
    ) -> ObjectInfo:
        """Small object: shards inline in xl.meta, one codec call."""
        size = len(data)
        etag = opts.etag or hashlib.md5(data).hexdigest()
        blocks = [data[i : i + BLOCK_SIZE] for i in range(0, size, BLOCK_SIZE)]
        with tracing.span("encode", "object", blocks=len(blocks)):
            encoded = self.codec.encode(blocks, k, m) if blocks else []
        shard_files = [
            _frame_shard([e[0][row] for e in encoded], [e[1][row] for e in encoded])
            for row in range(k + m)
        ]
        write_quorum = k + 1 if k == m else k
        base_meta = {"etag": etag, "content-type": opts.content_type, **opts.user_defined}

        def write_one(args) -> None:
            i, disk = args
            if disk is None:
                raise errors.DiskNotFound()
            shard_row = distribution[i] - 1
            fi = self._make_put_fi(
                bucket,
                object_name,
                shard_row,
                k=k,
                m=m,
                size=size,
                distribution=distribution,
                version_id=version_id,
                mod_time=mod_time,
                data_dir="",
                base_meta=base_meta,
                inline_blob=shard_files[shard_row],
            )
            disk.write_metadata(bucket, object_name, fi)

        # Inline puts have no staging: the metadata write IS the commit.
        with tracing.span("commit", "object", drives=self.drive_count):
            lk = self.ns_lock.new(bucket, object_name)
            if not lk.acquire(writer=True, timeout=30):
                raise errors.ErasureWriteQuorum(
                    bucket, object_name, "namespace lock timeout"
                )
            try:
                results = meta_mod.parallel_map(write_one, list(enumerate(self._online())))
            finally:
                lk.release()
        errs = [e for _, e in results]
        n_ok = sum(1 for e in errs if e is None)
        if n_ok < write_quorum:
            self._cleanup_failed_put(bucket, object_name, version_id, errs)
            raise errors.ErasureWriteQuorum(
                bucket, object_name, f"write quorum {write_quorum} not met ({n_ok} ok)"
            )
        if n_ok < len(errs) and self.on_partial is not None:
            self.on_partial(bucket, object_name, version_id)
        fi = self._make_put_fi(
            bucket,
            object_name,
            distribution[0] - 1,
            k=k,
            m=m,
            size=size,
            distribution=distribution,
            version_id=version_id,
            mod_time=mod_time,
            data_dir="",
            base_meta=base_meta,
        )
        fi.is_latest = True
        oi = ObjectInfo.from_file_info(fi, bucket, object_name)
        oi.etag = etag
        return oi

    def _put_streaming(
        self, bucket, object_name, windows, opts, k, m, distribution,
        version_id, mod_time,
    ) -> ObjectInfo:
        """Large object: pipelined window encode + gathered staged appends,
        committed with rename_data under the namespace lock. `windows`
        yields _Window views (released here as each group's encode lands)."""
        n = k + m
        data_dir = str(uuid.uuid4())
        # pid-scoped staging: the recovery scan (storage/recovery.py) GCs a
        # tmp entry only when its owner pid is dead, so a respawned pre-fork
        # worker can sweep its dead sibling's stage files without touching
        # live siblings' in-flight uploads on the same drives.
        upload_id = f"{os.getpid()}.{uuid.uuid4()}"
        write_quorum = k + 1 if k == m else k
        disks = self._online()
        size = 0

        def tmp_dir(i: int) -> str:
            return f"tmp/{upload_id}/{i}"

        whole_algo = None
        if opts.bitrot_algorithm:
            whole_algo = bitrot_mod.BitrotAlgorithm(opts.bitrot_algorithm)
            if whole_algo.streaming:
                whole_algo = None  # streaming IS the default layout
        writer = ShardStageWriter(
            self.codec, disks, distribution, k, m, lambda i: f"{tmp_dir(i)}/part.1",
            algo=whole_algo,
        )
        ok = writer.ok

        def cleanup(indices) -> None:
            def rm(i):
                d = disks[i]
                if d is None:
                    return
                try:
                    d.delete(META_BUCKET, f"tmp/{upload_id}", recursive=True)
                except errors.StorageError:
                    pass

            meta_mod.parallel_map(rm, list(indices))

        # Etag strategy: digest-stream md5 rides the encode for free; the
        # content-md5 fallback (MTPU_FAST_ETAG=0 / explicit algorithms)
        # hashes blocks as they stream. Created immediately before the try
        # so every failure path reaches the shutdown handler.
        etag_h = hashlib.md5() if use_fast_etag(opts) else None
        md5h = make_etag_md5() if (not opts.etag and etag_h is None) else None
        try:
            try:
                for win in windows:
                    # Budget check at the window boundary: an expired
                    # deadline aborts into the cleanup path below (stage
                    # shards deleted locally, no budget needed), so a slow
                    # client or slow drives can't stream past the caller's
                    # patience.
                    try:
                        deadline.check("erasure put")
                    except errors.DeadlineExceeded:
                        GLOBAL_DEGRADE.record_deadline_abort("erasure-put")
                        raise
                    blocks = win.blocks()
                    size += len(win)
                    if md5h is not None:
                        for b in blocks:
                            _etag_update(md5h, b)
                    for run in _uniform_runs(blocks):
                        stream = writer.append_group(run)
                        if etag_h is not None and stream:
                            etag_h.update(stream)
                    # The group's writes hold encoder-owned views, never the
                    # window -- recycle it before the next read lands.
                    win.release()
                    # One window's groups appended (pre-sync, pre-drain):
                    # dying here leaves partial stage files + a checked-out
                    # readahead window for the recovery scan to account for.
                    crash.crash_point("put.after-stage")
                    if writer.alive() < write_quorum:
                        raise errors.ErasureWriteQuorum(
                            bucket, object_name, f"write quorum {write_quorum} lost mid-stream"
                        )
                writer.drain()
                writer.finalize()  # zero-byte payloads still commit a shard file
                if writer.alive() < write_quorum:
                    raise errors.ErasureWriteQuorum(
                        bucket, object_name, f"write quorum {write_quorum} lost mid-stream"
                    )
            except BaseException:
                # Writes must settle before cleanup deletes tmp (a late
                # append racing the delete would resurrect the staged file).
                writer.abort()
                if isinstance(md5h, _PipelinedMD5):
                    md5h.shutdown()  # never leak the etag thread on a failed put
                cleanup(range(n))
                raise
        finally:
            closer = getattr(windows, "close", None)
            if closer is not None:
                closer()  # stop the stager thread, recycle queued windows

        etag = opts.etag or (etag_h.hexdigest() if etag_h is not None else md5h.hexdigest())
        base_meta = {"etag": etag, "content-type": opts.content_type, **opts.user_defined}
        row_sums = writer.whole_checksums()
        # All shards staged + drained, no xl.meta exists anywhere yet: the
        # un-acked object must be invisible after restart.
        crash.crash_point("put.before-commit")

        def commit(i) -> None:
            if not ok[i]:
                raise errors.DiskNotFound()
            # Fires on the (skip+1)-th drive entering commit: skip=j models
            # dying with exactly j drives' rename_data already durable
            # (partial-quorum commit). `raise` mode degrades just that drive.
            crash.crash_point("put.mid-commit", disks[i].endpoint() if disks[i] else "")
            shard_row = distribution[i] - 1
            checksums = None
            if row_sums is not None:
                checksums = [
                    {
                        "part": 1,
                        "algo": whole_algo.value,
                        "hash": row_sums[shard_row].hex(),
                    }
                ]
            fi = self._make_put_fi(
                bucket,
                object_name,
                shard_row,
                k=k,
                m=m,
                size=size,
                distribution=distribution,
                version_id=version_id,
                mod_time=mod_time,
                data_dir=data_dir,
                base_meta=base_meta,
                checksums=checksums,
            )
            disks[i].rename_data(META_BUCKET, tmp_dir(i), fi, bucket, object_name)

        # The commit stage covers lock wait + rename_data quorum fan-out:
        # both are serialization costs the encode pipeline can't hide.
        with tracing.span("commit", "object", drives=n):
            lk = self.ns_lock.new(bucket, object_name)
            if not lk.acquire(writer=True, timeout=30):
                cleanup(range(n))
                raise errors.ErasureWriteQuorum(
                    bucket, object_name, "namespace lock timeout"
                )
            try:
                results = meta_mod.parallel_map(commit, list(range(n)))
            finally:
                lk.release()
        errs = [e for _, e in results]
        n_ok = sum(1 for e in errs if e is None)
        # Drop stragglers' staging dirs (committed drives' tmp dirs were
        # consumed by rename_data).
        cleanup([i for i, e in enumerate(errs) if e is not None])
        if n_ok < write_quorum:
            self._cleanup_failed_put(bucket, object_name, version_id, errs)
            raise errors.ErasureWriteQuorum(
                bucket, object_name, f"write quorum {write_quorum} not met ({n_ok} ok)"
            )
        if n_ok < len(errs) and self.on_partial is not None:
            self.on_partial(bucket, object_name, version_id)
        # Quorum reached but the client never saw the 200: after restart the
        # object may exist (it reached quorum) -- if it does, it must be
        # complete and bit-identical, never partially visible.
        crash.crash_point("put.after-commit")
        fi = self._make_put_fi(
            bucket,
            object_name,
            distribution[0] - 1,
            k=k,
            m=m,
            size=size,
            distribution=distribution,
            version_id=version_id,
            mod_time=mod_time,
            data_dir=data_dir,
            base_meta=base_meta,
        )
        fi.is_latest = True
        oi = ObjectInfo.from_file_info(fi, bucket, object_name)
        oi.etag = etag
        return oi

    def _cleanup_failed_put(self, bucket, object_name, version_id, errs) -> None:
        def rm(args):
            disk, err = args
            if disk is None or err is not None:
                return
            try:
                disk.delete_version(
                    bucket, object_name, FileInfo(version_id=version_id)
                )
            except errors.StorageError:
                pass

        meta_mod.parallel_map(rm, list(zip(self._online(), errs)))

    # ------------------------------------------------------------------- get

    def _read_quorum_fi(
        self, bucket: str, object_name: str, version_id: str = ""
    ) -> tuple[FileInfo, list[FileInfo | None], list[StorageAPI | None]]:
        disks = self._online()
        metas, errs = meta_mod.read_all_file_info(disks, bucket, object_name, version_id)
        if all(fi is None for fi in metas):
            count, err = errors.reduce_errs(errs)
            if isinstance(err, errors.FileNotFound):
                raise errors.ObjectNotFound(bucket, object_name)
            if isinstance(err, errors.FileVersionNotFound):
                raise errors.VersionNotFound(bucket, object_name)
            if isinstance(err, errors.VolumeNotFound):
                raise errors.BucketNotFound(bucket)
            raise err or errors.ObjectNotFound(bucket, object_name)
        read_quorum, _ = meta_mod.object_quorum_from_meta(metas, errs, self.parity)
        try:
            fi = meta_mod.find_file_info_in_quorum(metas, read_quorum)
        except errors.ErasureReadQuorum:
            raise errors.InsufficientReadQuorum(bucket, object_name)
        return fi, metas, disks

    def get_object_info(
        self, bucket: str, object_name: str, opts: GetObjectOptions | None = None
    ) -> ObjectInfo:
        opts = opts or GetObjectOptions()
        self._check_bucket(bucket)
        fi, metas, _ = self._read_quorum_fi(bucket, object_name, opts.version_id)
        n_versions = max((f.num_versions for f in metas if f is not None), default=1)
        fi.num_versions = n_versions
        if fi.deleted:
            if not opts.version_id:
                raise errors.ObjectNotFound(bucket, object_name)
            oi = ObjectInfo.from_file_info(fi, bucket, object_name)
            raise errors.MethodNotAllowed(bucket, object_name)
        return ObjectInfo.from_file_info(fi, bucket, object_name)

    def get_object(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> tuple[ObjectInfo, bytes]:
        oi, stream = self.get_object_stream(bucket, object_name, opts, offset, length)
        # Chunks are views over pooled buffers valid only until the next
        # next() -- copy each one while it is live (b"".join(stream) would
        # drain the whole iterator first and join dead views).
        buf = bytearray()
        for c in stream:
            buf += c  # mtpulint: disable=hot-path-copy -- buffered get_object() convenience; zero-copy callers use get_object_stream
        return oi, bytes(buf)  # mtpulint: disable=hot-path-copy -- buffered get_object() convenience; zero-copy callers use get_object_stream

    def get_object_stream(
        self,
        bucket: str,
        object_name: str,
        opts: GetObjectOptions | None = None,
        offset: int = 0,
        length: int = -1,
    ) -> tuple[ObjectInfo, Iterator[bytes]]:
        """Streaming erasure get: yields decoded byte chunks covering
        [offset, offset+length), reading ONLY the shard-file frames of the
        covered blocks (range -> block/shard-offset mapping; the reference's
        ShardFileOffset + lazy parallelReader, cmd/erasure-coding.go:141,
        erasure-decode.go:31-202). Memory is O(GROUP_BLOCKS x BLOCK_SIZE)."""
        opts = opts or GetObjectOptions()
        self._check_bucket(bucket)
        # The object span covers the quorum metadata read; per-drive shard
        # reads during streaming publish storage spans as the body flows.
        with tracing.span(
            "object.GetObject", "object", bucket=bucket, object=object_name
        ):
            fi, metas, disks = self._read_quorum_fi(bucket, object_name, opts.version_id)
        if fi.deleted:
            raise (
                errors.MethodNotAllowed(bucket, object_name)
                if opts.version_id
                else errors.ObjectNotFound(bucket, object_name)
            )
        oi = ObjectInfo.from_file_info(fi, bucket, object_name)
        size = fi.size
        if offset < 0 or offset > size:
            raise errors.InvalidArgument(bucket, object_name, "range out of bounds")
        end = size if length < 0 else min(offset + length, size)
        if size == 0 or offset >= end:
            return oi, iter(())

        k = fi.erasure.data_blocks
        online = meta_mod.list_online_disks(disks, metas, [None] * len(disks), fi)
        by_shard = meta_mod.shuffle_disks_by_index(online, fi.erasure.distribution)
        metas_by_shard = meta_mod.shuffle_disks_by_index(  # type: ignore[arg-type]
            [m if o is not None else None for m, o in zip(metas, online)],
            fi.erasure.distribution,
        )
        inline = bool(fi.inline_data) or any(
            m is not None and m.inline_data for m in metas_by_shard
        )

        stream_range = (
            self._stream_part_range_whole
            if _whole_layout(metas)
            else self._stream_part_range
        )

        def gen() -> Iterator[bytes]:
            abs_pos = 0
            for part in fi.parts:
                p_lo = max(offset - abs_pos, 0)
                p_hi = min(end - abs_pos, part.size)
                if p_lo < p_hi:
                    yield from stream_range(
                        bucket, object_name, fi, by_shard, metas_by_shard,
                        part, inline, p_lo, p_hi,
                    )
                abs_pos += part.size
                if abs_pos >= end:
                    return

        return oi, gen()

    def _stream_part_range(
        self,
        bucket: str,
        object_name: str,
        fi: FileInfo,
        by_shard: list[StorageAPI | None],
        metas_by_shard,
        part: ObjectPartInfo,
        inline: bool,
        lo: int,
        hi: int,
    ) -> Iterator[bytes]:
        """Decode part-local byte range [lo, hi), group by group."""
        k = fi.erasure.data_blocks
        mth = fi.erasure.parity_blocks
        chunk_full = -(-BLOCK_SIZE // k)
        frame_full = DIGEST_LEN + chunk_full
        nblocks = -(-part.size // BLOCK_SIZE)
        last_block_len = part.size - (nblocks - 1) * BLOCK_SIZE

        def chunk_len(b: int) -> int:
            return chunk_full if b < nblocks - 1 else -(-last_block_len // k)

        def block_len(b: int) -> int:
            return BLOCK_SIZE if b < nblocks - 1 else last_block_len

        part_file = f"part.{part.number}"
        b0, b1 = lo // BLOCK_SIZE, (hi - 1) // BLOCK_SIZE

        # Slot selection: the k lowest-latency ONLINE slots carry the window
        # (ranked by the metered read_file EWMAs + breaker state); the rest
        # queue as hedge spares, best first. Inline payloads ride the
        # metadata already in hand -- no drive IO, nothing to hedge.
        if inline:
            primaries = list(range(k))
            spares = [j for j in range(k, k + mth) if metas_by_shard[j] is not None]
        else:
            ranked = _rank_read_slots(by_shard, k)
            primaries = ranked[:k] if len(ranked) >= k else ranked
            spares = ranked[len(primaries):]

        pool = bufpool.shard_pool()

        def make_window(g0: int):
            """Issue the window's primary-slot reads immediately (futures);
            the readahead stage -- window g+1's drive IO overlaps window g's
            verify/decode (klauspost/readahead's role in the reference read
            pipeline, cmd/object-api-utils.go:686)."""
            g1 = min(g0 + GROUP_BLOCKS - 1, b1)
            window_sizes = [chunk_len(b) for b in range(g0, g1 + 1)]
            file_off = g0 * frame_full
            file_len = sum(DIGEST_LEN + s for s in window_sizes)
            bufs = _WindowBufs()

            def read_window(
                j: int,
            ) -> tuple[list[tuple[memoryview, memoryview]], list[bool]] | None:
                disk = by_shard[j]
                pb = None
                try:
                    if inline:
                        m = metas_by_shard[j]
                        blob = m.inline_data if m is not None else b""
                        if not blob:
                            return None
                        blob = blob[file_off : file_off + file_len]
                    else:
                        if disk is None:
                            return None
                        path = os.path.join(object_name, fi.data_dir, part_file)
                        rfi = getattr(disk, "read_file_into", None)
                        if rfi is not None:
                            # Zero-copy row read: the shard image lands ONCE
                            # in a pooled buffer; frames below are views over
                            # it. The window's _WindowBufs owns the buffer
                            # until the decoded chunks are consumed.
                            pb = pool.acquire(file_len)
                            blob = pb.view(0, file_len)
                            if rfi(bucket, path, file_off, blob) < file_len:
                                raise errors.FileCorrupt("short shard file")
                        else:
                            blob = disk.read_file(bucket, path, file_off, file_len)
                    # Stage mark via direct ledger record: pool threads carry
                    # no span context (same rationale as storage metering).
                    t_fp = time.perf_counter()
                    c_fp = time.thread_time()
                    parsed = _parse_frames(blob, window_sizes)
                    # Copy-ledger hop: frame parsing slices memoryviews over
                    # the read blob -- zero-copy by construction.
                    GLOBAL_PROFILER.copy.record("frame-parse", MOVED, len(blob))
                    # Verify here, in the parallel read thread: the native
                    # verifier releases the GIL, so rows verify concurrently.
                    oks = _verify_frames(blob, window_sizes, parsed)
                    GLOBAL_PERF.ledger.record(
                        "object", "frame-parse",
                        time.perf_counter() - t_fp, time.thread_time() - c_fp,
                    )
                    if pb is not None:
                        if bufs.add(pb):
                            pb = None
                            return parsed, oks
                        # Hedged straggler: the window was consumed and
                        # its registry closed while this read was in
                        # flight. The result is discarded, so drop this
                        # frame's exports first; the finally recycles pb.
                        for d, c in parsed:
                            _quiet_release(d, c)
                        _quiet_release(blob)
                        return None
                    return parsed, oks
                except (errors.DiskError, errors.FileCorrupt):
                    return None
                finally:
                    if pb is not None:
                        pb.release()

            issued_at = {j: time.monotonic() for j in primaries}
            futures = dict(
                zip(primaries, meta_mod.parallel_submit(read_window, primaries))
            )
            return g1, read_window, futures, issued_at, bufs

        def gather_hedged(read_window, futures, issued_at, install) -> None:
            """Collect window reads, arming hedges when a primary straggles.

            Reconstruction needs ANY k of the n rows, so the moment a primary
            exceeds max(HEDGE_FLOOR, HEDGE_MULT x median completed duration)
            the best spare slot is launched against it; the first k usable
            rows win and stragglers are left to finish in their pool thread
            (results discarded). Spares also replace failed reads outright."""
            by_future = {f: j for j, f in futures.items()}
            spare_queue = list(spares)
            hedged: set[int] = set()
            covered: set[int] = set()
            durations: list[float] = []
            usable: set[int] = set()
            launched = 0

            def launch(j: int, covering: int | None) -> None:
                nonlocal launched
                issued_at[j] = time.monotonic()
                f = meta_mod.parallel_submit(read_window, [j])[0]
                by_future[f] = j
                if covering is not None:
                    hedged.add(j)
                    covered.add(covering)
                    launched += 1

            while len(usable) < k and by_future:
                try:
                    deadline.check("hedged erasure read")
                except errors.DeadlineExceeded:
                    GLOBAL_DEGRADE.record_deadline_abort("erasure-get")
                    raise
                done, _ = _cf.wait(
                    set(by_future), timeout=_HEDGE_POLL,
                    return_when=_cf.FIRST_COMPLETED,
                )
                now = time.monotonic()
                for f in done:
                    j = by_future.pop(f)
                    result = f.result()[0]
                    install(j, result)
                    durations.append(now - issued_at[j])
                    if result is not None:
                        usable.add(j)
                    elif spare_queue:
                        # Failed read: its replacement is routing, not hedging.
                        launch(spare_queue.pop(0), covering=None)
                if len(usable) >= k or not spare_queue:
                    continue
                # Hedge decision: need a median worth trusting (at least
                # half the quorum completed), then every uncovered
                # outstanding slot past the threshold gets one hedge.
                if len(durations) * 2 < k:
                    continue
                med = sorted(durations)[len(durations) // 2]
                threshold = max(HEDGE_FLOOR, HEDGE_MULT * med)
                for j in list(by_future.values()):
                    if not spare_queue:
                        break
                    if j in covered or j in hedged:
                        continue
                    if now - issued_at[j] > threshold:
                        launch(spare_queue.pop(0), covering=j)
            wins = len(usable & hedged)
            if launched:
                GLOBAL_DEGRADE.record_hedge(launched, wins)
                cur = tracing.current()
                if cur is not None:
                    cur.set(hedge_launched=launched, hedge_wins=wins)

        starts = list(range(b0, b1 + 1, GROUP_BLOCKS))

        def windows():
            """Produce one (chunks, close) unit per window. `chunks` are
            memoryviews over pooled shard buffers (or decoded bytes on a
            degraded read); close() recycles the window's buffers and must
            run only after the consumer is done with the views."""
            pending = make_window(starts[0])
            try:
                for win_i, g0 in enumerate(starts):
                    g1, read_window, futures, issued_at, bufs = pending
                    # Kick off the NEXT window's reads before verifying this
                    # one.
                    pending = (
                        make_window(starts[win_i + 1])
                        if win_i + 1 < len(starts)
                        else None
                    )
                    try:
                        chunks = self._decode_window(
                            bucket, object_name, k, mth, g0, g1,
                            read_window, futures, issued_at, gather_hedged,
                            chunk_len, block_len, lo, hi, len(primaries),
                        )
                    except BaseException:
                        bufs.close()
                        raise

                    def unit_close(chunks=chunks, futures=futures, bufs=bufs):
                        # Drop the refs this pipeline owns before the
                        # buffers recycle: straggler futures pin their
                        # (parsed, oks) rows and the registry list pins
                        # unconsumed chunks (bufsan: view-outlives-buffer).
                        # Views already yielded to the consumer are NOT
                        # invalidated -- bufs.close() demotes any buffer
                        # they still export to a discard.
                        futures.clear()
                        del chunks[:]
                        bufs.close()

                    yield chunks, unit_close
            finally:
                if pending is not None:
                    # Consumer abandoned the stream with a prefetched window
                    # in flight: its reads recycle into the closed registry.
                    pending[4].close()

        # The get-stager overlaps window g+1's drive reads + verify with the
        # response write of window g (MTPU_GET_READAHEAD units in flight).
        depth = int(os.environ.get("MTPU_GET_READAHEAD", "1"))
        it = _GetStager(windows(), depth) if depth > 0 else windows()
        try:
            for chunks, close in it:
                try:
                    # pop() so this frame never pins a yielded view: by the
                    # time close() runs, only the consumer's own references
                    # (if any) keep a chunk's storage exported.
                    while chunks:
                        yield chunks.pop(0)
                finally:
                    # Runs when the consumer asks past the window's last
                    # chunk or tears down mid-window.
                    close()
        finally:
            closer = getattr(it, "close", None)
            if closer is not None:
                closer()

    def _decode_window(
        self,
        bucket: str,
        object_name: str,
        k: int,
        mth: int,
        g0: int,
        g1: int,
        read_window,
        futures,
        issued_at,
        gather_hedged,
        chunk_len,
        block_len,
        lo: int,
        hi: int,
        n_primaries: int,
    ) -> list:
        """Gather + verify one window's rows and return its response chunks
        (row views on the healthy path; decoded bytes where reconstructed)."""
        # Ranked rows first; spares pulled lazily on any failure (the
        # lazy-spare parallelReader discipline, erasure-decode.go:119).
        frames: list[list[tuple[memoryview, memoryview]] | None] = [None] * (k + mth)
        oks: list[list[bool] | None] = [None] * (k + mth)
        loaded = [False] * (k + mth)

        def install(j: int, result) -> None:
            frames[j], oks[j] = result if result is not None else (None, None)
            loaded[j] = True

        # GET-side stage mark: the hedged shard gather is where a
        # degraded or slow-drive read spends its time.
        with tracing.span("shard-read", "object", drives=n_primaries):
            gather_hedged(read_window, futures, issued_at, install)

        def load_spares() -> None:
            spare = [j for j in range(k + mth) if not loaded[j]]
            if not spare:
                return
            spare_results = meta_mod.parallel_map(read_window, spare)
            for idx, j in enumerate(spare):
                install(j, spare_results[idx][0])

        if sum(1 for j in range(k + mth) if frames[j] is not None) < k:
            load_spares()

        def valid_rows(w: int) -> list[bytes | None]:
            # Frames were bitrot-verified at read time (one native call
            # per row window); a failed frame drops its whole shard, as
            # the reference's bitrot readers do.
            rows: list[bytes | None] = [None] * (k + mth)
            for j in range(k + mth):
                if frames[j] is None:
                    continue
                if oks[j][w]:
                    rows[j] = frames[j][w][1]
                else:
                    frames[j] = None  # corrupt: drop the shard
            return rows

        # Pass 1: verify every block in the window, pulling spares once
        # if any block falls under read quorum.
        rows_by_block: list[list[bytes | None]] = []
        for b in range(g0, g1 + 1):
            rows = valid_rows(b - g0)
            if sum(1 for r in rows if r is not None) < k:
                load_spares()
                rows = valid_rows(b - g0)
            if sum(1 for r in rows if r is not None) < k:
                raise errors.InsufficientReadQuorum(bucket, object_name)
            rows_by_block.append(rows)

        # Pass 2: rebuild missing data rows for the whole window in
        # batched codec calls, grouped by loss pattern -- a degraded GET
        # runs ONE device program per window instead of a per-block host
        # reconstruct (the served decode path, cmd/erasure-decode.go:206).
        groups: dict[tuple[tuple[bool, ...], tuple[int, ...]], list[int]] = {}
        for wi, rows in enumerate(rows_by_block):
            want = tuple(j for j in range(k) if rows[j] is None)
            if want:
                pattern = tuple(r is not None for r in rows)
                groups.setdefault((pattern, want), []).append(wi)
        if groups:
            # Only a degraded window pays for (and reports) a decode
            # stage; healthy reads skip the mark entirely.
            with tracing.span("decode", "object", blocks=len(rows_by_block)):
                for (_, want), idxs in groups.items():
                    results = self.codec.reconstruct_batch(
                        [rows_by_block[wi] for wi in idxs], k, mth, want
                    )
                    for wi, (chunks, _) in zip(idxs, results):
                        for slot, j in enumerate(want):
                            rows_by_block[wi][j] = chunks[slot]
                            # Copy-ledger hop: a degraded read rebuilds
                            # the missing rows into fresh buffers.
                            GLOBAL_PROFILER.copy.record(
                                "decode", COPIED, len(chunks[slot])
                            )

        # Healthy path: the response chunks ARE the data-row views -- no
        # join, no copy; _block_pieces trims the range/tail per block.
        out: list = []
        for b in range(g0, g1 + 1):
            s = max(lo - b * BLOCK_SIZE, 0)
            e = min(hi - b * BLOCK_SIZE, block_len(b))
            if s < e:
                out.extend(
                    _block_pieces(rows_by_block[b - g0], chunk_len(b), s, e)
                )
        return out

    def _stream_part_range_whole(
        self,
        bucket: str,
        object_name: str,
        fi: FileInfo,
        by_shard,
        metas_by_shard,
        part: ObjectPartInfo,
        inline: bool,
        lo: int,
        hi: int,
    ) -> Iterator[bytes]:
        """Range decode of a LEGACY whole-file-bitrot part.

        The shard files are raw bytes; integrity is one checksum per part
        per row stored in each drive's own metadata (cmd/bitrot-whole.go:62
        wholeBitrotReader). Verification therefore reads the ENTIRE row file
        once (the reference pays the same cost), then blocks are sliced and
        missing data rows rebuilt with the batched codec.
        """
        k = fi.erasure.data_blocks
        mth = fi.erasure.parity_blocks
        chunk_full = -(-BLOCK_SIZE // k)
        nblocks = -(-part.size // BLOCK_SIZE)
        last_block_len = part.size - (nblocks - 1) * BLOCK_SIZE

        def chunk_len(b: int) -> int:
            return chunk_full if b < nblocks - 1 else -(-last_block_len // k)

        def block_len(b: int) -> int:
            return BLOCK_SIZE if b < nblocks - 1 else last_block_len

        part_file = f"part.{part.number}"
        blobs: list[bytes | None] = [None] * (k + mth)
        loaded = [False] * (k + mth)
        # Verification must hash the ENTIRE row file (whole-file semantics,
        # same cost the reference's wholeBitrotReader pays), but only the
        # region covering the requested blocks is retained afterwards, so a
        # small range GET of a large legacy object doesn't hold k full rows.
        b0, b1 = lo // BLOCK_SIZE, (hi - 1) // BLOCK_SIZE
        region_off = b0 * chunk_full
        region_end = (b1 + 1) * chunk_full

        def load_row(j: int) -> bytes | None:
            meta = metas_by_shard[j]
            disk = by_shard[j]
            if meta is None:
                return None
            try:
                if inline:
                    blob = meta.inline_data or b""
                else:
                    if disk is None:
                        return None
                    blob = disk.read_file(
                        bucket, os.path.join(object_name, fi.data_dir, part_file)
                    )
            except (errors.DiskError, errors.FileCorrupt):
                return None
            if not _whole_sum_matches(meta, part.number, blob):
                return None  # whole-file bitrot: the entire row is suspect
            return blob[region_off:region_end]

        def ensure(rows_idx: list[int]) -> None:
            todo = [j for j in rows_idx if not loaded[j]]
            if not todo:
                return
            results = meta_mod.parallel_map(load_row, todo)
            for idx, j in enumerate(todo):
                blobs[j] = results[idx][0] if results[idx][1] is None else None
                loaded[j] = True

        ensure(list(range(k)))
        if any(blobs[j] is None for j in range(k)):
            ensure(list(range(k + mth)))
        if sum(1 for b in blobs if b is not None) < k:
            raise errors.InsufficientReadQuorum(bucket, object_name)

        for g0 in range(b0, b1 + 1, GROUP_BLOCKS):
            g1 = min(g0 + GROUP_BLOCKS - 1, b1)
            rows_by_block: list[list[bytes | None]] = []
            for b in range(g0, g1 + 1):
                cl = chunk_len(b)
                off = b * chunk_full - region_off
                rows_by_block.append(
                    [
                        blobs[j][off : off + cl] if blobs[j] is not None else None
                        for j in range(k + mth)
                    ]
                )
            missing = tuple(j for j in range(k) if blobs[j] is None)
            if missing:
                results = self.codec.reconstruct_batch(rows_by_block, k, mth, missing)
                for rows, (chunks, _) in zip(rows_by_block, results):
                    for slot, j in enumerate(missing):
                        rows[j] = chunks[slot]
            for b in range(g0, g1 + 1):
                joined = _join_block_rows(rows_by_block[b - g0], k, block_len(b))
                s = max(lo - b * BLOCK_SIZE, 0)
                e = min(hi - b * BLOCK_SIZE, block_len(b))
                # Full-range slice of bytes returns the same object, so a
                # full-block yield is copy-free now that the join is exact.
                yield joined[s:e]

    # ---------------------------------------------------------------- delete

    def put_object_metadata(
        self,
        bucket: str,
        object_name: str,
        version_id: str = "",
        updates: dict[str, str] | None = None,
        removes: list[str] | None = None,
    ) -> ObjectInfo:
        """Update user metadata of an existing version in place
        (PutObjectMetadata / PutObjectTags, cmd/erasure-object.go equivalent:
        read quorum FileInfo, mutate metadata, update xl.meta on all drives)."""
        self._check_bucket(bucket)
        fi, metas, disks = self._read_quorum_fi(bucket, object_name, version_id)
        if fi.deleted:
            raise errors.MethodNotAllowed(bucket, object_name)
        for k in removes or []:
            fi.metadata.pop(k, None)
        fi.metadata.update(updates or {})

        # Each drive keeps ITS OWN FileInfo (per-drive erasure index and
        # shard checksums differ) -- only the metadata dict is replaced.
        # Writing the quorum FileInfo verbatim to every drive would clobber
        # shard identity and corrupt reads.
        def upd(args):
            i, d = args
            if d is None:
                raise errors.DiskNotFound()
            own = metas[i]
            if own is None:
                raise errors.FileNotFound(bucket, object_name)
            own.metadata = dict(fi.metadata)
            d.update_metadata(bucket, object_name, own)

        results = meta_mod.parallel_map(upd, list(enumerate(disks)))
        errs = [e for _, e in results]
        write_quorum = fi.write_quorum(self.parity)
        err = errors.reduce_quorum_errs(
            errs, write_quorum, errors.InsufficientWriteQuorum(bucket, object_name)
        )
        if err is not None:
            raise err
        return ObjectInfo.from_file_info(fi, bucket, object_name)

    def transition_object(
        self,
        bucket: str,
        object_name: str,
        version_id: str,
        tier: str,
        remote_name: str,
        expected_etag: str = "",
        expected_mtime: float = 0.0,
    ) -> ObjectInfo:
        """Mark a version transitioned to a remote tier and free its local
        data parts (the reference's DeleteObject w/ transition markers in
        cmd/bucket-lifecycle.go transitionObject + erasure-object.go: xl.meta
        keeps TransitionStatus/TransitionedObjName/TransitionTier while the
        shard files are reclaimed). The caller has already uploaded the bytes
        to the tier under remote_name; expected_etag/mtime guard against the
        version having been overwritten since the caller read it (otherwise a
        concurrent PUT on an unversioned bucket would be stamped as pointing
        at stale tier bytes and lose the new data). Inline (small) objects
        are left local — reclaiming xl.meta-inline bytes saves nothing."""
        from ..control.tiering import (
            META_TRANSITION_NAME,
            META_TRANSITION_STATUS,
            META_TRANSITION_TIER,
            STATUS_COMPLETE,
        )

        self._check_bucket(bucket)
        fi, metas, disks = self._read_quorum_fi(bucket, object_name, version_id)
        if fi.deleted:
            raise errors.MethodNotAllowed(bucket, object_name)
        if not fi.data_dir:
            raise errors.InvalidArgument(bucket, object_name, "inline object not transitionable")
        if expected_etag and fi.metadata.get("etag", "") != expected_etag:
            raise errors.PreconditionFailed(msg="object changed since tier upload")
        if expected_mtime and abs(fi.mod_time - expected_mtime) > 1e-6:
            raise errors.PreconditionFailed(msg="object changed since tier upload")
        updates = {
            META_TRANSITION_STATUS: STATUS_COMPLETE,
            META_TRANSITION_TIER: tier,
            META_TRANSITION_NAME: remote_name,
        }
        oi = self.put_object_metadata(bucket, object_name, version_id, updates=updates)

        # Metadata is durable first: a crash here leaves orphan part files
        # (reclaimed by heal/scan) but never a transitioned object whose
        # local parts are gone without the remote pointer being recorded.
        def free(d):
            if d is None:
                return
            try:
                d.delete(bucket, os.path.join(object_name, fi.data_dir), recursive=True)
            except errors.DiskError:
                pass

        meta_mod.parallel_map(free, list(disks))
        return oi

    def delete_object(
        self, bucket: str, object_name: str, opts: DeleteObjectOptions | None = None
    ) -> ObjectInfo:
        with tracing.span(
            "object.DeleteObject", "object", bucket=bucket, object=object_name
        ):
            return self._delete_object(bucket, object_name, opts)

    def _delete_object(
        self, bucket: str, object_name: str, opts: DeleteObjectOptions | None = None
    ) -> ObjectInfo:
        opts = opts or DeleteObjectOptions()
        self._check_bucket(bucket)
        disks = self._online()
        write_quorum = self.drive_count // 2 + 1

        if opts.versioned and not opts.version_id:
            # Write a delete marker as the new latest version.
            marker = FileInfo(
                volume=bucket,
                name=object_name,
                version_id=str(uuid.uuid4()),
                deleted=True,
                mod_time=now(),
            )

            def mark(d):
                if d is None:
                    raise errors.DiskNotFound()
                d.delete_version(bucket, object_name, marker)

            results = meta_mod.parallel_map(mark, disks)
            errs = [e for _, e in results]
            err = errors.reduce_quorum_errs(
                errs, write_quorum, errors.ErasureWriteQuorum(bucket, object_name)
            )
            if err:
                raise err
            oi = ObjectInfo(
                bucket=bucket,
                name=object_name,
                version_id=marker.version_id,
                delete_marker=True,
                mod_time=marker.mod_time,
            )
            return oi

        # Physical delete of one version (or the null version).
        vid = opts.version_id
        fi = FileInfo(volume=bucket, name=object_name, version_id=vid)

        def rm(d):
            if d is None:
                raise errors.DiskNotFound()
            d.delete_version(bucket, object_name, fi)

        results = meta_mod.parallel_map(rm, disks)
        errs = [e for _, e in results]
        not_found = (errors.FileNotFound, errors.FileVersionNotFound)
        if errs and all(e is not None and isinstance(e, not_found) for e in errs):
            # Every drive agrees the version was never there: that is a clean
            # not-found, not a write-quorum failure (the multi-pool delete
            # sweep relies on this to skip pools that never held the object).
            if vid:
                raise errors.VersionNotFound(bucket, object_name)
            raise errors.ObjectNotFound(bucket, object_name)
        err = errors.reduce_quorum_errs(
            errs,
            write_quorum,
            errors.ErasureWriteQuorum(bucket, object_name),
            ignored=not_found,
        )
        if err:
            raise err
        return ObjectInfo(bucket=bucket, name=object_name, version_id=vid)

    # ------------------------------------------------------------------ heal

    def heal_object(
        self, bucket: str, object_name: str, version_id: str = "", dry_run: bool = False
    ) -> HealResultItem:
        """Reconstruct missing/corrupt shards onto stale drives
        (cmd/erasure-healing.go:257 healObject equivalent)."""
        with tracing.span(
            "object.HealObject", "object", bucket=bucket, object=object_name
        ):
            return self._heal_object(bucket, object_name, version_id, dry_run)

    def _heal_object(
        self, bucket: str, object_name: str, version_id: str = "", dry_run: bool = False
    ) -> HealResultItem:
        disks = self._online()
        metas, errs = meta_mod.read_all_file_info(disks, bucket, object_name, version_id)
        read_quorum, _ = meta_mod.object_quorum_from_meta(metas, errs, self.parity)
        fi = meta_mod.find_file_info_in_quorum(metas, read_quorum)
        k, mth = fi.erasure.data_blocks, fi.erasure.parity_blocks

        result = HealResultItem(
            bucket=bucket,
            object=object_name,
            version_id=fi.version_id,
            data_blocks=k,
            parity_blocks=mth,
        )
        online = meta_mod.list_online_disks(disks, metas, errs, fi)
        state = []
        for d, o in zip(disks, online):
            if d is None:
                state.append("offline")
            elif o is None:
                state.append("missing")
            else:
                state.append("ok")
        result.before_drive_state = list(state)

        from ..control.tiering import META_TRANSITION_STATUS, STATUS_COMPLETE

        if fi.deleted or fi.metadata.get(META_TRANSITION_STATUS) == STATUS_COMPLETE:
            # Delete markers and transitioned versions have no local shard
            # data; heal = copy the metadata record to stale drives.
            to_heal = [i for i, s in enumerate(state) if s == "missing"]
            if not dry_run:
                for i in to_heal:
                    d = disks[i]
                    if d is not None:
                        d.write_metadata(bucket, object_name, fi)
                        state[i] = "healed"
            result.after_drive_state = state
            result.disks_healed = len(to_heal)
            return result

        by_shard = meta_mod.shuffle_disks_by_index(online, fi.erasure.distribution)
        metas_by_shard = meta_mod.shuffle_disks_by_index(  # type: ignore[arg-type]
            [m if o is not None else None for m, o in zip(metas, online)],
            fi.erasure.distribution,
        )
        inline = bool(fi.inline_data) or (
            fi.size > 0 and fi.size < SMALL_FILE_THRESHOLD and not fi.data_dir
        )
        parts = fi.parts or [ObjectPartInfo(1, fi.size, fi.size)]
        part_chunks = {p.number: _shard_chunk_sizes(p.size, k) for p in parts}
        # Legacy whole-file-bitrot objects: raw shard files, one checksum per
        # part per row in each drive's own metadata (cmd/bitrot-whole.go).
        # Majority vote -- one drive's lost cs list must not flip the layout.
        whole = _whole_layout(metas)

        # Verified single-part whole-file blobs are kept for the rebuild so
        # the heal doesn't read every surviving row twice (verify + rebuild).
        # Multi-part objects skip the cache to bound memory at one part.
        whole_blobs: dict[tuple[int, int], bytes] = {}

        def _read_raw(j: int, part: ObjectPartInfo) -> bytes:
            cached = whole_blobs.get((j, part.number))
            if cached is not None:
                return cached
            disk = by_shard[j]
            if disk is None:
                raise errors.DiskNotFound()
            if inline:
                m = metas_by_shard[j]
                blob = m.inline_data if m is not None else b""
                if not blob:
                    raise errors.FileNotFound()
                return blob
            return disk.read_file(
                bucket, os.path.join(object_name, fi.data_dir, f"part.{part.number}")
            )

        def read_part_frames(j: int, part: ObjectPartInfo):
            """(digest, chunk) frames; digest is None for whole-file rows
            (their integrity is the single per-part checksum, verified in
            _whole_row_ok, not per chunk)."""
            blob = _read_raw(j, part)
            if not whole:
                return _parse_frames(blob, part_chunks[part.number])
            frames, pos = [], 0
            for sz in part_chunks[part.number]:
                chunk = blob[pos : pos + sz]
                if len(chunk) != sz:
                    raise errors.FileCorrupt("short whole-bitrot shard file")
                frames.append((None, chunk))
                pos += sz
            return frames

        def _whole_row_ok(j: int, part: ObjectPartInfo) -> bool:
            m = metas_by_shard[j]
            if m is None:
                return False
            try:
                blob = _read_raw(j, part)
            except (errors.DiskError, errors.FileCorrupt):
                return False
            if not _whole_sum_matches(m, part.number, blob):
                return False
            # Rows are verified in index order and the rebuild re-reads only
            # the FIRST k surviving rows, so caching the first k verified
            # rows covers exactly the reuse set (single-part only: memory is
            # bounded at k rows ~ the part size).
            if len(parts) == 1 and len(whole_blobs) < k:
                whole_blobs[(j, part.number)] = blob
            return True

        # Which shard rows need rebuilding? (missing drive, bad metadata, or
        # failed verification of any part chunk.) Verification is batched
        # ACROSS rows per part and routed through the codec, so the batching
        # device codec runs one verify_digests program per chunk-length
        # group (the scanner's deep-scan consumer, VERDICT r3 #9) instead of
        # a per-shard host loop.
        bad: set[int] = {j for j in range(k + mth) if by_shard[j] is None}
        if fi.size > 0 and whole:
            for part in parts:
                for j in range(k + mth):
                    if j not in bad and not _whole_row_ok(j, part):
                        bad.add(j)
        elif fi.size > 0 and isinstance(self.codec, codec_mod.HostCodec):
            # Host codec: verify each row's H||chunk frames IN PLACE against
            # the raw file image (one C call per row, no chunk slicing or
            # re-stacking -- the GET path's discipline).
            for part in parts:
                sizes = part_chunks[part.number]
                for j in range(k + mth):
                    if j in bad:
                        continue
                    try:
                        blob = _read_raw(j, part)
                        parsed = _parse_frames(blob, sizes)
                        if not all(_verify_frames(blob, sizes, parsed)):
                            bad.add(j)
                    except (errors.DiskError, errors.FileCorrupt):
                        bad.add(j)
        elif fi.size > 0:
            # Device codec: rows are verified in batched digest calls
            # (grouped across rows so small objects still form real device
            # batches -- the scanner's deep-scan consumer, VERDICT r3 #9)
            # but flushed before the pending chunks exceed ~32 MiB, so
            # memory stays O(flush window + one row), not
            # O(whole part x all rows).
            FLUSH_BYTES = 32 << 20

            for part in parts:
                pending: list[tuple[int, bytes, bytes]] = []  # (row, digest, chunk)
                pending_bytes = 0

                def flush() -> None:
                    nonlocal pending, pending_bytes
                    by_len: dict[int, list[int]] = {}
                    for i, (_, _, c) in enumerate(pending):
                        by_len.setdefault(len(c), []).append(i)
                    for idxs in by_len.values():
                        digs = self.codec.digests_batch([pending[i][2] for i in idxs])
                        for i, got in zip(idxs, digs):
                            if got != pending[i][1]:
                                bad.add(pending[i][0])
                    pending = []
                    pending_bytes = 0

                for j in range(k + mth):
                    if j in bad:
                        continue
                    try:
                        for digest, chunk in read_part_frames(j, part):
                            pending.append((j, digest, chunk))
                            pending_bytes += len(chunk)
                    except (errors.DiskError, errors.FileCorrupt):
                        bad.add(j)
                        continue
                    if pending_bytes >= FLUSH_BYTES:
                        flush()
                flush()

        oks = [j not in bad for j in range(k + mth)]
        bad_rows = tuple(j for j, ok in enumerate(oks) if not ok)
        if not bad_rows:
            result.after_drive_state = state
            return result
        if sum(oks) < k:
            raise errors.InsufficientReadQuorum(bucket, object_name, "object unhealable")
        if dry_run:
            result.after_drive_state = state
            result.disks_healed = len(bad_rows)
            return result

        # Rebuild bad rows per part, block by block, from surviving shards.
        surviving = [j for j, ok in enumerate(oks) if ok][: k]
        rebuilt_files: dict[int, dict[int, bytes]] = {j: {} for j in bad_rows}  # row -> part -> blob
        rebuilt_sums: dict[int, list[dict]] = {j: [] for j in bad_rows}  # whole-file only
        whole_algo_heal = None
        if whole:
            # Algorithm for rebuilt checksums: first parsable entry from a
            # VERIFIED surviving row (the quorum representative's field may
            # be the one corrupted drive's).
            for j in surviving:
                m_ = metas_by_shard[j]
                for ent in m_.erasure.checksums if m_ is not None else []:
                    try:
                        whole_algo_heal = bitrot_mod.BitrotAlgorithm(ent.get("algo", ""))
                        break
                    except ValueError:
                        continue
                if whole_algo_heal is not None:
                    break
            if whole_algo_heal is None:
                raise errors.FileCorrupt(
                    "whole-file bitrot object has no parsable checksum algorithm"
                )
        if fi.size > 0:
            for part in parts:
                frames_by_row = {j: read_part_frames(j, part) for j in surviving}
                per_row: dict[int, list[tuple[bytes, bytes]]] = {j: [] for j in bad_rows}
                nblocks = len(part_chunks[part.number])
                # Rebuild GROUP_BLOCKS windows per codec call: heal runs the
                # same batched device program as encode (reconstruct + bitrot
                # digests in one fused step; the reference loops per block,
                # cmd/erasure-lowlevel-heal.go:31). The short tail block makes
                # its window irregular and falls back to the host codec.
                for g0 in range(0, nblocks, GROUP_BLOCKS):
                    window = range(g0, min(g0 + GROUP_BLOCKS, nblocks))
                    rows_batch: list[list[bytes | None]] = []
                    for b in window:
                        rows: list[bytes | None] = [None] * (k + mth)
                        for j in surviving:
                            rows[j] = frames_by_row[j][b][1]
                        rows_batch.append(rows)
                    results = self.codec.reconstruct_batch(
                        rows_batch, k, mth, bad_rows, with_digests=True
                    )
                    for chunks, digests in results:
                        for idx, j in enumerate(bad_rows):
                            per_row[j].append((digests[idx], chunks[idx]))
                for j in bad_rows:
                    if whole:
                        raw = b"".join(c for _, c in per_row[j])  # mtpulint: disable=hot-path-copy -- heal materializes the rebuilt part
                        rebuilt_files[j][part.number] = raw
                        rebuilt_sums[j].append(
                            {
                                "part": part.number,
                                "algo": whole_algo_heal.value,
                                "hash": bitrot_mod.digest_of(raw, whole_algo_heal).hex(),
                            }
                        )
                    else:
                        rebuilt_files[j][part.number] = _frame_shard(
                            [c for _, c in per_row[j]], [d for d, _ in per_row[j]]
                        )

        # Write rebuilt shards to the drives that should hold them.
        healed = 0
        # pid-scoped like the PUT staging: a heal interrupted by worker death
        # leaves tmp dirs the recovery scan can attribute to the dead pid.
        upload_id = f"{os.getpid()}.{uuid.uuid4()}"
        for j in bad_rows:
            # Find the drive index whose distribution slot is shard j.
            drive_index = fi.erasure.distribution.index(j + 1)
            disk = disks[drive_index]
            if disk is None:
                continue
            new_fi = FileInfo(
                volume=bucket,
                name=object_name,
                version_id=fi.version_id,
                data_dir=fi.data_dir if not inline else "",
                mod_time=fi.mod_time,
                size=fi.size,
                metadata=dict(fi.metadata),
                parts=[ObjectPartInfo(p.number, p.size, p.actual_size, p.mod_time) for p in fi.parts],
                erasure=ErasureInfo(
                    data_blocks=k,
                    parity_blocks=mth,
                    block_size=fi.erasure.block_size,
                    index=j + 1,
                    distribution=list(fi.erasure.distribution),
                    checksums=rebuilt_sums[j] if whole else [],
                ),
                inline_data=rebuilt_files[j].get(1, b"") if inline else b"",
            )
            try:
                if inline or fi.size == 0:
                    disk.write_metadata(bucket, object_name, new_fi)
                else:
                    tmp_path = f"tmp/{upload_id}/{j}"
                    for part in parts:
                        disk.create_file(
                            META_BUCKET,
                            f"{tmp_path}/part.{part.number}",
                            rebuilt_files[j][part.number],
                        )
                    disk.rename_data(META_BUCKET, tmp_path, new_fi, bucket, object_name)
                healed += 1
                state[drive_index] = "healed"
            except errors.DiskError:
                continue
        result.after_drive_state = state
        result.disks_healed = healed
        return result
