"""Pool lifecycle: attach-pool expansion, checkpointed decommission, status.

Role of the reference's pool lifecycle machinery (cmd/erasure-server-pool-
decom.go + cmd/erasure-server-pool-mgmt.go): the ServerPools list stops
being a boot-time constant and becomes a managed set. Three operations:

  * attach   -- a new pool joins a running cluster. Two-phase: the pool is
                added SUSPENDED, the bumped pool-config epoch is persisted
                and fanned out to every peer (dist/peer.py `poolsreload`),
                and only once the cluster agrees on the pool set is the
                pool flipped ACTIVE so new writes may land on it.
  * drain    -- decommission: walk the pool's namespace through the
                metacache resume-cursor discipline, re-PUT every version
                into the remaining pools with the existing erasure PUT
                path, delete the source copy, checkpoint the (bucket,
                object) cursor like control/healmgr.HealingTracker so a
                crash or node kill RESUMES instead of restarting.
  * status   -- per-pool capacity/used/objects + drain progress, served by
                GET /mtpu/admin/v1/pools/status and the minio_tpu_pool_*
                gauges in control/metrics.py.

Pool statuses live on ServerPools (object/pools.py) so placement decisions
never need this module; the manager owns transitions, persistence (the
pool-config epoch + drain trackers are journaled into SYS_DIR on every
pool's set-0 drives, storage/recovery.py-style: readable after any single
pool is lost), and the background drain/rebalance threads.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass

from ..control.perf import GLOBAL_PERF
from ..control.sanitizer import san_lock
from ..storage.format import SYS_DIR
from ..storage.xlmeta import XLMeta
from ..utils import errors
from . import metadata as meta_mod
from .pools import (
    POOL_ACTIVE,
    POOL_DECOMMISSIONED,
    POOL_DRAINING,
    POOL_SUSPENDED,
    ServerPools,
)

log = logging.getLogger("minio_tpu.pool")

CONFIG_FILE = "pools/config.json"
DRAIN_FILE = "pools/drain-{}.json"

# Verification passes after the namespace first reads empty: in-flight
# multipart commits and racing PUTs that slipped into the draining pool
# behind the walk are re-swept, bounded so a write loop cannot pin the
# drain forever (the reference re-lists after decom for the same reason).
MAX_DRAIN_ROUNDS = 5

_GAUGE_TTL_S = 5.0  # per-pool data walk cache for /metrics + /pools/status


class PoolLifecycleStats:
    """Process-wide pool-lifecycle counters, rendered as minio_tpu_pool_*
    in control/metrics.py (the mtpulint metrics-rendered rule holds every
    counter bumped here to that exposition)."""

    def __init__(self):
        self._lock = san_lock("PoolLifecycleStats._lock")
        self.pools_attached = 0
        self.epoch_bumps = 0
        self.decommissions_started = 0
        self.decommissions_resumed = 0
        self.decommissions_completed = 0
        self.objects_moved = 0
        self.bytes_moved = 0
        self.move_failures = 0
        self.checkpoints = 0
        self.rebalance_rounds = 0

    def note_attach(self) -> None:
        with self._lock:
            self.pools_attached += 1

    def note_epoch(self) -> None:
        with self._lock:
            self.epoch_bumps += 1

    def note_decommission(self, event: str) -> None:
        with self._lock:
            if event == "started":
                self.decommissions_started += 1
            elif event == "resumed":
                self.decommissions_resumed += 1
            elif event == "completed":
                self.decommissions_completed += 1

    def note_move(self, nbytes: int) -> None:
        with self._lock:
            self.objects_moved += 1
            self.bytes_moved += nbytes

    def note_move_failure(self) -> None:
        with self._lock:
            self.move_failures += 1

    def note_checkpoint(self) -> None:
        with self._lock:
            self.checkpoints += 1

    def note_rebalance_round(self) -> None:
        with self._lock:
            self.rebalance_rounds += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                k: v for k, v in self.__dict__.items() if not k.startswith("_")
            }


STATS = PoolLifecycleStats()


@dataclass
class DecommissionTracker:
    """Drain progress journaled to the surviving pools' drives (the
    HealingTracker discipline of control/healmgr.py, persisted OFF the
    dying pool): a node killed mid-drain resumes from the last
    checkpointed (bucket, object) cursor instead of re-walking."""

    pool_index: int = 0
    started: float = 0.0
    last_update: float = 0.0
    finished: bool = False
    failed: str = ""
    objects_moved: int = 0
    objects_failed: int = 0
    bytes_moved: int = 0
    checkpoints: int = 0
    # Resume cursor: buckets and objects walk in sorted order; restart
    # skips buckets < resume_bucket and, within it, names <= resume_object.
    resume_bucket: str = ""
    resume_object: str = ""

    def save(self, pools: ServerPools) -> None:
        self.last_update = time.time()
        _write_sys(
            pools,
            DRAIN_FILE.format(self.pool_index),
            json.dumps(asdict(self)).encode(),
            exclude=self.pool_index,
        )

    @staticmethod
    def load(pools: ServerPools, pool_index: int) -> "DecommissionTracker | None":
        raw = _read_sys(pools, DRAIN_FILE.format(pool_index))
        if raw is None:
            return None
        try:
            return DecommissionTracker(**json.loads(raw.decode()))
        except (ValueError, TypeError):
            return None


def _sys_drives(pools: ServerPools, exclude: int = -1, per_pool: int = 2):
    """First N online set-0 drives of every pool (minus `exclude`): the
    replica set the pool config + drain journals are written to. Reads scan
    the same drives, so the journal survives losing any one pool."""
    for pi, p in enumerate(pools.pools):
        if pi == exclude or not p.sets:
            continue
        n = 0
        for d in p.sets[0].disks:
            if d is None or not d.is_online():
                continue
            yield d
            n += 1
            if n >= per_pool:
                break


def _write_sys(pools: ServerPools, path: str, blob: bytes, exclude: int = -1) -> None:
    for d in _sys_drives(pools, exclude=exclude):
        try:
            d.write_all(SYS_DIR, path, blob)
        except errors.StorageError:
            continue


def _read_sys(pools: ServerPools, path: str) -> bytes | None:
    for d in _sys_drives(pools):
        try:
            return d.read_all(SYS_DIR, path)
        except errors.StorageError:
            continue
    return None


class PoolManager:
    """Owns pool statuses, the pool-config epoch, attach/decommission
    transitions, and the drain/rebalance worker threads. One per node
    (dist/node.py builds it right after the peer NotificationSys); a bare
    ServerPools works too (unit tests) -- attach-by-endpoints and fanout
    are then unavailable, everything else behaves."""

    def __init__(self, pools: ServerPools, notification=None, node=None):
        self.pools = pools
        self.notification = notification
        self.node = node
        self.epoch = 0
        self._lock = san_lock("PoolManager._lock")
        self._drain_threads: dict[int, threading.Thread] = {}
        self._drain_stops: dict[int, threading.Event] = {}
        self.trackers: dict[int, DecommissionTracker] = {}
        self._gauge_cache: dict[int, tuple[float, int, int]] = {}
        from ..control.rebalance import RebalanceEngine

        self.rebalancer = RebalanceEngine(pools, stats=STATS)
        # Raw endpoint specs per pool index (None for boot pools built from
        # an endpoint list the node already knows, or pools with no node).
        self._endpoints: dict[int, list[str]] = {}

    # -- config persistence ---------------------------------------------------

    def _persist(self) -> None:
        doc = {
            "epoch": self.epoch,
            "pools": [
                {
                    "endpoints": self._endpoints.get(i),
                    "status": self.pools.statuses[i],
                }
                for i in range(len(self.pools.pools))
            ],
        }
        _write_sys(self.pools, CONFIG_FILE, json.dumps(doc).encode())

    def _bump_epoch_and_fanout(self) -> None:
        """Persist the new pool config under a bumped epoch, then tell every
        peer to reload it. Callers mutate statuses BEFORE calling this, so
        by the time the fanout returns, all reachable nodes agree."""
        self.epoch += 1
        STATS.note_epoch()
        self._persist()
        if self.notification is not None:
            self.notification.pools_reload_all()

    def load_config(self) -> bool:
        """Apply the persisted pool config if its epoch is newer than ours:
        statuses by index, and (when a node callback is available) attach
        any pool this process has not built yet. Returns True if applied."""
        raw = _read_sys(self.pools, CONFIG_FILE)
        if raw is None:
            return False
        try:
            doc = json.loads(raw.decode())
        except ValueError:
            return False
        epoch = int(doc.get("epoch", 0))
        if epoch <= self.epoch:
            return False
        entries = doc.get("pools", [])
        for i, ent in enumerate(entries):
            if i >= len(self.pools.pools):
                eps = ent.get("endpoints")
                if self.node is None or not eps:
                    log.warning(
                        "pool %d in persisted config has no buildable "
                        "endpoints on this node; skipped", i,
                    )
                    continue
                try:
                    sets = self.node.build_pool_from_endpoints(eps)
                except errors.StorageError as e:
                    log.error("cannot build persisted pool %d: %s", i, e)
                    continue
                self._replicate_buckets(sets)
                self.pools.add_pool(sets, status=ent.get("status", POOL_SUSPENDED))
                self._endpoints[i] = list(eps)
                if hasattr(self.node, "_wire_new_pool"):
                    self.node._wire_new_pool(sets)
            else:
                if ent.get("endpoints"):
                    self._endpoints[i] = list(ent["endpoints"])
                self.pools.set_pool_status(i, ent.get("status", POOL_ACTIVE))
        self.epoch = epoch
        return True

    def resume_pending(self) -> list[int]:
        """Restart the drain of every pool the persisted config left in
        DRAINING (the crash/kill recovery path): the tracker's checkpointed
        cursor picks up where the dead process stopped."""
        resumed = []
        for i, st in enumerate(self.pools.statuses):
            if st != POOL_DRAINING or i in self._drain_threads:
                continue
            tracker = DecommissionTracker.load(self.pools, i)
            if tracker is None or tracker.finished:
                tracker = DecommissionTracker(pool_index=i, started=time.time())
            tracker.failed = ""  # fresh attempt; the crash note served its turn
            STATS.note_decommission("resumed")
            self._spawn_drain(i, tracker)
            resumed.append(i)
        return resumed

    # -- attach ---------------------------------------------------------------

    def attach(self, sets, endpoints: list[str] | None = None) -> int:
        """Attach an already-built ErasureSets as a new pool. Two-phase so
        no node routes a write to the pool before the whole cluster knows
        it exists: SUSPENDED + epoch fanout first, ACTIVE + epoch fanout
        second."""
        from ..control import tracing

        with tracing.span("attach", "pool", pools=len(self.pools.pools) + 1):
            with self._lock:
                self._replicate_buckets(sets)
                idx = self.pools.add_pool(sets, status=POOL_SUSPENDED)
                if endpoints:
                    self._endpoints[idx] = list(endpoints)
                self._bump_epoch_and_fanout()
                # Every peer now agrees pool `idx` exists (suspended):
                # flipping it ACTIVE cannot race a write from a node that
                # would route it to a pool set without the newcomer.
                self.pools.set_pool_status(idx, POOL_ACTIVE)
                self._bump_epoch_and_fanout()
            STATS.note_attach()
        return idx

    def _replicate_buckets(self, sets) -> None:
        """Existing buckets must exist on a joining pool before any write
        can be placed there (the reference heals buckets into new pools)."""
        try:
            buckets = self.pools.list_buckets()
        except errors.StorageError:
            return
        for bi in buckets:
            try:
                sets.make_bucket(bi.name)
            except (errors.ObjectError, errors.StorageError):
                continue

    def attach_endpoints(self, endpoints: list[str]) -> int:
        """Attach a pool from raw endpoint specs (the admin POST body).
        Needs the node: drive construction is an endpoint concern."""
        if self.node is None:
            raise errors.InvalidArgument(
                "", "", "attach by endpoints needs a running node"
            )
        return self.node.attach_pool(endpoints)

    # -- decommission ----------------------------------------------------------

    def start_decommission(
        self, pool_index: int, wait: bool = False,
        checkpoint_every: int | None = None,
    ) -> DecommissionTracker:
        with self._lock:
            if not 0 <= pool_index < len(self.pools.pools):
                raise errors.InvalidArgument("", "", f"no pool {pool_index}")
            active = [
                i for i, st in enumerate(self.pools.statuses)
                if st == POOL_ACTIVE and i != pool_index
            ]
            if not active:
                raise errors.InvalidArgument(
                    "", "", "cannot drain the last active pool"
                )
            st = self.pools.statuses[pool_index]
            if st == POOL_DRAINING:
                raise errors.InvalidArgument("", "", f"pool {pool_index} already draining")
            if st == POOL_DECOMMISSIONED:
                raise errors.InvalidArgument("", "", f"pool {pool_index} already decommissioned")
            self.pools.set_pool_status(pool_index, POOL_DRAINING)
            self._bump_epoch_and_fanout()
            tracker = DecommissionTracker(pool_index=pool_index, started=time.time())
            if checkpoint_every is not None:
                self._checkpoint_every = checkpoint_every
            tracker.save(self.pools)
            STATS.note_decommission("started")
            t = self._spawn_drain(pool_index, tracker, checkpoint_every)
        if wait:
            t.join()
        return tracker

    def _spawn_drain(
        self, pool_index: int, tracker: DecommissionTracker,
        checkpoint_every: int | None = None,
    ) -> threading.Thread:
        stop = threading.Event()
        self._drain_stops[pool_index] = stop
        self.trackers[pool_index] = tracker

        def run():
            try:
                self._drain(pool_index, tracker, stop, checkpoint_every)
            except Exception as e:  # noqa: BLE001 - drain thread must not die silently
                tracker.failed = f"{type(e).__name__}: {e}"[:300]
                try:
                    tracker.save(self.pools)
                except errors.StorageError:
                    pass
                log.error("drain of pool %d failed: %s", pool_index, e)

        t = threading.Thread(
            target=run, daemon=True, name=f"pool-drain-{pool_index}"
        )
        self._drain_threads[pool_index] = t
        t.start()
        return t

    def _pool_buckets(self, pool) -> list[str]:
        """Every volume present on the pool's drives -- INCLUDING system
        buckets: config-store objects living on a drained pool must move
        with everything else or a restart loses them. Raw non-object files
        (format.json, journals, metacache images) are invisible to the
        object walk and stay put; the drained pool keeps its volumes."""
        names: set[str] = set()
        for s in pool.sets:
            for d in s.disks:
                if d is None:
                    continue
                try:
                    names.update(v.name for v in d.list_vols())
                except errors.StorageError:
                    continue
        return sorted(names)

    @staticmethod
    def _iter_entries(pool, bucket: str, marker: str):
        """Error-tolerant namespace walk: volumes that hold only raw files
        (persisted metacache images, journals) fail the object walk with
        BucketNotFound on most drives -- skip them, they carry no objects."""
        try:
            yield from pool.metacache.entries_from(bucket, "", marker)
        except errors.StorageError as e:
            log.debug("walk of %s skipped: %s", bucket, e)

    def _drain(
        self, pool_index: int, tracker: DecommissionTracker,
        stop: threading.Event, checkpoint_every: int | None = None,
    ) -> None:
        """The decommission state machine body: DRAINING -> (walk + move +
        checkpoint)* -> verify-empty -> DECOMMISSIONED. Runs on the drain
        thread; also callable synchronously (tests inject crashes here)."""
        from ..control.rebalance import ObjectMover, ThrottleBudget

        pool = self.pools.pools[pool_index]
        every = checkpoint_every or int(os.environ.get("MTPU_DECOM_CHECKPOINT", "64"))
        workers = max(1, int(os.environ.get("MTPU_DECOM_WORKERS", "4")))
        mover = ObjectMover(self.pools, ThrottleBudget(), stats=STATS)
        t0 = time.perf_counter()
        c0 = time.thread_time()
        try:
            for _round in range(MAX_DRAIN_ROUNDS):
                for bucket in self._pool_buckets(pool):
                    if bucket < tracker.resume_bucket:
                        continue
                    marker = (
                        tracker.resume_object
                        if bucket == tracker.resume_bucket else ""
                    )
                    batch: list[tuple[str, bytes]] = []
                    for name, raw in self._iter_entries(pool, bucket, marker):
                        if stop.is_set():
                            tracker.save(self.pools)
                            return
                        batch.append((name, raw))
                        if len(batch) >= workers:
                            self._move_batch(
                                pool_index, bucket, batch, mover, tracker, every
                            )
                            batch = []
                    if batch:
                        self._move_batch(
                            pool_index, bucket, batch, mover, tracker, every
                        )
                    # Past-the-end marker: resume skips the whole bucket.
                    tracker.resume_bucket, tracker.resume_object = bucket, "￿"
                if self._pool_object_count(pool) == 0:
                    break
                # Writers raced the walk (multipart commits in flight when
                # the drain started): rescan from the top.
                tracker.resume_bucket = tracker.resume_object = ""
                tracker.save(self.pools)
            else:
                raise errors.StorageError(
                    f"pool {pool_index} still non-empty after "
                    f"{MAX_DRAIN_ROUNDS} drain rounds"
                )
            with self._lock:
                self.pools.set_pool_status(pool_index, POOL_DECOMMISSIONED)
                self._bump_epoch_and_fanout()
            tracker.finished = True
            tracker.save(self.pools)
            STATS.note_decommission("completed")
            log.info(
                "pool %d decommissioned: %d objects / %d bytes moved "
                "(%d failed)", pool_index, tracker.objects_moved,
                tracker.bytes_moved, tracker.objects_failed,
            )
        finally:
            GLOBAL_PERF.ledger.record(
                "pool", "drain",
                time.perf_counter() - t0, time.thread_time() - c0,
            )

    def _move_batch(
        self, pool_index: int, bucket: str, batch: list,
        mover, tracker: DecommissionTracker, every: int,
    ) -> None:
        src = self.pools.pools[pool_index]

        def one(item):
            name, raw = item
            dst = self._placement_pool(exclude=pool_index)
            return mover.move(src, dst, bucket, name, raw)

        for (res, err), (name, _raw) in zip(
            meta_mod.parallel_map(one, batch), batch
        ):
            if err is not None:
                tracker.objects_failed += 1
                STATS.note_move_failure()
                log.warning("drain move %s/%s failed: %s", bucket, name, err)
            else:
                tracker.objects_moved += 1
                tracker.bytes_moved += int(res or 0)
        tracker.resume_bucket = bucket
        tracker.resume_object = batch[-1][0]
        if tracker.objects_moved // every != (
            tracker.objects_moved - len(batch)
        ) // every:
            tracker.checkpoints += 1
            tracker.save(self.pools)
            STATS.note_checkpoint()
        hook = getattr(self, "_drain_hook", None)
        if hook is not None:
            hook(tracker)

    def _placement_pool(self, exclude: int):
        """Most-free ACTIVE pool other than `exclude` (deterministic, the
        same (free, index) order _pool_with_space uses)."""
        best = None
        best_key = None
        for i, p in enumerate(self.pools.pools):
            if i == exclude or self.pools.statuses[i] != POOL_ACTIVE:
                continue
            free = 0
            for d in p.disks:
                if d is None:
                    continue
                try:
                    free += d.disk_info().free
                except errors.DiskError:
                    continue
            key = (-free, i)
            if best_key is None or key < best_key:
                best, best_key = p, key
        if best is None:
            raise errors.StorageError("no active pool to drain into")
        return best

    # -- rebalance -------------------------------------------------------------

    def start_rebalance(self, threshold: float | None = None) -> dict:
        self.rebalancer.start(threshold=threshold)
        return self.rebalancer.status()

    def stop_rebalance(self) -> dict:
        self.rebalancer.stop()
        return self.rebalancer.status()

    # -- status / gauges -------------------------------------------------------

    def _pool_object_count(self, pool) -> int:
        n = 0
        for bucket in self._pool_buckets(pool):
            for _name, _raw in self._iter_entries(pool, bucket, ""):
                n += 1
        return n

    def pool_gauges(self, pool_index: int) -> dict:
        """capacity/free from disk_info; objects/data bytes from a merged
        namespace walk, TTL-cached so /metrics scrapes stay cheap."""
        pool = self.pools.pools[pool_index]
        total = free = 0
        for d in pool.disks:
            if d is None:
                continue
            try:
                di = d.disk_info()
                total += di.total
                free += di.free
            except errors.DiskError:
                continue
        now = time.monotonic()
        cached = self._gauge_cache.get(pool_index)
        if cached is not None and now - cached[0] < _GAUGE_TTL_S:
            objects, data_bytes = cached[1], cached[2]
        else:
            objects = data_bytes = 0
            for bucket in self._pool_buckets(pool):
                try:
                    for _name, raw in pool.metacache.entries_from(bucket, "", ""):
                        objects += 1
                        try:
                            meta = XLMeta.from_bytes(raw)
                        except errors.StorageError:
                            continue
                        data_bytes += sum(
                            v.size for v in meta.versions if not v.deleted
                        )
                except errors.StorageError:
                    continue
            self._gauge_cache[pool_index] = (now, objects, data_bytes)
        return {
            "index": pool_index,
            "status": self.pools.statuses[pool_index],
            "capacity_bytes": total,
            "free_bytes": free,
            "data_bytes": data_bytes,
            "objects": objects,
        }

    def status(self) -> dict:
        out = {
            "epoch": self.epoch,
            "stats": STATS.snapshot(),
            "rebalance": self.rebalancer.status(),
            "pools": [],
        }
        for i in range(len(self.pools.pools)):
            row = self.pool_gauges(i)
            # Freshest of the in-memory tracker and the journal: after a
            # local kill another node may have resumed the drain, and its
            # checkpoints land in the journal, not in this process.
            mem = self.trackers.get(i)
            disk = DecommissionTracker.load(self.pools, i)
            tracker = mem
            if disk is not None and (
                mem is None or disk.last_update >= mem.last_update
            ):
                tracker = disk
            if tracker is not None:
                row["drain"] = asdict(tracker)
            out["pools"].append(row)
        return out

    # -- lifecycle -------------------------------------------------------------

    def join(self, timeout: float = 60.0) -> None:
        """Wait out running drains (tests + decommission --wait)."""
        for t in list(self._drain_threads.values()):
            t.join(timeout)
        self.rebalancer.join(timeout)

    def stop(self) -> None:
        """Stop drain + rebalance workers; drains checkpoint their cursor
        on the way out so a later resume_pending continues, not restarts."""
        for ev in self._drain_stops.values():
            ev.set()
        self.rebalancer.stop()
        for t in self._drain_threads.values():
            t.join(10.0)
