"""Metadata quorum logic: agreeing on an object's state across drives.

Role of cmd/erasure-metadata.go + erasure-metadata-utils.go: read xl.meta
from every drive, find the version agreed by a read quorum
(findFileInfoInQuorum), compute read/write quorums from the geometry, and
decide per-drive freshness for healing.
"""

from __future__ import annotations

import contextvars
from concurrent.futures import ThreadPoolExecutor

from ..storage.interface import StorageAPI
from ..storage.types import FileInfo
from ..utils import errors

# Shared pool for fan-out drive IO. The reference bounds per-call concurrency
# with errgroup (internal/sync/errgroup); a process-wide pool does the same.
_POOL = ThreadPoolExecutor(max_workers=64, thread_name_prefix="drive-io")


def parallel_map(fn, items):
    """Run fn over items concurrently; return ordered [(result, error)].

    Each task runs under a copy of the CALLER's contextvars (pool threads
    don't inherit them), so the request trace context follows the fan-out
    into per-drive storage calls."""
    ctx = contextvars.copy_context()

    def wrap(item):
        try:
            return ctx.copy().run(fn, item), None
        except Exception as e:  # noqa: BLE001 - error values are the contract
            return None, e

    return list(_POOL.map(wrap, items))


def parallel_submit(fn, items):
    """Like parallel_map but returns futures of (result, error) immediately
    — the read-ahead primitive (klauspost/readahead's role: issue the next
    window's drive reads while the current one decodes)."""
    ctx = contextvars.copy_context()

    def wrap(item):
        try:
            return ctx.copy().run(fn, item), None
        except Exception as e:  # noqa: BLE001
            return None, e

    return [_POOL.submit(wrap, item) for item in items]


def read_all_file_info(
    disks: list[StorageAPI | None], bucket: str, path: str, version_id: str = ""
) -> tuple[list[FileInfo | None], list[Exception | None]]:
    """ReadVersion from every drive in parallel (readAllFileInfo,
    cmd/erasure-metadata-utils.go:122)."""

    def read_one(disk):
        if disk is None:
            raise errors.DiskNotFound()
        return disk.read_version(bucket, path, version_id)

    results = parallel_map(read_one, disks)
    return [r for r, _ in results], [e for _, e in results]


def _quorum_key(fi: FileInfo) -> tuple:
    return (
        round(fi.mod_time, 6),
        fi.version_id,
        fi.data_dir,
        fi.deleted,
        fi.size,
        fi.erasure.data_blocks,
        fi.erasure.parity_blocks,
    )


def find_file_info_in_quorum(
    metas: list[FileInfo | None], quorum: int
) -> FileInfo:
    """Pick the FileInfo agreed by >= quorum drives
    (findFileInfoInQuorum, cmd/erasure-metadata.go)."""
    counts: dict[tuple, int] = {}
    rep: dict[tuple, FileInfo] = {}
    for fi in metas:
        if fi is None:
            continue
        k = _quorum_key(fi)
        counts[k] = counts.get(k, 0) + 1
        rep.setdefault(k, fi)
    if counts:
        k = max(counts, key=lambda k: counts[k])
        if counts[k] >= quorum:
            return rep[k]
    raise errors.ErasureReadQuorum(msg="no metadata quorum")


def object_quorum_from_meta(
    metas: list[FileInfo | None], errs: list[Exception | None], default_parity: int
) -> tuple[int, int]:
    """(read_quorum, write_quorum) from the latest metadata
    (objectQuorumFromMeta, cmd/erasure-object.go:62 equivalent)."""
    for fi in metas:
        if fi is not None and fi.erasure.data_blocks:
            d, p = fi.erasure.data_blocks, fi.erasure.parity_blocks
            return d, (d + 1 if d == p else d)
    n = len(metas)
    d = n - default_parity
    return d, (d + 1 if d == default_parity else d)


def list_online_disks(
    disks: list[StorageAPI | None],
    metas: list[FileInfo | None],
    errs: list[Exception | None],
    quorum_fi: FileInfo,
) -> list[StorageAPI | None]:
    """Drives whose metadata matches the quorum version; others -> None
    (listOnlineDisks, cmd/erasure-healing-common.go)."""
    want = _quorum_key(quorum_fi)
    out: list[StorageAPI | None] = []
    for disk, fi in zip(disks, metas):
        if disk is not None and fi is not None and _quorum_key(fi) == want:
            out.append(disk)
        else:
            out.append(None)
    return out


def shuffle_disks_by_index(
    disks: list[StorageAPI | None], distribution: list[int]
) -> list[StorageAPI | None]:
    """Reorder so position j holds the drive storing shard j
    (shuffleDisks, cmd/erasure-metadata-utils.go): drive i holds shard
    distribution[i]-1."""
    if not distribution:
        return list(disks)
    shuffled: list[StorageAPI | None] = [None] * len(disks)
    for i, disk in enumerate(disks):
        shuffled[distribution[i] - 1] = disk
    return shuffled
