"""Metacache: persistent, resumable listing caches.

The reference never re-walks drives for every ListObjects page: listPath
(cmd/metacache-server-pool.go:59) looks up / creates a per-(bucket, prefix)
metacache, streamMetadataParts (cmd/metacache-set.go:349) serves pages out of
persisted cache blocks with resume cursors, and WalkDir (metacache-walk.go:62)
only runs when the cache is absent or stale. This module is the TPU build's
equivalent: one merged walk fills an in-memory sorted entry list; subsequent
pages bisect into it; bucket writes invalidate; a msgpack image is persisted
under the meta bucket so a restarted process can serve the first page without
a cold walk.

Coherence model (same tradeoff the reference makes): caches may serve a
listing a few seconds stale. Local writes invalidate immediately via the
write-generation counter; remote writers are bounded by the TTL.
"""

from __future__ import annotations

import bisect
import hashlib
import struct
import threading
import time

import msgpack

from ..control.logging import GLOBAL_LOGGER
from ..control.sanitizer import san_lock, san_rlock

META_BUCKET = ".minio.sys"

# Persisted image layout: 8-byte big-endian unix-time header, then the
# msgpack body. The header lets a reader reject a stale image without
# unpacking a potentially multi-MB entry list.
_HDR = struct.Struct(">d")

# How long a filled cache may serve pages before a fresh walk is forced.
DEFAULT_TTL_S = 15.0
# Entry cap: a listing bigger than this is served straight from the walk
# (memory bound; the reference bounds cache block count similarly).
MAX_ENTRIES = 500_000


class _Cache:
    """One filled listing: sorted names + raw xl.meta images."""

    __slots__ = ("names", "raws", "filled_at", "generation")

    def __init__(self, names: list[str], raws: list[bytes], generation: int):
        self.names = names
        self.raws = raws
        self.filled_at = time.monotonic()
        self.generation = generation


def cache_path(bucket: str, prefix: str) -> str:
    """On-disk cache image path under the meta bucket (persistence parity
    with putMetacacheObject, cmd/metacache-set.go write-back blocks)."""
    h = hashlib.sha256(f"{bucket}\0{prefix}".encode()).hexdigest()[:16]
    return f"buckets/{bucket}/.metacache/{h}"


class MetacacheManager:
    """Per-namespace listing cache manager.

    `walk` is the expensive merged-drive walk: fn(bucket, prefix) -> iterator
    of (name, raw). `persist`/`load` write/read a cache image under the meta
    bucket (best effort; None disables persistence).
    """

    def __init__(self, walk, persist=None, load=None, ttl_s: float = DEFAULT_TTL_S):
        self._walk = walk
        self._persist = persist
        self._load = load
        self.ttl_s = ttl_s
        self._caches: dict[tuple[str, str], _Cache] = {}
        self._generations: dict[str, int] = {}
        # Persisted images are only worth consulting once per (bucket,
        # prefix) per process: after that, either the in-memory cache or a
        # walk is strictly fresher.
        self._cold_checked: set[tuple[str, str]] = set()
        self._lock = san_lock("MetacacheManager._lock")
        # Instrumentation: tests pin that paging does not re-walk per page.
        self.walks = 0
        self.hits = 0

    # -- invalidation ------------------------------------------------------

    def generation(self, bucket: str) -> int:
        with self._lock:
            return self._generations.get(bucket, 0)

    def invalidate(self, bucket: str) -> None:
        """Called on every namespace write to the bucket."""
        with self._lock:
            self._generations[bucket] = self._generations.get(bucket, 0) + 1
            stale = [k for k in self._caches if k[0] == bucket]
            for k in stale:
                del self._caches[k]

    # -- lookup ------------------------------------------------------------

    def _valid(self, c: _Cache, bucket: str) -> bool:
        return (
            c.generation == self.generation(bucket)
            and time.monotonic() - c.filled_at < self.ttl_s
        )

    def entries_from(self, bucket: str, prefix: str, marker: str):
        """Iterate (name, raw) with name > marker, from cache when valid.

        Fills the cache on miss (one walk), persists the image, and serves
        the page by bisect -- the resume-cursor discipline of
        cmd/metacache-set.go:349.
        """
        key = (bucket, prefix)
        with self._lock:
            cache = self._caches.get(key)
            check_cold = key not in self._cold_checked
            self._cold_checked.add(key)
        if cache is not None and self._valid(cache, bucket):
            self.hits += 1
            return self._page(cache, marker)
        if check_cold:
            cache = self._load_persisted(bucket, prefix)
            if cache is not None:
                with self._lock:
                    self._caches[key] = cache
                self.hits += 1
                return self._page(cache, marker)
        return self._fill(key, marker)

    def _page(self, cache: _Cache, marker: str):
        start = bisect.bisect_right(cache.names, marker) if marker else 0
        names, raws = cache.names, cache.raws
        for i in range(start, len(names)):
            yield names[i], raws[i]

    def _fill(self, key: tuple[str, str], marker: str):
        """Run the walk to completion, cache + persist, then serve the page.

        The walk was already fully materialized per List call before this
        module existed (the merged-quorum resolve needs every drive's view),
        so paying it once and then paging by cursor strictly dominates.
        """
        bucket, prefix = key
        generation = self.generation(bucket)
        self.walks += 1
        names: list[str] = []
        raws: list[bytes] = []
        for name, raw in self._walk(bucket, prefix):
            names.append(name)
            raws.append(raw)
        cache = _Cache(names, raws, generation)
        if len(names) <= MAX_ENTRIES:
            with self._lock:
                self._caches[key] = cache
            if self._persist is not None:
                try:
                    body = msgpack.packb(
                        {"v": 1, "bucket": bucket, "prefix": prefix,
                         "entries": list(zip(names, raws))},
                        use_bin_type=True,
                    )
                    self._persist(
                        cache_path(bucket, prefix), _HDR.pack(time.time()) + body
                    )
                except Exception as e:  # noqa: BLE001 - persistence is best effort
                    GLOBAL_LOGGER.log_once(
                        f"metacache persist failed for {bucket}/{prefix}: {e}",
                        key="metacache-persist",
                    )
        return self._page(cache, marker)

    def _load_persisted(self, bucket: str, prefix: str) -> _Cache | None:
        """Cold-start reuse of a persisted image, bounded by wall-clock TTL.

        Only consulted once per key per process, before the first walk (a
        fresh process has no in-memory cache); the write-generation guard
        cannot span restarts, so the TTL alone bounds staleness here. The
        image's remaining TTL is its ORIGINAL one: filled_at is backdated by
        the image's age so a 14s-old image serves for 1s more, not 15s.
        """
        if self._load is None:
            return None
        with self._lock:
            if self._generations.get(bucket, 0) != 0:
                return None  # bucket already written in this process: walk
        try:
            blob = self._load(cache_path(bucket, prefix))
            age = time.time() - _HDR.unpack(blob[: _HDR.size])[0]
            if not 0 <= age <= self.ttl_s:
                return None
            doc = msgpack.unpackb(blob[_HDR.size :], raw=False)
            if doc.get("v") != 1:
                return None
            names = [n for n, _ in doc["entries"]]
            raws = [r for _, r in doc["entries"]]
            cache = _Cache(names, raws, self.generation(bucket))
            cache.filled_at -= age
            return cache
        except Exception:  # noqa: BLE001
            return None
