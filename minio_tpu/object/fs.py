"""FS backend: single-mount plain-file ObjectLayer (no erasure).

Role of the reference's fs-v1 backend (cmd/fs-v1.go:119 NewFSObjectLayer,
fs-v1-multipart.go, fs-v1-metadata.go, format-fs.go): objects are plain
files under <root>/<bucket>/<object>, per-object metadata lives in an
`fs.json` analogue under the sys prefix, multipart parts stage under the
sys prefix and concatenate on complete. Selected for single-path
deployments (server-main.go:636-643 picks FS for one endpoint).
Versioning is not supported (as in the reference's FS mode); versioned
requests behave as unversioned with a "null" version id.

The NAS gateway (cmd/gateway/nas) is this same layer pointed at a shared
mount — see gateway.py.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import time
import uuid

from ..storage.local import FSYNC_NEVER, fsync_mode
from ..storage.types import ObjectPartInfo
from ..utils import errors
from .types import (
    BucketInfo,
    DeleteObjectOptions,
    GetObjectOptions,
    HealResultItem,
    ListObjectsInfo,
    ListObjectVersionsInfo,
    ObjectInfo,
    PutObjectOptions,
)

SYS_PREFIX = ".minio_tpu.sys"
META_DIR = os.path.join(SYS_PREFIX, "fs-meta")
MULTIPART_DIR = os.path.join(SYS_PREFIX, "fs-multipart")
FORMAT_FILE = os.path.join(SYS_PREFIX, "format-fs.json")


def _valid_bucket(bucket: str) -> bool:
    return bool(bucket) and not bucket.startswith(".") and "/" not in bucket


class FSObjectLayer:
    """ObjectLayer over one filesystem path (fs-v1.go fsObjects role)."""

    supports_streaming = True  # put_object accepts .read(n) streams

    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, SYS_PREFIX), exist_ok=True)
        fmt = os.path.join(root, FORMAT_FILE)
        if not os.path.exists(fmt):
            with open(fmt, "w") as f:
                json.dump({"version": 1, "format": "fs", "id": str(uuid.uuid4())}, f)
        # ConfigStore and friends address layer.pools[0]; the FS layer is its
        # own single pool.
        self.pools = [self]
        self.ns_lock = None

    # -- paths ---------------------------------------------------------------

    def _bucket_path(self, bucket: str) -> str:
        return os.path.join(self.root, bucket)

    def _obj_path(self, bucket: str, object_name: str) -> str:
        p = os.path.normpath(os.path.join(self._bucket_path(bucket), object_name))
        if not p.startswith(os.path.normpath(self._bucket_path(bucket)) + os.sep):
            raise errors.InvalidArgument(msg=f"invalid object name {object_name!r}")
        return p

    def _meta_path(self, bucket: str, object_name: str) -> str:
        return os.path.join(self.root, META_DIR, bucket, object_name + ".json")

    def _check_bucket(self, bucket: str) -> None:
        if not os.path.isdir(self._bucket_path(bucket)):
            raise errors.BucketNotFound(bucket)

    # -- buckets -------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        if not _valid_bucket(bucket) and bucket != SYS_PREFIX and not bucket.startswith("."):
            raise errors.InvalidArgument(msg=f"invalid bucket name {bucket!r}")
        p = self._bucket_path(bucket)
        if os.path.isdir(p):
            raise errors.BucketExists(bucket)
        os.makedirs(p)

    def bucket_exists(self, bucket: str) -> bool:
        return os.path.isdir(self._bucket_path(bucket))

    def get_bucket_info(self, bucket: str) -> BucketInfo:
        self._check_bucket(bucket)
        st = os.stat(self._bucket_path(bucket))
        return BucketInfo(name=bucket, created=st.st_mtime)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._check_bucket(bucket)
        p = self._bucket_path(bucket)
        if not force and any(os.scandir(p)):
            raise errors.BucketNotEmpty(bucket)
        shutil.rmtree(p)
        shutil.rmtree(os.path.join(self.root, META_DIR, bucket), ignore_errors=True)

    def list_buckets(self) -> list[BucketInfo]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name.startswith("."):
                continue
            p = os.path.join(self.root, name)
            if os.path.isdir(p):
                out.append(BucketInfo(name=name, created=os.stat(p).st_mtime))
        return out

    # -- objects -------------------------------------------------------------

    def put_object(
        self, bucket: str, object_name: str, data,
        opts: PutObjectOptions | None = None,
    ) -> ObjectInfo:
        """data: bytes or a .read(n) stream (streamed straight to disk)."""
        opts = opts or PutObjectOptions()
        self._check_bucket(bucket)
        path = self._obj_path(bucket, object_name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp-{uuid.uuid4().hex}"
        md5h = hashlib.md5()
        size = 0
        try:
            with open(tmp, "wb") as f:
                if isinstance(data, (bytes, bytearray, memoryview)):
                    buf = bytes(data)
                    f.write(buf)
                    md5h.update(buf)
                    size = len(buf)
                else:
                    while True:
                        chunk = data.read(1 << 20)
                        if not chunk:
                            break
                        f.write(chunk)
                        md5h.update(chunk)
                        size += len(chunk)
                if fsync_mode() != FSYNC_NEVER:
                    f.flush()
                    os.fsync(f.fileno())
        except BaseException:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            raise
        os.replace(tmp, path)  # atomic commit (fs-v1 putObject rename)
        etag = opts.etag or md5h.hexdigest()
        meta = {
            "etag": etag,
            "content_type": opts.content_type,
            "mod_time": time.time(),
            "size": size,
            "user_defined": dict(opts.user_defined),
        }
        mp = self._meta_path(bucket, object_name)
        os.makedirs(os.path.dirname(mp), exist_ok=True)
        mtmp = mp + ".tmp"
        with open(mtmp, "w") as f:
            json.dump(meta, f)
            if fsync_mode() != FSYNC_NEVER:
                f.flush()
                os.fsync(f.fileno())
        os.replace(mtmp, mp)
        return self._info(bucket, object_name, meta)

    def _load_meta(self, bucket: str, object_name: str) -> dict:
        try:
            with open(self._meta_path(bucket, object_name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _info(self, bucket: str, object_name: str, meta: dict | None = None) -> ObjectInfo:
        path = self._obj_path(bucket, object_name)
        try:
            st = os.stat(path)
        except FileNotFoundError:
            raise errors.ObjectNotFound(bucket, object_name)
        if os.path.isdir(path):
            raise errors.ObjectNotFound(bucket, object_name)
        meta = meta if meta is not None else self._load_meta(bucket, object_name)
        user = dict(meta.get("user_defined", {}))
        return ObjectInfo(
            bucket=bucket,
            name=object_name,
            size=st.st_size,
            mod_time=meta.get("mod_time", st.st_mtime),
            etag=meta.get("etag", ""),
            content_type=meta.get("content_type", "application/octet-stream"),
            user_defined={k: v for k, v in user.items() if not k.startswith("x-internal-")},
            internal={k: v for k, v in user.items() if k.startswith("x-internal-")},
            version_id="",  # FS mode is unversioned
        )

    def get_object_info(
        self, bucket: str, object_name: str, opts: GetObjectOptions | None = None
    ) -> ObjectInfo:
        self._check_bucket(bucket)
        return self._info(bucket, object_name)

    def get_object(
        self, bucket: str, object_name: str,
        opts: GetObjectOptions | None = None, offset: int = 0, length: int = -1,
    ) -> tuple[ObjectInfo, bytes]:
        oi = self.get_object_info(bucket, object_name, opts)
        with open(self._obj_path(bucket, object_name), "rb") as f:
            if offset:
                f.seek(offset)
            data = f.read() if length < 0 else f.read(length)
        return oi, data

    def get_object_stream(
        self, bucket: str, object_name: str,
        opts: GetObjectOptions | None = None, offset: int = 0, length: int = -1,
    ):
        """(ObjectInfo, chunk iterator) — plain-file chunked reads."""
        oi = self.get_object_info(bucket, object_name, opts)
        end = oi.size if length < 0 else min(offset + length, oi.size)
        path = self._obj_path(bucket, object_name)

        def gen():
            remaining = end - offset
            if remaining <= 0:
                return
            with open(path, "rb") as f:
                f.seek(offset)
                while remaining > 0:
                    chunk = f.read(min(1 << 20, remaining))
                    if not chunk:
                        return
                    remaining -= len(chunk)
                    yield chunk

        return oi, gen()

    def put_object_metadata(
        self, bucket: str, object_name: str, version_id: str = "",
        updates: dict | None = None, removes: list | None = None,
    ) -> ObjectInfo:
        self._check_bucket(bucket)
        self._info(bucket, object_name)
        meta = self._load_meta(bucket, object_name)
        user = meta.setdefault("user_defined", {})
        for k in removes or []:
            user.pop(k, None)
        user.update(updates or {})
        mp = self._meta_path(bucket, object_name)
        os.makedirs(os.path.dirname(mp), exist_ok=True)
        with open(mp, "w") as f:
            json.dump(meta, f)
        return self._info(bucket, object_name, meta)

    def delete_object(
        self, bucket: str, object_name: str, opts: DeleteObjectOptions | None = None
    ) -> ObjectInfo:
        self._check_bucket(bucket)
        path = self._obj_path(bucket, object_name)
        try:
            os.remove(path)
        except FileNotFoundError:
            raise errors.ObjectNotFound(bucket, object_name)
        except IsADirectoryError:
            raise errors.ObjectNotFound(bucket, object_name)
        try:
            os.remove(self._meta_path(bucket, object_name))
        except OSError:
            pass
        # Trim now-empty parent dirs (plain FS keeps no empty prefixes).
        parent = os.path.dirname(path)
        stop = self._bucket_path(bucket)
        while parent != stop and os.path.isdir(parent) and not os.listdir(parent):
            os.rmdir(parent)
            parent = os.path.dirname(parent)
        return ObjectInfo(bucket=bucket, name=object_name)

    def delete_objects(self, bucket: str, objects, versioned: bool = False):
        out = []
        for name, _vid in objects:
            try:
                out.append((self.delete_object(bucket, name), None))
            except errors.StorageError as e:
                out.append((None, e))
        return out

    # -- listing -------------------------------------------------------------

    def _walk(self, bucket: str):
        """Yield object names in full-key lexicographic order (S3 listing
        contract): directories recurse in place, sorted with a trailing '/'
        so 'dir.txt' < 'dir/... ' compares like the flat keys do."""
        base = self._bucket_path(bucket)

        def recurse(d: str, rel: str):
            try:
                entries = list(os.scandir(d))
            except OSError:
                return
            entries.sort(key=lambda e: e.name + "/" if e.is_dir() else e.name)
            for e in entries:
                if e.is_dir():
                    yield from recurse(e.path, rel + e.name + "/")
                elif ".tmp-" not in e.name:
                    yield rel + e.name

        yield from recurse(base, "")

    def list_objects(
        self, bucket: str, prefix: str = "", marker: str = "",
        delimiter: str = "", max_keys: int = 1000,
    ) -> ListObjectsInfo:
        self._check_bucket(bucket)
        res = ListObjectsInfo()
        seen_prefixes: set[str] = set()
        count = 0
        for name in self._walk(bucket):
            if not name.startswith(prefix):
                continue
            display = name
            if delimiter:
                rest = name[len(prefix):]
                cut = rest.find(delimiter)
                if cut >= 0:
                    display = prefix + rest[: cut + len(delimiter)]
                    if display in seen_prefixes or (marker and display <= marker):
                        continue
                    if count >= max_keys:
                        res.is_truncated = True
                        res.next_marker = name
                        break
                    seen_prefixes.add(display)
                    res.prefixes.append(display)
                    count += 1
                    continue
            if marker and name <= marker:
                continue
            if count >= max_keys:
                res.is_truncated = True
                res.next_marker = name
                break
            res.objects.append(self._info(bucket, name))
            count += 1
        if res.is_truncated and not res.next_marker:
            last = res.objects[-1].name if res.objects else ""
            res.next_marker = last
        return res

    def list_object_versions(
        self, bucket: str, prefix: str = "", key_marker: str = "",
        version_marker: str = "", delimiter: str = "", max_keys: int = 1000,
    ) -> ListObjectVersionsInfo:
        listing = self.list_objects(bucket, prefix, key_marker, delimiter, max_keys)
        out = ListObjectVersionsInfo(
            is_truncated=listing.is_truncated,
            next_key_marker=listing.next_marker,
            prefixes=listing.prefixes,
        )
        for o in listing.objects:
            o.version_id = "null"
            out.objects.append(o)
        return out

    # -- multipart (fs-v1-multipart.go role) ----------------------------------

    def _upload_dir(self, upload_id: str) -> str:
        return os.path.join(self.root, MULTIPART_DIR, upload_id)

    def new_multipart_upload(
        self, bucket: str, object_name: str, opts: PutObjectOptions | None = None
    ) -> str:
        opts = opts or PutObjectOptions()
        self._check_bucket(bucket)
        upload_id = uuid.uuid4().hex
        d = self._upload_dir(upload_id)
        os.makedirs(d)
        with open(os.path.join(d, "upload.json"), "w") as f:
            json.dump(
                {
                    "bucket": bucket,
                    "object": object_name,
                    "initiated": time.time(),
                    "content_type": opts.content_type,
                    "user_defined": dict(opts.user_defined),
                },
                f,
            )
        return upload_id

    def _upload_info(self, bucket: str, object_name: str, upload_id: str) -> dict:
        try:
            with open(os.path.join(self._upload_dir(upload_id), "upload.json")) as f:
                info = json.load(f)
        except (OSError, ValueError):
            raise errors.InvalidUploadID(bucket, object_name, msg=f"upload {upload_id}")
        if info["bucket"] != bucket or info["object"] != object_name:
            raise errors.InvalidUploadID(bucket, object_name, msg=f"upload {upload_id}")
        return info

    def put_object_part(
        self, bucket: str, object_name: str, upload_id: str, part_number: int, data: bytes
    ):
        self._upload_info(bucket, object_name, upload_id)
        etag = hashlib.md5(data).hexdigest()
        with open(os.path.join(self._upload_dir(upload_id), f"part.{part_number}"), "wb") as f:
            f.write(data)
        with open(
            os.path.join(self._upload_dir(upload_id), f"part.{part_number}.json"), "w"
        ) as f:
            json.dump({"etag": etag, "size": len(data), "mod_time": time.time()}, f)
        return ObjectPartInfo(part_number, len(data), len(data), time.time(), etag)

    def list_parts(
        self, bucket: str, object_name: str, upload_id: str,
        part_marker: int = 0, max_parts: int = 1000,
    ) -> list[ObjectPartInfo]:
        self._upload_info(bucket, object_name, upload_id)
        d = self._upload_dir(upload_id)
        parts = []
        for name in os.listdir(d):
            if name.startswith("part.") and name.endswith(".json"):
                n = int(name.split(".")[1])
                if n <= part_marker:
                    continue
                with open(os.path.join(d, name)) as f:
                    meta = json.load(f)
                parts.append(
                    ObjectPartInfo(
                        n, meta["size"], meta["size"], meta.get("mod_time", 0.0), meta["etag"]
                    )
                )
        parts.sort(key=lambda p: p.number)
        return parts[:max_parts]

    def complete_multipart_upload(
        self, bucket: str, object_name: str, upload_id: str, parts: list[tuple[int, str]]
    ) -> ObjectInfo:
        info = self._upload_info(bucket, object_name, upload_id)
        d = self._upload_dir(upload_id)
        blob = b""
        md5s = b""
        for n, etag in parts:
            try:
                with open(os.path.join(d, f"part.{n}.json")) as f:
                    meta = json.load(f)
            except OSError:
                raise errors.InvalidPart(bucket, object_name, msg=f"part {n} missing")
            if meta["etag"] != etag.strip('"').strip():
                raise errors.InvalidPart(bucket, object_name, msg=f"part {n} etag mismatch")
            with open(os.path.join(d, f"part.{n}"), "rb") as f:
                blob += f.read()
            md5s += bytes.fromhex(meta["etag"])
        final_etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        opts = PutObjectOptions(
            user_defined=dict(info.get("user_defined", {})),
            content_type=info.get("content_type", "application/octet-stream"),
            etag=final_etag,
        )
        oi = self.put_object(bucket, object_name, blob, opts)
        shutil.rmtree(d, ignore_errors=True)
        return oi

    def abort_multipart_upload(self, bucket: str, object_name: str, upload_id: str) -> None:
        self._upload_info(bucket, object_name, upload_id)
        shutil.rmtree(self._upload_dir(upload_id), ignore_errors=True)

    def list_multipart_uploads(self, bucket: str, prefix: str = "") -> list[dict]:
        base = os.path.join(self.root, MULTIPART_DIR)
        out = []
        if not os.path.isdir(base):
            return out
        for upload_id in os.listdir(base):
            try:
                with open(os.path.join(base, upload_id, "upload.json")) as f:
                    info = json.load(f)
            except (OSError, ValueError):
                continue
            if info["bucket"] == bucket and info["object"].startswith(prefix):
                out.append(
                    {
                        "upload_id": upload_id,
                        "object": info["object"],
                        "initiated": info["initiated"],
                    }
                )
        return sorted(out, key=lambda u: (u["object"], u["initiated"]))

    # -- heal (no redundancy on FS: no-ops, like the reference's fs backend) --

    def heal_bucket(self, bucket: str) -> None:
        self._check_bucket(bucket)

    def heal_object(
        self, bucket: str, object_name: str, version_id: str = "", dry_run: bool = False
    ) -> HealResultItem:
        self._info(bucket, object_name)
        return HealResultItem(bucket=bucket, object=object_name)
