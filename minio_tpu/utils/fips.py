"""FIPS mode: restrict the SECURITY crypto surface to approved algorithms.

Role of the reference's FIPS build flavor (internal/fips, built with
boringcrypto): in FIPS deployments only approved primitives may serve
security functions. The reference selects this at BUILD time with a Go
toolchain tag; a Python/JAX build has one artifact, so this is a RUNTIME
switch (MINIO_TPU_FIPS=on) enforced at the policy point the flag owns:

  * Signature V2 auth is refused (HMAC-SHA1); SigV4 (HMAC-SHA256) stays.

Everything else already sits on approved primitives whose implementation
comes from the host OpenSSL (hashlib / the cryptography package) — under a
FIPS-provisioned OpenSSL those are the validated module, the same way the
reference swaps in boringcrypto: AES-256-GCM for SSE/KMS envelopes, SHA-256
for SigV4/content digests, HS256/RS256 for JWTs.

Deliberately NOT restricted, matching the reference's FIPS build: bitrot
checksums (HighwayHash) and the MD5 ETag. Both are integrity/wire-compat
checksums, not security controls — the reference ships HighwayHash bitrot
and MD5 ETags unchanged in its FIPS flavor.
"""

from __future__ import annotations

import os


def enabled() -> bool:
    return os.environ.get("MINIO_TPU_FIPS", "").lower() in ("1", "on", "true", "yes")
