"""Error taxonomy for the storage stack.

The reference threads typed sentinel errors through every layer (cmd/
storage-errors.go, object-api-errors.go); quorum logic counts them by
identity. Here they are exception classes with the same roles: drive-level
errors (DiskError subclasses) are counted toward read/write quorums, and
object-level errors map 1:1 onto S3 API error codes in api/errors.py.
"""

from __future__ import annotations


class StorageError(Exception):
    """Base for all storage-stack errors."""


# ---------------------------------------------------------------------------
# Drive-level (per-disk) errors -- the quorum-countable set.
# ---------------------------------------------------------------------------


class DiskError(StorageError):
    pass


class DiskNotFound(DiskError):
    """Drive offline / unreachable (errDiskNotFound)."""


class UnformattedDisk(DiskError):
    """Drive has no format file yet (errUnformattedDisk)."""


class DiskAccessDenied(DiskError):
    pass


class FaultyDisk(DiskError):
    pass


class DiskFull(DiskError):
    pass


class VolumeNotFound(DiskError):
    """Bucket directory missing on this drive (errVolumeNotFound)."""


class VolumeExists(DiskError):
    pass


class VolumeNotEmpty(DiskError):
    pass


class FileNotFound(DiskError):
    """Object/shard file missing on this drive (errFileNotFound)."""


class FileVersionNotFound(DiskError):
    pass


class FileCorrupt(DiskError):
    """Bitrot or metadata parse failure (errFileCorrupt)."""


class FileAccessDenied(DiskError):
    pass


class IsNotRegular(DiskError):
    """Path exists but is not a regular file (errIsNotRegular)."""


class PathNotEmpty(DiskError):
    pass


class DiskIDMismatch(DiskError):
    """Drive answered with the wrong identity (errDiskNotFound analogue for
    the disk-id check wrapper, cmd/xl-storage-disk-id-check.go:68)."""


class CircuitOpen(DiskError):
    """Fail-fast refusal from a tripped per-drive circuit breaker
    (storage/breaker.py). A DiskError on purpose: the quorum reducers count
    the gated drive as failed and the erasure layer routes around it, the
    same way a dead spindle is handled -- just without burning a timeout."""


class DriveBusy(DiskError):
    """Per-drive admission control rejected the call: the drive's bounded
    in-flight window is full (errDiskOngoingReq role). Quorum-countable so
    an overloaded drive sheds to its peers instead of queueing unboundedly."""


class CrashInjected(StorageError):
    """An armed crash point fired in ``raise`` mode (chaos/crash.py): the
    in-process stand-in for process death used by tests and loadgen
    scenarios that must outlive the "crash". NOT a DiskError at the object
    layer -- but the commit fan-out catches it per drive, so a mid-commit
    raise degrades exactly like that drive dying at the point."""


class DeadlineExceeded(StorageError):
    """The request's propagated time budget (X-Mtpu-Deadline) is spent.
    NOT a DiskError: an expired budget says nothing about drive health and
    must abort the whole request, not count against one drive's quorum."""


# ---------------------------------------------------------------------------
# Object-layer errors (cmd/object-api-errors.go equivalents).
# ---------------------------------------------------------------------------


class ObjectError(StorageError):
    def __init__(self, bucket: str = "", object: str = "", msg: str = ""):
        self.bucket = bucket
        self.object = object
        super().__init__(msg or f"{type(self).__name__}: {bucket}/{object}")


class BucketNotFound(ObjectError):
    pass


class BucketExists(ObjectError):
    pass


class BucketNotEmpty(ObjectError):
    pass


class ObjectNotFound(ObjectError):
    pass


class VersionNotFound(ObjectError):
    pass


class MethodNotAllowed(ObjectError):
    """E.g. GET on a delete marker."""


class InvalidArgument(ObjectError):
    pass


class ObjectExistsAsDirectory(ObjectError):
    pass


class InvalidUploadID(ObjectError):
    pass


class InvalidPart(ObjectError):
    pass


class ObjectNameInvalid(ObjectError):
    pass


class BucketNameInvalid(ObjectError):
    pass


class ErasureReadQuorum(ObjectError):
    """Not enough drives answered consistently for a read
    (errErasureReadQuorum)."""


class ErasureWriteQuorum(ObjectError):
    """Write could not reach quorum (errErasureWriteQuorum)."""


class PreconditionFailed(ObjectError):
    pass


class InsufficientReadQuorum(ErasureReadQuorum):
    pass


class InsufficientWriteQuorum(ErasureWriteQuorum):
    pass


def reduce_errs(errs: list[Exception | None], ignored: tuple[type, ...] = ()) -> tuple[int, Exception | None]:
    """Count the most common error identity (None = success counts too).

    The quorum reducer (cmd/erasure-metadata-utils.go reduceErrs
    equivalent): returns (max_count, representative_error).
    """
    counts: dict[str, int] = {}
    rep: dict[str, Exception | None] = {}
    for e in errs:
        if e is not None and ignored and isinstance(e, ignored):
            continue
        key = type(e).__name__ if e is not None else "__ok__"
        counts[key] = counts.get(key, 0) + 1
        rep[key] = e
    if not counts:
        return 0, None
    key = max(counts, key=lambda k: counts[k])
    return counts[key], rep[key]


def reduce_quorum_errs(
    errs: list[Exception | None],
    quorum: int,
    quorum_err: Exception,
    ignored: tuple[type, ...] = (),
) -> Exception | None:
    """None if the dominant outcome reaches quorum and is success; the
    dominant error if it reaches quorum; otherwise quorum_err."""
    count, err = reduce_errs(errs, ignored)
    if count >= quorum:
        return err
    return quorum_err
