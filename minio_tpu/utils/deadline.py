"""End-to-end deadline propagation: a per-request time budget.

Role of the reference's context deadlines (Go threads a context.Context
with a deadline through every layer; gRPC carries it cross-process as
grpc-timeout). Python has no ambient context argument, so the budget rides
a contextvar -- the same vehicle the trace span uses -- which survives
`asyncio.to_thread` for free and is copied into the drive-IO pool per task
by object/metadata.py.

Wire form: the remaining budget in seconds travels as the X-Mtpu-Deadline
header, decremented at each hop (`dist/transport.py` stamps it on every
outgoing RPC; the storage/peer/lock REST servers re-bind it around their
handlers). A 5 s client deadline therefore can never spend 30 s inside a
nested RPC: each hop caps its socket timeout at the remaining budget and
fails fast with DeadlineExceeded once the budget is spent.

The deadline is stored as an ABSOLUTE time.monotonic() instant, so nested
scopes compose by min() and "remaining" never drifts under clock skew
(monotonic is per-process; cross-node hops re-anchor from the header's
relative seconds, which is why the wire form is a duration, not an instant).
"""

from __future__ import annotations

import contextvars
import time

from . import errors

DEADLINE_HEADER = "X-Mtpu-Deadline"

# Budgets below this are noise (a hop can't do anything useful in 1 ms);
# treat them as already expired rather than arming sub-millisecond timeouts.
MIN_BUDGET = 0.001

_deadline: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "minio_tpu_deadline", default=None
)


def remaining() -> float | None:
    """Seconds left in the active budget, or None when no deadline is set.
    May be zero or negative once the budget is spent."""
    d = _deadline.get()
    if d is None:
        return None
    return d - time.monotonic()


def check(what: str = "") -> None:
    """Raise DeadlineExceeded if the active budget is spent. Sprinkled at
    loop boundaries in the object layer so a long streaming operation
    notices expiry between windows instead of running to completion."""
    rem = remaining()
    if rem is not None and rem < MIN_BUDGET:
        raise errors.DeadlineExceeded(
            f"deadline exceeded{': ' + what if what else ''} "
            f"({rem * 1e3:.0f} ms over budget)" if rem < 0 else
            f"deadline exceeded{': ' + what if what else ''}"
        )


def header_value() -> str | None:
    """Wire form of the remaining budget ('' semantics: no deadline)."""
    rem = remaining()
    if rem is None:
        return None
    return f"{max(rem, 0.0):.3f}"


def parse_header(value: str | None) -> float | None:
    """Relative seconds from an X-Mtpu-Deadline header; None when absent
    or malformed (a garbled budget must not take down the request)."""
    if not value:
        return None
    try:
        seconds = float(value)
    except ValueError:
        return None
    if seconds != seconds or seconds < 0:  # NaN / negative
        return 0.0
    return seconds


class scope:
    """Bind a deadline for the current context: `with deadline.scope(5.0):`.

    Nested scopes only ever SHRINK the budget (min of the instants) -- an
    inner layer granting itself more time than its caller would defeat
    propagation. `scope(None)` is a no-op passthrough, so call sites can
    bind an optional header value unconditionally.
    """

    __slots__ = ("_seconds", "_token")

    def __init__(self, seconds: float | None):
        self._seconds = seconds
        self._token = None

    def __enter__(self) -> "scope":
        if self._seconds is not None:
            new = time.monotonic() + self._seconds
            cur = _deadline.get()
            self._token = _deadline.set(new if cur is None else min(cur, new))
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _deadline.reset(self._token)
            self._token = None
        return False


def bind_header(value: str | None) -> scope:
    """Server-side adoption of a propagated budget (the deadline twin of
    tracing.bind_header): re-anchors the header's relative seconds on this
    process's monotonic clock."""
    return scope(parse_header(value))
