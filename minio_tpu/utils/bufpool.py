"""Bounded reusable buffer pool for the zero-copy data plane.

Role of the reference's internal/bpool (bpool.BytePoolCap): the PUT path
lands socket bytes into pooled ``bytearray`` windows once, and every
downstream hop (sigv4 chunk parse, erasure staging, shard slicing) operates
on ``memoryview``s over the same storage. The pool bounds steady-state
memory (capacity x buf_size) while never blocking a request: when the free
list is empty an overflow buffer is allocated and simply dropped on release
instead of being retained.

Lifecycle is explicit refcounting, not GC: ``acquire`` hands out a
PooledBuffer with one reference; pipeline stages that hold the buffer past
the caller's scope (readahead queue, in-flight drive writes) ``retain`` it
and ``release`` when done. The last release recycles the storage. Tests
pigeonhole this: after any PUT -- including chaos-faulted ones -- the pool
reports zero outstanding buffers.
"""

from __future__ import annotations

import os

from ..control import bufsan as _bufsan
from ..control.sanitizer import san_lock


class PooledBuffer:
    """A refcounted bytearray window handed out by a BufferPool."""

    # __weakref__ lets the armed bufsan (control/bufsan.py) hang a leak
    # detector off every handle; _san is its per-handle shadow state (None
    # when disarmed: one attribute, no behavior change).
    __slots__ = ("data", "_pool", "_refs", "_san", "__weakref__")

    def __init__(self, data: bytearray, pool: "BufferPool | None"):
        self.data = data
        self._pool = pool
        self._refs = 1
        self._san = None

    def __len__(self) -> int:
        return len(self.data)

    def view(self, start: int = 0, end: int | None = None) -> memoryview:
        """Writable window over the storage. Views must not outlive the
        buffer's last release -- the storage is reused afterwards.

        Bounds are checked eagerly: after the last release the storage is
        poisoned to 0 bytes, and a silently-empty slice would mask exactly
        the use-after-release that poisoning exists to surface. Negative
        indices are rejected for the same reason -- they re-anchor on
        whatever length the (possibly recycled) storage has now.
        """
        n = len(self.data)
        stop = n if end is None else end
        if start < 0 or stop < start or stop > n:
            raise ValueError(
                f"view({start}, {end}) out of bounds for {n}-byte storage"
                " -- a 0-byte buffer is one whose last release already"
                " recycled the storage"
            )
        san = _bufsan.ACTIVE
        if san is not None:
            san.note_view(self)
        return memoryview(self.data)[start:stop]

    def retain(self) -> "PooledBuffer":
        pool = self._pool
        if pool is None:  # detached (pool-less) buffer: no accounting
            return self
        with pool._lock:
            if self._refs <= 0:
                raise RuntimeError("retain() on a released PooledBuffer")
            self._refs += 1
        return self

    def release(self) -> None:
        self._release(discard=False)

    def discard(self) -> None:
        """Release this reference, but never recycle the storage.

        For exception paths: an in-flight traceback pins frames this code
        does not own (a reader's ``readinto``, a codec callback), and those
        frames may hold views over the storage. Recycling would let a stale
        view observe another request's bytes; discarding lets the allocator
        reclaim the storage only once every pinned frame is gone. Costs one
        allocation on a cold path; buys a hard lifetime guarantee.
        """
        self._release(discard=True)

    def release_or_discard(self) -> None:
        """Release, demoting to ``discard()`` if live exports remain.

        For consumer-facing streams: the zero-copy GET hands memoryview
        chunks to callers whose contract lets them keep the bytes (collect
        the whole stream, then join). At close time the owner cannot know
        which they did, so the last release probes the storage -- no
        exports means a normal recycle; a surviving export means the
        allocator keeps the storage alive for its holder and the pool
        never sees it again.
        """
        self._release(discard=False, demote_if_exported=True)

    def _release(self, discard: bool, demote_if_exported: bool = False) -> None:
        pool = self._pool
        if pool is None:
            return
        with pool._lock:
            if self._refs <= 0:
                san = _bufsan.ACTIVE
                if san is not None:
                    san.note_double_release(self)
                raise RuntimeError("release() on an already-released PooledBuffer")
            self._refs -= 1
            if self._refs == 0:
                if demote_if_exported and _exported(self.data):
                    discard = True
                pool._recycle_locked(self, discard=discard)


def _exported(storage: bytearray) -> bool:
    """True if any live memoryview/buffer export pins `storage`. A bytearray
    refuses to resize while exported, so a 1-byte append is a definitive
    O(1) probe; on success the byte is removed again."""
    try:
        storage.append(0)
    except BufferError:
        return True
    del storage[-1:]
    return False


class BufferPool:
    """Bounded free-list of equal-size bytearrays. acquire() never blocks:
    past `capacity` it allocates overflow buffers that are dropped (not
    pooled) on release, so a burst degrades to plain allocation instead of
    deadlocking the data plane on its own memory bound."""

    def __init__(self, buf_size: int, capacity: int, name: str = "bufpool"):
        if buf_size <= 0 or capacity <= 0:
            raise ValueError("buf_size and capacity must be positive")
        self.buf_size = buf_size
        self.capacity = capacity
        self.name = name
        self._lock = san_lock("BufferPool._lock")
        self._free: list[bytearray] = []
        self._outstanding = 0
        self._gets = 0
        self._reuses = 0
        self._overflow = 0
        self._discards = 0

    def acquire(self, size: int | None = None) -> PooledBuffer:
        """Hand out a buffer of at least `size` bytes (default buf_size).

        Requests that fit buf_size reuse the pooled storage -- callers slice
        their own window with view(0, size), so a short read never shrinks
        the pooled bytearray. Oversize requests overflow-allocate exactly
        `size` bytes; _recycle_locked drops odd-size storage on release.
        """
        want = self.buf_size if size is None else size
        if want <= 0:
            raise ValueError("acquire size must be positive")
        # ALL accounting (gets/outstanding/reuses/overflow) stays inside the
        # critical section: a concurrent burst bumping counters outside the
        # lock loses increments and undercounts overflow, and the burst is
        # exactly when the overflow number matters. Only the bytearray
        # allocation itself happens outside.
        storage: bytearray | None = None
        with self._lock:
            self._gets += 1
            self._outstanding += 1
            if want <= self.buf_size and self._free:
                self._reuses += 1
                storage = self._free.pop()
            elif self._outstanding > self.capacity or want > self.buf_size:
                self._overflow += 1
        reused = storage is not None
        if storage is None:
            # Allocation happens outside the lock: a multi-MiB bytearray fill
            # is not something to serialize the whole data plane behind.
            storage = bytearray(self.buf_size if want <= self.buf_size else want)
        pb = PooledBuffer(storage, self)
        san = _bufsan.ACTIVE
        if san is not None:
            san.note_acquire(pb, self.name, reused)
        return pb

    def _recycle_locked(self, pb: PooledBuffer, discard: bool = False) -> None:
        self._outstanding -= 1
        storage = pb.data
        pooled = (
            not discard
            and len(self._free) < self.capacity
            and len(storage) == self.buf_size
        )
        if discard:
            self._discards += 1
        san = _bufsan.ACTIVE
        if san is not None:
            # Before the storage can be handed to anyone else: probe for
            # views that outlive this buffer, then sentinel-poison what goes
            # back on the free list. (A discarded storage is never reused,
            # so its lingering traceback-pinned views are harmless.)
            san.note_recycle(pb, storage, pooled)
        if pooled:
            self._free.append(storage)
        pb.data = bytearray(0)  # poison: stale views see an empty buffer

    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    def stats(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "buf_size": self.buf_size,
                "capacity": self.capacity,
                "free": len(self._free),
                "outstanding": self._outstanding,
                "gets": self._gets,
                "reuses": self._reuses,
                "overflow_allocs": self._overflow,
                "discards": self._discards,
            }


# -- process-wide window pool --------------------------------------------------

# The PUT pipeline lands body bytes in GROUP-sized windows (16 MiB: see
# object/erasure.py GROUP_BLOCKS x BLOCK_SIZE). Capacity bounds steady-state
# pool memory at capacity x 16 MiB; concurrent bursts overflow-allocate.
WINDOW_BYTES = 16 * (1 << 20)

_GLOBAL: BufferPool | None = None
_global_lock = san_lock("bufpool._global_lock")


def window_pool() -> BufferPool:
    """The shared PUT window pool (MTPU_POOL_BUFFERS sizes it, default 8)."""
    global _GLOBAL
    with _global_lock:
        if _GLOBAL is None:
            cap = max(1, int(os.environ.get("MTPU_POOL_BUFFERS", "8")))
            _GLOBAL = BufferPool(WINDOW_BYTES, cap, name="put-window")
        return _GLOBAL


# The GET pipeline reads one shard row (WINDOW_BYTES / k data bytes plus
# 32 B digest framing per block) per drive per window. Rows for common k
# (4..12) fit a 2 MiB buffer; larger rows overflow-allocate exactly.
SHARD_BYTES = 2 * (1 << 20)

_SHARD: BufferPool | None = None


def shard_pool() -> BufferPool:
    """The shared GET shard-row pool (MTPU_SHARD_BUFFERS sizes it)."""
    global _SHARD
    with _global_lock:
        if _SHARD is None:
            cap = max(1, int(os.environ.get("MTPU_SHARD_BUFFERS", "32")))
            _SHARD = BufferPool(SHARD_BYTES, cap, name="get-shard")
        return _SHARD
