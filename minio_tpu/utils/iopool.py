"""GIL-escaping I/O worker pool with per-lane FIFO ordering.

The PUT fan-out needs two properties at once:

  * concurrency ACROSS drives -- shard writes to 16 drives should overlap,
    and the hot loops (os.writev, file appends, storage-RPC sends) all
    release the GIL, so workers escape the interpreter while data moves;
  * strict ordering WITHIN a drive -- a staged shard file is append-only,
    so group g must hit drive d's file before group g+1 does.

LanePool provides both: submissions carry a lane key (the drive index) and
are queued per lane; a lane is drained by at most one worker at a time, in
submission order, on a shared ThreadPoolExecutor. Workers hold buffers, not
locks: the pool lock guards only the tiny queue bookkeeping, never I/O
(mtpusan's lock-blocking-io rule holds this file to that).
"""

from __future__ import annotations

import os
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor

from ..control.sanitizer import san_lock


class LanePool:
    """Shared worker pool; per-lane FIFO serialization."""

    def __init__(self, workers: int, name: str = "drive-io-lane"):
        self._ex = ThreadPoolExecutor(max_workers=workers, thread_name_prefix=name)
        self._lock = san_lock("LanePool._lock")
        self._lanes: dict = {}     # lane -> deque[(fn, args, Future)]
        self._active: set = set()  # lanes currently owned by a drain worker

    def submit(self, lane, fn, *args) -> Future:
        """Run fn(*args) after every earlier submission on `lane`."""
        fut: Future = Future()
        with self._lock:
            q = self._lanes.get(lane)
            if q is None:
                q = self._lanes[lane] = deque()
            q.append((fn, args, fut))
            start = lane not in self._active
            if start:
                self._active.add(lane)
        if start:
            self._ex.submit(self._drain, lane)
        return fut

    def _drain(self, lane) -> None:
        while True:
            with self._lock:
                q = self._lanes.get(lane)
                if not q:
                    self._active.discard(lane)
                    self._lanes.pop(lane, None)
                    return
                fn, args, fut = q.popleft()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # surfaced through the Future
                fut.set_exception(e)

    def shutdown(self) -> None:
        self._ex.shutdown(wait=True)


_SHARED: LanePool | None = None
_shared_lock = san_lock("iopool._shared_lock")


def shard_writer_pool() -> LanePool:
    """Process-wide shard-write pool (MTPU_IO_WORKERS sizes it).

    Default scales with the host: enough workers that a 16-drive fan-out
    overlaps on multi-core boxes without spawning 16 idle threads on a
    single-core one."""
    global _SHARED
    with _shared_lock:
        if _SHARED is None:
            default = min(16, 4 * (os.cpu_count() or 1))
            workers = max(1, int(os.environ.get("MTPU_IO_WORKERS", str(default))))
            _SHARED = LanePool(workers, name="drive-io-lane")
        return _SHARED
