"""Deterministic placement hashes: SipHash-2-4, crc32 helpers.

Used for object -> erasure-set distribution and per-object drive rotation,
matching the reference's semantics bit-for-bit so placement is stable:
  * sip_hash_mod: cmd/erasure-sets.go:747-780 (dchest/siphash Hash(k0,k1,key))
  * crc_hash_mod + hash_order: cmd/erasure-metadata-utils.go:107,
    crc32 IEEE of the object name.
"""

from __future__ import annotations

import zlib

_MASK = 0xFFFFFFFFFFFFFFFF


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & _MASK


def siphash24(k0: int, k1: int, data: bytes) -> int:
    """SipHash-2-4 with 64-bit output (dchest/siphash.Hash semantics)."""
    v0 = k0 ^ 0x736F6D6570736575
    v1 = k1 ^ 0x646F72616E646F6D
    v2 = k0 ^ 0x6C7967656E657261
    v3 = k1 ^ 0x7465646279746573

    def sipround(v0, v1, v2, v3):
        v0 = (v0 + v1) & _MASK
        v1 = _rotl(v1, 13)
        v1 ^= v0
        v0 = _rotl(v0, 32)
        v2 = (v2 + v3) & _MASK
        v3 = _rotl(v3, 16)
        v3 ^= v2
        v0 = (v0 + v3) & _MASK
        v3 = _rotl(v3, 21)
        v3 ^= v0
        v2 = (v2 + v1) & _MASK
        v1 = _rotl(v1, 17)
        v1 ^= v2
        v2 = _rotl(v2, 32)
        return v0, v1, v2, v3

    n = len(data)
    end = n - (n % 8)
    for i in range(0, end, 8):
        m = int.from_bytes(data[i : i + 8], "little")
        v3 ^= m
        v0, v1, v2, v3 = sipround(v0, v1, v2, v3)
        v0, v1, v2, v3 = sipround(v0, v1, v2, v3)
        v0 ^= m
    # Final block: remaining bytes + length in the top byte.
    b = (n & 0xFF) << 56
    tail = data[end:]
    for i, ch in enumerate(tail):
        b |= ch << (8 * i)
    v3 ^= b
    v0, v1, v2, v3 = sipround(v0, v1, v2, v3)
    v0, v1, v2, v3 = sipround(v0, v1, v2, v3)
    v0 ^= b
    v2 ^= 0xFF
    for _ in range(4):
        v0, v1, v2, v3 = sipround(v0, v1, v2, v3)
    return (v0 ^ v1 ^ v2 ^ v3) & _MASK


def sip_hash_mod(key: str, cardinality: int, deployment_id: bytes) -> int:
    """Object name -> set index (cmd/erasure-sets.go:747)."""
    if cardinality <= 0:
        return -1
    k0 = int.from_bytes(deployment_id[0:8], "little")
    k1 = int.from_bytes(deployment_id[8:16], "little")
    return siphash24(k0, k1, key.encode()) % cardinality


def crc_hash_mod(key: str, cardinality: int) -> int:
    if cardinality <= 0:
        return -1
    return (zlib.crc32(key.encode()) & 0xFFFFFFFF) % cardinality


def hash_order(key: str, cardinality: int) -> list[int]:
    """Consistent 1-based drive order for an object
    (cmd/erasure-metadata-utils.go:107)."""
    if cardinality <= 0:
        return []
    key_crc = zlib.crc32(key.encode()) & 0xFFFFFFFF
    start = key_crc % cardinality
    return [1 + ((start + i) % cardinality) for i in range(1, cardinality + 1)]
