"""Distributed locking: local locker + dsync quorum RW mutex + lock REST.

Role of the reference's internal/dsync (drwmutex.go:64 DRWMutex) +
cmd/local-locker.go + lock-rest-{client,server}.go: a lock is acquired by
broadcasting to every node's locker and holding a quorum (N/2+1 for writes,
N/2 for reads, drwmutex.go:173-185); partially acquired locks are released
and retried with jitter; held locks are refreshed every few seconds and a
lost refresh quorum cancels the protected operation via callback
(drwmutex.go:221-254). Locker entries expire server-side when refreshes stop,
so crashed holders don't wedge the namespace.
"""

from __future__ import annotations

import hmac
import random
import threading
import time
import uuid
from dataclasses import dataclass, field

import msgpack

from ..control.logging import GLOBAL_LOGGER
from aiohttp import web

from ..utils import deadline, errors
from .transport import ERROR_HEADER, TOKEN_HEADER, RestClient
from ..control.sanitizer import san_lock, san_rlock

LOCK_PREFIX = "/mtpu/lock/v1"
REFRESH_INTERVAL = 3.0
EXPIRY = 30.0  # entries without refresh die after this


class LockNotHeld(errors.StorageError):
    pass


# ---------------------------------------------------------------------------
# Local locker (one per node; cmd/local-locker.go:53)
# ---------------------------------------------------------------------------


@dataclass
class _Entry:
    writer: bool
    uids: dict[str, float] = field(default_factory=dict)  # uid -> last refresh


class LocalLocker:
    def __init__(self):
        self._lock = san_lock("LocalLocker._lock")
        self._map: dict[str, _Entry] = {}

    def _expire(self, resource: str) -> None:
        e = self._map.get(resource)
        if not e:
            return
        now = time.monotonic()
        dead = [u for u, t in e.uids.items() if now - t > EXPIRY]
        for u in dead:
            del e.uids[u]
        if not e.uids:
            self._map.pop(resource, None)

    def lock(self, resource: str, uid: str, writer: bool) -> bool:
        with self._lock:
            self._expire(resource)
            e = self._map.get(resource)
            if e is None:
                self._map[resource] = _Entry(writer=writer, uids={uid: time.monotonic()})
                return True
            if writer or e.writer:
                return False  # exclusive conflicts with anything
            e.uids[uid] = time.monotonic()  # shared read
            return True

    def unlock(self, resource: str, uid: str) -> bool:
        with self._lock:
            e = self._map.get(resource)
            if e is None or uid not in e.uids:
                return False
            del e.uids[uid]
            if not e.uids:
                del self._map[resource]
            return True

    def refresh(self, resource: str, uid: str) -> bool:
        with self._lock:
            e = self._map.get(resource)
            if e is None or uid not in e.uids:
                return False
            e.uids[uid] = time.monotonic()
            return True

    def force_unlock(self, resource: str) -> bool:
        with self._lock:
            return self._map.pop(resource, None) is not None

    def is_online(self) -> bool:
        return True

    def top_locks(self) -> list[dict]:
        with self._lock:
            return [
                {"resource": r, "writer": e.writer, "holders": list(e.uids)}
                for r, e in self._map.items()
            ]


# ---------------------------------------------------------------------------
# Lock REST (server + client) -- lock-rest-server-common.go:31-37 endpoints
# ---------------------------------------------------------------------------


def make_lock_app(locker: LocalLocker, token: str) -> web.Application:
    app = web.Application()

    def handler(fn):
        async def wrapped(request: web.Request):
            # Constant-time compare, like every api/ auth path.
            if not hmac.compare_digest(request.headers.get(TOKEN_HEADER, ""), token):
                return web.Response(status=403)
            body = await request.read()
            a = msgpack.unpackb(body, raw=False) if body else {}
            try:
                with deadline.bind_header(request.headers.get(deadline.DEADLINE_HEADER)):
                    ok = fn(a)
                return web.Response(
                    body=msgpack.packb({"ok": ok}), content_type="application/x-msgpack"
                )
            except Exception as e:  # noqa: BLE001
                return web.Response(status=500, headers={ERROR_HEADER: type(e).__name__}, text=str(e))

        return wrapped

    app.router.add_post("/lock", handler(lambda a: locker.lock(a["resource"], a["uid"], True)))
    app.router.add_post("/rlock", handler(lambda a: locker.lock(a["resource"], a["uid"], False)))
    app.router.add_post("/unlock", handler(lambda a: locker.unlock(a["resource"], a["uid"])))
    app.router.add_post("/runlock", handler(lambda a: locker.unlock(a["resource"], a["uid"])))
    app.router.add_post("/refresh", handler(lambda a: locker.refresh(a["resource"], a["uid"])))
    app.router.add_post(
        "/force-unlock", handler(lambda a: locker.force_unlock(a["resource"]))
    )
    return app


class RemoteLocker:
    """Lock REST client to one peer node."""

    def __init__(self, node_url: str, token: str):
        self.client = RestClient(node_url.rstrip("/") + LOCK_PREFIX, token, timeout=5.0)

    def _call(self, op: str, resource: str, uid: str) -> bool:
        try:
            r = self.client.call(f"/{op}", {"resource": resource, "uid": uid})
            return bool(r and r.get("ok"))
        except errors.StorageError:
            return False

    def lock(self, resource, uid, writer):
        return self._call("lock" if writer else "rlock", resource, uid)

    def unlock(self, resource, uid):
        return self._call("unlock", resource, uid)

    def refresh(self, resource, uid):
        return self._call("refresh", resource, uid)

    def force_unlock(self, resource):
        return self._call("force-unlock", resource, "")

    def is_online(self):
        return self.client.is_online()


# ---------------------------------------------------------------------------
# DRWMutex -- quorum lock over all lockers (internal/dsync/drwmutex.go:64)
# ---------------------------------------------------------------------------


class DRWMutex:
    def __init__(self, lockers: list, resource: str, on_lost=None):
        self.lockers = lockers
        self.resource = resource
        self.uid = str(uuid.uuid4())
        self.on_lost = on_lost
        self._held: list[int] = []  # locker indexes we hold
        self._writer = False
        self._stop = threading.Event()
        self.lost = threading.Event()

    def _quorum(self, writer: bool) -> int:
        # Write: N/2+1; read: N/2 (min 1) -- drwmutex.go:173-185.
        n = len(self.lockers)
        return n // 2 + 1 if writer else max(n // 2, 1)

    def acquire(self, writer: bool = True, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        quorum = self._quorum(writer)
        while time.monotonic() < deadline:
            held = []
            for i, lk in enumerate(self.lockers):
                try:
                    if lk.lock(self.resource, self.uid, writer):
                        held.append(i)
                except Exception as e:  # noqa: BLE001 - a dead locker is a no-vote
                    GLOBAL_LOGGER.log_once(
                        f"locker {i} vote failed for {self.resource}: {e}",
                        key=f"locker-vote-{i}",
                    )
                    continue
            if len(held) >= quorum:
                self._held = held
                self._writer = writer
                self._start_refresher()
                return True
            # Partial acquisition: release and retry with jitter
            # (drwmutex.go:216 randomized backoff).
            for i in held:
                try:
                    self.lockers[i].unlock(self.resource, self.uid)
                except Exception as e:  # noqa: BLE001 - best-effort rollback
                    GLOBAL_LOGGER.log_once(
                        f"locker {i} rollback-unlock failed: {e}", key=f"locker-unlock-{i}"
                    )
            time.sleep(random.uniform(0.005, 0.05))
        return False

    def release(self) -> None:
        self._stop.set()
        _refresh_daemon.unregister(self)
        for i in self._held:
            try:
                self.lockers[i].unlock(self.resource, self.uid)
            except Exception as e:  # noqa: BLE001 - lease expiry reclaims it
                GLOBAL_LOGGER.log_once(
                    f"locker {i} release-unlock failed: {e}", key=f"locker-unlock-{i}"
                )
        self._held = []

    def _start_refresher(self) -> None:
        self._stop.clear()
        _refresh_daemon.register(self)

    def _refresh_round(self) -> bool:
        """One refresh sweep; returns False when the quorum is lost."""
        ok = 0
        for i in list(self._held):
            try:
                if self.lockers[i].refresh(self.resource, self.uid):
                    ok += 1
            except Exception as e:  # noqa: BLE001 - counted as a lost vote
                GLOBAL_LOGGER.log_once(
                    f"locker {i} refresh failed for {self.resource}: {e}",
                    key=f"locker-refresh-{i}",
                )
                continue
        if ok >= self._quorum(self._writer):
            return True
        # Lost the lock: cancel the protected operation
        # (drwmutex.go:221 loss callback).
        self.lost.set()
        if self.on_lost is not None:
            try:
                self.on_lost()
            except Exception as e:  # noqa: BLE001 - loss is already being handled
                GLOBAL_LOGGER.error("lock-lost callback raised", exc=e)
        return False

    def __enter__(self):
        if not self.acquire(True):
            raise LockNotHeld(self.resource)
        return self

    def __exit__(self, *exc):
        self.release()


class _RefreshDaemon:
    """One process-wide refresher thread for every held DRWMutex.

    The reference runs a goroutine per held lock (drwmutex.go:221); a Python
    thread per acquisition costs ~1 ms of spawn+join on the PUT commit path
    for a lock typically held for microseconds. One shared daemon sweeping
    all registered mutexes every REFRESH_INTERVAL gives the same liveness
    (server-side entries expire after EXPIRY=30 s — ten missed sweeps)."""

    def __init__(self):
        self._mu = san_lock("_RefreshDaemon._mu")
        self._live: dict[int, DRWMutex] = {}
        self._thread: threading.Thread | None = None
        self._pool = None

    def register(self, m: DRWMutex) -> None:
        with self._mu:
            self._live[id(m)] = m
            if self._thread is None or not self._thread.is_alive():
                # mtpulint: disable=unjoined-thread -- process-lifetime
                # singleton by design: one daemon sweeps EVERY live DRWMutex
                # for the process and parks (see _loop) when none remain;
                # mtpusan SUPPRESSIONS carries the matching runtime entry.
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="lock-refresh"
                )
                self._thread.start()

    def unregister(self, m: DRWMutex) -> None:
        with self._mu:
            self._live.pop(id(m), None)

    def _loop(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        # Refresh mutexes CONCURRENTLY: a blackholed peer stalls its
        # RemoteLocker call for the full 5 s REST timeout, and a sequential
        # sweep of many held locks through one dead peer could overrun the
        # 30 s server-side EXPIRY — expiring locks this daemon exists to
        # keep alive. Eight lanes bound the convoy to ceil(n/8) timeouts.
        self._pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="lock-refresh")
        while True:
            time.sleep(REFRESH_INTERVAL)
            with self._mu:
                batch = list(self._live.values())
            if not batch:
                continue

            def one(m):
                if m._stop.is_set() or not m._refresh_round():
                    self.unregister(m)

            list(self._pool.map(one, batch))


_refresh_daemon = _RefreshDaemon()


# ---------------------------------------------------------------------------
# Namespace lock (cmd/namespace-lock.go role)
# ---------------------------------------------------------------------------


class NamespaceLock:
    """Per-object lock factory. Single-node: one LocalLocker. Distributed:
    all nodes' lockers behind DRWMutex quorum."""

    def __init__(self, lockers: list | None = None):
        self.lockers = lockers if lockers is not None else [LocalLocker()]

    def new(self, bucket: str, object_name: str, on_lost=None) -> DRWMutex:
        return DRWMutex(self.lockers, f"{bucket}/{object_name}", on_lost=on_lost)
