"""Storage REST: a local drive served over HTTP + its remote StorageAPI proxy.

Role of the reference's storage-rest-server.go / storage-rest-client.go (wire
v42): every StorageAPI method gets an endpoint under /mtpu/storage/v1/;
remote drives are indistinguishable from local ones to the object layer.
Shard payloads travel as raw HTTP bodies; structured args/results are
msgpack. Per-drive identity is the drive's format disk-id, checked on every
call via header (the xl-storage-disk-id-check.go role is split between client
and server here).
"""

from __future__ import annotations

import hmac
import itertools
import urllib.parse

import msgpack
from aiohttp import web

from ..storage.interface import StorageAPI
from ..storage.local import LocalDrive
from ..storage.types import DiskInfo, FileInfo, VolInfo
from ..storage.xlmeta import XLMeta
from ..control import tracing
from ..utils import deadline, errors
from .transport import ERROR_HEADER, TOKEN_HEADER, RestClient, error_to_name, name_to_error

PREFIX = "/mtpu/storage/v1"


def _fi_pack(fi: FileInfo) -> dict:
    d = fi.to_dict(with_inline=True)
    return d


def _fi_unpack(d: dict) -> FileInfo:
    return FileInfo.from_dict(d)


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------


def make_storage_app(drives: dict[str, "StorageAPI"], token: str) -> web.Application:
    """drives: url-path -> LocalDrive (e.g. "/data/disk0" -> LocalDrive)."""
    app = web.Application(client_max_size=1 << 31)

    def get_drive(request: web.Request) -> LocalDrive:
        # Constant-time compare: the timing of an equality mismatch must not
        # leak how much of a guessed token matched (same discipline as the
        # api/ signature checks).
        if not hmac.compare_digest(request.headers.get(TOKEN_HEADER, ""), token):
            raise web.HTTPForbidden(text="bad cluster token")
        dpath = request.query.get("disk", "")
        d = drives.get(dpath)
        if d is None:
            raise errors.DiskNotFound(dpath)
        return d

    def error_resp(e: Exception) -> web.Response:
        """Typed error transport: exception class name rides a header."""
        return web.Response(
            status=500 if not isinstance(e, errors.StorageError) else 400,
            headers={ERROR_HEADER: error_to_name(e)},
            text=str(e),
        )

    def handler(fn):
        async def wrapped(request: web.Request):
            import asyncio

            try:
                drive = get_drive(request)
                body = await request.read()
                # Adopt the caller's trace context AND its deadline budget:
                # to_thread copies this coroutine's context, so drive spans
                # parent under the hop and the remaining budget keeps
                # shrinking through nested RPCs.
                with tracing.bind_header(request.headers.get(tracing.TRACE_HEADER)), \
                        deadline.bind_header(request.headers.get(deadline.DEADLINE_HEADER)):
                    result = await asyncio.to_thread(fn, drive, request, body)
                if isinstance(result, bytes):
                    return web.Response(body=result)
                return web.Response(
                    body=msgpack.packb(result, use_bin_type=True),
                    content_type="application/x-msgpack",
                )
            except web.HTTPException:
                raise
            except Exception as e:  # noqa: BLE001 - typed error transport
                return error_resp(e)

        return wrapped

    def args(request, body: bytes) -> dict:
        if request.content_type == "application/x-msgpack" and body:
            return msgpack.unpackb(body, raw=False, strict_map_key=False)
        return {k: v for k, v in request.query.items() if k != "disk"}

    # -- endpoints ----------------------------------------------------------

    def h_disk_info(d, request, body):
        return d.disk_info().to_dict()

    def h_disk_id(d, request, body):
        return {"id": d.disk_id()}

    def h_make_vol(d, request, body):
        d.make_vol(args(request, body)["volume"])

    def h_stat_vol(d, request, body):
        v = d.stat_vol(args(request, body)["volume"])
        return {"name": v.name, "created": v.created}

    def h_list_vols(d, request, body):
        return [{"name": v.name, "created": v.created} for v in d.list_vols()]

    def h_delete_vol(d, request, body):
        a = args(request, body)
        d.delete_vol(a["volume"], bool(a.get("force")))

    def h_write_all(d, request, body):
        d.write_all(request.query["volume"], request.query["path"], body)

    def h_read_all(d, request, body):
        a = args(request, body)
        return d.read_all(a["volume"], a["path"])

    def h_delete(d, request, body):
        a = args(request, body)
        d.delete(a["volume"], a["path"], bool(a.get("recursive")))

    def h_create_file(d, request, body):
        d.create_file(request.query["volume"], request.query["path"], body)

    def h_append_file(d, request, body):
        d.append_file(request.query["volume"], request.query["path"], body)

    def h_read_file(d, request, body):
        a = args(request, body)
        return d.read_file(a["volume"], a["path"], int(a.get("offset", 0)), int(a.get("length", -1)))

    def h_stat_file(d, request, body):
        a = args(request, body)
        return {"size": d.stat_file(a["volume"], a["path"])}

    def h_read_xl(d, request, body):
        a = args(request, body)
        meta = d.read_xl(a["volume"], a["path"])
        return meta.to_bytes()

    def h_read_version(d, request, body):
        a = args(request, body)
        fi = d.read_version(a["volume"], a["path"], a.get("version_id", ""))
        return _fi_pack(fi)

    def h_write_metadata(d, request, body):
        a = args(request, body)
        d.write_metadata(a["volume"], a["path"], _fi_unpack(a["fi"]))

    def h_update_metadata(d, request, body):
        a = args(request, body)
        d.update_metadata(a["volume"], a["path"], _fi_unpack(a["fi"]))

    def h_delete_version(d, request, body):
        a = args(request, body)
        fi = _fi_unpack(a["fi"])
        fi.deleted = a.get("deleted", False) or fi.deleted
        d.delete_version(a["volume"], a["path"], fi)

    def h_rename_data(d, request, body):
        a = args(request, body)
        d.rename_data(
            a["src_volume"], a["src_path"], _fi_unpack(a["fi"]), a["dst_volume"], a["dst_path"]
        )

    def h_rename_file(d, request, body):
        a = args(request, body)
        d.rename_file(a["src_volume"], a["src_path"], a["dst_volume"], a["dst_path"])

    def h_list_dir(d, request, body):
        a = args(request, body)
        return d.list_dir(a["volume"], a.get("path", ""))

    def h_walk_dir(d, request, body):
        a = args(request, body)
        out = []
        for name, raw in d.walk_dir(a["volume"], a.get("base", ""), bool(a.get("recursive", True))):
            out.append([name, raw])
        return out

    def h_verify_file(d, request, body):
        a = args(request, body)
        d.verify_file(a["volume"], a["path"], _fi_unpack(a["fi"]))

    async def h_walk_stream(request: web.Request):
        """Streaming WalkDir: msgpack-framed [name, raw] entries flow as the
        walk produces them (the reference's metacache-walk.go:62 streaming
        discipline) instead of one buffered body per listing -- a 100K-entry
        remote listing stays O(batch) in memory at both ends. The FIRST
        batch is pulled before headers go out, so lazy-generator errors
        (VolumeNotFound on a missing bucket) still take the typed-error
        path rather than aborting a started chunked response."""
        import asyncio

        def next_batch(it):
            return list(itertools.islice(it, 256))

        binder = tracing.bind_header(request.headers.get(tracing.TRACE_HEADER))
        dl_binder = deadline.bind_header(request.headers.get(deadline.DEADLINE_HEADER))
        try:
            drive = get_drive(request)
            body = await request.read()
            a = args(request, body)
            with binder, dl_binder:
                it = drive.walk_dir(a["volume"], a.get("base", ""), bool(a.get("recursive", True)))
                first = await asyncio.to_thread(next_batch, it)
        except web.HTTPException:
            raise
        except Exception as e:  # noqa: BLE001 - typed error transport
            return error_resp(e)

        resp = web.StreamResponse()
        resp.content_type = "application/x-msgpack"
        await resp.prepare(request)
        batch = first
        try:
            while batch:
                await resp.write(
                    b"".join(msgpack.packb([n, r], use_bin_type=True) for n, r in batch)
                )
                if len(batch) < 256:
                    break
                with binder, dl_binder:
                    batch = await asyncio.to_thread(next_batch, it)
        except (ConnectionError, asyncio.CancelledError):
            raise  # client went away: nothing to tell it
        except Exception as e:  # noqa: BLE001
            # Headers already went out: carry the typed error IN-BAND as a
            # dict frame (list frames are entries). Silent truncation would
            # make an incomplete listing look complete; a bare connection
            # abort would read as a dead peer instead of a storage error.
            await resp.write(
                msgpack.packb(
                    {"__error__": error_to_name(e), "msg": str(e)[:200]},
                    use_bin_type=True,
                )
            )
        await resp.write_eof()
        return resp

    app.router.add_post("/walkdirstream", h_walk_stream)

    for name, fn in {
        "diskinfo": h_disk_info,
        "diskid": h_disk_id,
        "makevol": h_make_vol,
        "statvol": h_stat_vol,
        "listvols": h_list_vols,
        "deletevol": h_delete_vol,
        "writeall": h_write_all,
        "readall": h_read_all,
        "delete": h_delete,
        "createfile": h_create_file,
        "appendfile": h_append_file,
        "readfile": h_read_file,
        "statfile": h_stat_file,
        "readxl": h_read_xl,
        "readversion": h_read_version,
        "writemetadata": h_write_metadata,
        "updatemetadata": h_update_metadata,
        "deleteversion": h_delete_version,
        "renamedata": h_rename_data,
        "renamefile": h_rename_file,
        "listdir": h_list_dir,
        "walkdir": h_walk_dir,
        "verifyfile": h_verify_file,
    }.items():
        app.router.add_post(f"/{name}", handler(fn))
    return app


# ---------------------------------------------------------------------------
# Client side: StorageAPI proxy
# ---------------------------------------------------------------------------


class RemoteDrive(StorageAPI):
    """StorageAPI over the storage REST wire (storage-rest-client.go role)."""

    def __init__(self, node_url: str, drive_path: str, token: str, timeout: float = 30.0):
        self.node_url = node_url.rstrip("/")
        self.drive_path = drive_path
        self.client = RestClient(self.node_url + PREFIX, token, timeout)
        self._disk_id = ""

    def _call(self, method: str, args: dict | None = None, body: bytes | None = None, raw=False):
        if body is not None:
            a = dict(args or {})
            a["disk"] = self.drive_path
            return self.client.call(f"/{method}", a, body=body, raw_response=raw)
        url = f"/{method}?disk={urllib.parse.quote(self.drive_path, safe='')}"
        return self.client.call(url, dict(args or {}), raw_response=raw)

    # identity
    def endpoint(self) -> str:
        return f"{self.node_url}{self.drive_path}"

    def is_online(self) -> bool:
        return self.client.is_online()

    def is_local(self) -> bool:
        return False

    def disk_id(self) -> str:
        if not self._disk_id:
            try:
                self._disk_id = self._call("diskid")["id"]
            except errors.StorageError:
                return ""
        return self._disk_id

    def set_disk_id(self, disk_id: str) -> None:
        self._disk_id = disk_id

    def disk_info(self) -> DiskInfo:
        return DiskInfo.from_dict(self._call("diskinfo"))

    # volumes
    def make_vol(self, volume: str) -> None:
        self._call("makevol", {"volume": volume})

    def stat_vol(self, volume: str) -> VolInfo:
        d = self._call("statvol", {"volume": volume})
        return VolInfo(d["name"], d["created"])

    def list_vols(self) -> list[VolInfo]:
        return [VolInfo(d["name"], d["created"]) for d in self._call("listvols")]

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._call("deletevol", {"volume": volume, "force": force})

    # small files
    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._call("writeall", {"volume": volume, "path": path}, body=data)

    def read_all(self, volume: str, path: str) -> bytes:
        return self._call("readall", {"volume": volume, "path": path}, raw=True)

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        self._call("delete", {"volume": volume, "path": path, "recursive": recursive})

    # shard files
    def create_file(self, volume: str, path: str, data: bytes) -> None:
        self._call("createfile", {"volume": volume, "path": path}, body=data)

    def append_file(self, volume: str, path: str, data: bytes) -> None:
        self._call("appendfile", {"volume": volume, "path": path}, body=data)

    def read_file(self, volume: str, path: str, offset: int = 0, length: int = -1) -> bytes:
        return self._call(
            "readfile",
            {"volume": volume, "path": path, "offset": offset, "length": length},
            raw=True,
        )

    def stat_file(self, volume: str, path: str) -> int:
        return self._call("statfile", {"volume": volume, "path": path})["size"]

    # metadata
    def read_xl(self, volume: str, path: str) -> XLMeta:
        raw = self._call("readxl", {"volume": volume, "path": path})
        return XLMeta.from_bytes(raw)

    def read_version(self, volume: str, path: str, version_id: str = "") -> FileInfo:
        return _fi_unpack(
            self._call("readversion", {"volume": volume, "path": path, "version_id": version_id})
        )

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("writemetadata", {"volume": volume, "path": path, "fi": _fi_pack(fi)})

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("updatemetadata", {"volume": volume, "path": path, "fi": _fi_pack(fi)})

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call(
            "deleteversion",
            {"volume": volume, "path": path, "fi": _fi_pack(fi), "deleted": fi.deleted},
        )

    # commit
    def rename_data(self, src_volume, src_path, fi, dst_volume, dst_path) -> None:
        self._call(
            "renamedata",
            {
                "src_volume": src_volume,
                "src_path": src_path,
                "fi": _fi_pack(fi),
                "dst_volume": dst_volume,
                "dst_path": dst_path,
            },
        )

    def rename_file(self, src_volume, src_path, dst_volume, dst_path) -> None:
        self._call(
            "renamefile",
            {
                "src_volume": src_volume,
                "src_path": src_path,
                "dst_volume": dst_volume,
                "dst_path": dst_path,
            },
        )

    # listing
    def list_dir(self, volume: str, path: str) -> list[str]:
        return self._call("listdir", {"volume": volume, "path": path})

    def walk_dir(self, volume: str, base: str = "", recursive: bool = True):
        """Streaming remote walk: entries decode incrementally from the
        chunked response (storage-rest-client WalkDir role). Typed errors
        (VolumeNotFound etc.) surface before the stream starts; transport
        failures mid-stream re-raise as the typed wire error."""
        url = f"/walkdirstream?disk={urllib.parse.quote(self.drive_path, safe='')}"
        resp = self.client.call(
            url,
            {"volume": volume, "base": base, "recursive": recursive},
            stream=True,
        )
        unpacker = msgpack.Unpacker(raw=False, max_buffer_size=1 << 30)
        try:
            with self.client.stream_guard():
                for chunk in resp.iter_content(chunk_size=1 << 16):
                    if not chunk:
                        continue
                    unpacker.feed(chunk)
                    for item in unpacker:
                        if isinstance(item, dict):  # in-band typed error frame
                            raise name_to_error(
                                item.get("__error__", "StorageError"), item.get("msg", "")
                            )
                        name, raw = item
                        yield name, raw
        finally:
            resp.close()

    # integrity
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("verifyfile", {"volume": volume, "path": path, "fi": _fi_pack(fi)})
