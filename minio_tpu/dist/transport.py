"""Inter-node REST plumbing: msgpack-over-HTTP with typed error transport.

Role of the reference's internal/rest (client.go:76 Client with health checks
and backoff) + the msgp wire encoding of storage-rest: all inter-node traffic
is HTTP with msgpack bodies on the DCN control path; shard payloads ride raw
request/response bodies. Errors cross the wire as exception class names and
re-raise as the same minio_tpu.utils.errors type on the caller.
"""

from __future__ import annotations

import hashlib
import hmac
import random
import threading
import time

import msgpack
import requests

from ..chaos import net as chaos_net
from ..chaos.faults import REGISTRY as _CHAOS
from ..control import tracing
from ..control.degrade import GLOBAL_DEGRADE
from ..control.perf import GLOBAL_PERF
from ..utils import deadline, errors
from ..control.sanitizer import san_lock, san_rlock

ERROR_HEADER = "X-Mtpu-Error"
TOKEN_HEADER = "X-Mtpu-Token"
TRACE_HEADER = tracing.TRACE_HEADER
DEADLINE_HEADER = deadline.DEADLINE_HEADER


def jitter(seconds: float, frac: float = 0.10) -> float:
    """Spread a retry/probe interval by ±frac. Peers partitioned at the same
    instant otherwise reconnect in lockstep, hammering the healed link on
    exact HEALTH_INTERVAL boundaries (the thundering-herd the reference
    avoids with randomized backoff in dsync and rest retries)."""
    return seconds * (1.0 + random.uniform(-frac, frac))


def cluster_token(secret: str) -> str:
    """Shared-secret auth token for intra-cluster REST (the reference signs
    internode requests with the root credentials; same idea)."""
    return hmac.new(secret.encode(), b"minio-tpu-internode", hashlib.sha256).hexdigest()


def error_to_name(e: Exception) -> str:
    return type(e).__name__


def name_to_error(name: str, msg: str = "") -> Exception:
    cls = getattr(errors, name, None)
    if cls is not None and isinstance(cls, type) and issubclass(cls, Exception):
        try:
            return cls(msg)
        except TypeError:
            return cls()
    return errors.StorageError(f"{name}: {msg}")


class DynamicTimeout:
    """Self-tuning per-channel timeout (cmd/dynamic-timeouts.go:36).

    Every 16 outcomes: >33% failures -> grow the timeout 25%; <10%
    failures -> shrink 50% of the way toward 1.25x the slowest observed
    success, floored at `minimum`. Healthy fast channels converge to
    tight timeouts (peers drop quickly), congested ones back off instead
    of flapping."""

    LOG_SIZE = 16
    MAX_TIMEOUT = 24 * 3600.0
    _FAILURE = float("inf")

    def __init__(self, timeout: float, minimum: float):
        self._timeout = timeout
        self.minimum = min(minimum, timeout)
        self._log: list[float] = []
        self._lock = san_lock("DynamicTimeout._lock")

    def timeout(self) -> float:
        return self._timeout

    def log_success(self, duration: float) -> None:
        self._entry(duration)

    def log_failure(self) -> None:
        self._entry(self._FAILURE)

    def _entry(self, duration: float) -> None:
        # The whole read-adjust-write runs under the lock: two windows
        # completing concurrently must not lose an adjustment.
        with self._lock:
            self._log.append(duration)
            if len(self._log) < self.LOG_SIZE:
                return
            entries, self._log = self._log, []
            failures = sum(1 for d in entries if d == self._FAILURE)
            slowest = max((d for d in entries if d != self._FAILURE), default=0.0)
            fail_pct = failures / len(entries)
            t = self._timeout
            if fail_pct > 0.33:
                t = min(t * 1.25, self.MAX_TIMEOUT)
            elif fail_pct < 0.10:
                target = slowest * 1.25
                if target < t:
                    t = max((target + t) / 2, self.minimum)
            self._timeout = t


class RestClient:
    """HTTP client to one peer with connection reuse, failure tracking,
    periodic reconnect probing, and a self-tuning default timeout
    (internal/rest/client.go + dynamic-timeouts.go behavior)."""

    HEALTH_INTERVAL = 3.0

    def __init__(self, base_url: str, token: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        # Per-peer ledger label: host:port, not the full prefixed URL --
        # one histogram per (peer, endpoint path) in the perf ledger.
        self._peer_label = self.base_url.split("//", 1)[-1].split("/", 1)[0]
        # One tuner PER ENDPOINT PATH: a ping and a bulk shard read must
        # not share a timeout (the reference keeps separate dynamicTimeouts
        # per operation class for the same reason). Floor at 5s so fast
        # metadata traffic can't shrink an op class under what a loaded
        # server legitimately needs.
        self._tuners: dict[str, DynamicTimeout] = {}
        self._tuners_lock = san_lock("RestClient._tuners_lock")
        self.session = requests.Session()
        self.session.headers[TOKEN_HEADER] = token
        self._online = True
        self._last_failure = 0.0
        self._probe_interval = self.HEALTH_INTERVAL
        self._lock = san_lock("RestClient._lock")

    def is_online(self) -> bool:
        with self._lock:
            if self._online:
                return True
            # Off-line: allow a probe every ~HEALTH_INTERVAL. The interval is
            # re-jittered on each failure so a fleet of clients that lost the
            # same peer together doesn't re-probe it in lockstep.
            return (time.monotonic() - self._last_failure) > self._probe_interval

    def _mark(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self._online = True
            else:
                self._online = False
                self._last_failure = time.monotonic()
                self._probe_interval = jitter(self.HEALTH_INTERVAL)

    def call(
        self,
        path: str,
        args: dict | None = None,
        body: bytes | None = None,
        raw_response: bool = False,
        timeout: float | None = None,
        stream: bool = False,
    ):
        """POST base/path. args -> msgpack body (or query when body given).
        Returns the msgpack-decoded object, raw bytes if raw_response, or
        the live response when stream=True (caller iterates + closes)."""
        # Chaos plane: one None check when disarmed. Covers storage-REST,
        # peer fanout, and lock clients -- everything rides this method.
        if _CHAOS.net is not None:
            chaos_net.before_rpc(self.base_url, path)
        url = self.base_url + path
        # Explicit timeouts win; plain calls ride the endpoint's self-tuned
        # timeout. Streams tune too: session.post(stream=True) returns at
        # response HEADERS, so the tuner times time-to-headers, and the 5s
        # tuner floor sits above the ~1s keep-alives long streams emit.
        tune = timeout is None
        dt: DynamicTimeout | None = None
        if tune:
            with self._tuners_lock:
                dt = self._tuners.get(path)
                if dt is None:
                    dt = self._tuners[path] = DynamicTimeout(
                        self.timeout, minimum=min(5.0, self.timeout)
                    )
        effective = timeout if timeout is not None else dt.timeout()
        # Deadline propagation: the remaining budget caps this hop's socket
        # timeout and rides the wire so the far side inherits it. Checked
        # AFTER the chaos hook -- an injected slow-rpc consumes budget like
        # a real slow link would.
        rem = deadline.remaining()
        capped = False
        if rem is not None:
            if rem < deadline.MIN_BUDGET:
                GLOBAL_DEGRADE.record_deadline_abort("rpc")
                raise errors.DeadlineExceeded(f"rpc{path}: budget spent before send")
            if rem < effective:
                effective = rem
                capped = True
        # The hop is a span of the caller's trace; its id rides the trace
        # header so spans opened on the far side chain under this hop.
        rpc = tracing.span(f"rpc{path}", "rpc", peer=self.base_url)
        trace_hdr = rpc.header()
        headers: dict[str, str] = {}
        if trace_hdr:
            headers[TRACE_HEADER] = trace_hdr
        if rem is not None:
            headers[DEADLINE_HEADER] = f"{max(rem, 0.0):.3f}"
        t0 = time.monotonic()
        c0 = time.thread_time()
        try:
            if body is not None:
                r = self.session.post(
                    url,
                    params={k: str(v) for k, v in (args or {}).items()},
                    data=body,
                    headers=headers or None,
                    timeout=effective,
                    stream=stream,
                )
            else:
                headers["Content-Type"] = "application/x-msgpack"
                r = self.session.post(
                    url,
                    data=msgpack.packb(args or {}, use_bin_type=True),
                    headers=headers,
                    timeout=effective,
                    stream=stream,
                )
        except requests.RequestException as e:
            self._mark(False)
            # Per-peer RPC histogram: recorded directly (not via the span,
            # which is a no-op outside request context) so background RPCs
            # -- heal, scanner, lock refresh -- are attributed too.
            GLOBAL_PERF.ledger.record(
                "rpc-peer",
                f"{path}@{self._peer_label}",
                time.monotonic() - t0,
                time.thread_time() - c0,
            )
            rpc.finish(error=type(e).__name__)
            # A timeout on a deadline-capped hop is the BUDGET expiring, not
            # the channel misbehaving: surface DeadlineExceeded (aborts the
            # whole request) instead of DiskNotFound (counts against the
            # drive), and don't feed the tuner -- a capped timeout says
            # nothing about how the channel should be sized.
            if capped and isinstance(e, requests.Timeout):
                GLOBAL_DEGRADE.record_deadline_abort("rpc")
                raise errors.DeadlineExceeded(f"rpc{path}: budget spent in flight")
            # Only READ timeouts are evidence the timeout is too small; a
            # down peer (connection-refused = ConnectionError, blackholed =
            # ConnectTimeout) says nothing about sizing and must not
            # ratchet the timeout toward the cap during an outage.
            if (
                dt is not None
                and isinstance(e, requests.Timeout)
                and not isinstance(e, requests.ConnectTimeout)
            ):
                dt.log_failure()
            raise errors.DiskNotFound(f"{url}: {e}")
        elapsed = time.monotonic() - t0
        GLOBAL_PERF.ledger.record(
            "rpc-peer",
            f"{path}@{self._peer_label}",
            elapsed,
            time.thread_time() - c0,
        )
        rpc.set(status=r.status_code)
        rpc.finish()
        self._mark(True)
        if dt is not None:
            dt.log_success(elapsed)
        if r.status_code != 200:
            name = r.headers.get(ERROR_HEADER, "StorageError")
            text = r.text[:200]
            r.close()
            raise name_to_error(name, text)
        if stream:
            return r
        if raw_response:
            return r.content
        if not r.content:
            return None
        return msgpack.unpackb(r.content, raw=False, strict_map_key=False)

    def stream_guard(self):
        """Context for consuming a streamed response body: translates
        transport failures into the typed wire error and marks the peer
        offline, matching call()'s contract."""
        return _StreamGuard(self)


class _StreamGuard:
    def __init__(self, client: "RestClient"):
        self._client = client

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and isinstance(exc, requests.RequestException):
            self._client._mark(False)
            raise errors.DiskNotFound(f"stream aborted: {exc}") from exc
        return False
