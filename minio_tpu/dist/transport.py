"""Inter-node REST plumbing: msgpack-over-HTTP with typed error transport.

Role of the reference's internal/rest (client.go:76 Client with health checks
and backoff) + the msgp wire encoding of storage-rest: all inter-node traffic
is HTTP with msgpack bodies on the DCN control path; shard payloads ride raw
request/response bodies. Errors cross the wire as exception class names and
re-raise as the same minio_tpu.utils.errors type on the caller.
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import time

import msgpack
import requests

from ..utils import errors

ERROR_HEADER = "X-Mtpu-Error"
TOKEN_HEADER = "X-Mtpu-Token"


def cluster_token(secret: str) -> str:
    """Shared-secret auth token for intra-cluster REST (the reference signs
    internode requests with the root credentials; same idea)."""
    return hmac.new(secret.encode(), b"minio-tpu-internode", hashlib.sha256).hexdigest()


def error_to_name(e: Exception) -> str:
    return type(e).__name__


def name_to_error(name: str, msg: str = "") -> Exception:
    cls = getattr(errors, name, None)
    if cls is not None and isinstance(cls, type) and issubclass(cls, Exception):
        try:
            return cls(msg)
        except TypeError:
            return cls()
    return errors.StorageError(f"{name}: {msg}")


class RestClient:
    """HTTP client to one peer with connection reuse, failure tracking and
    periodic reconnect probing (internal/rest/client.go behavior)."""

    HEALTH_INTERVAL = 3.0

    def __init__(self, base_url: str, token: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.session = requests.Session()
        self.session.headers[TOKEN_HEADER] = token
        self._online = True
        self._last_failure = 0.0
        self._lock = threading.Lock()

    def is_online(self) -> bool:
        with self._lock:
            if self._online:
                return True
            # Off-line: allow a probe every HEALTH_INTERVAL.
            return (time.monotonic() - self._last_failure) > self.HEALTH_INTERVAL

    def _mark(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self._online = True
            else:
                self._online = False
                self._last_failure = time.monotonic()

    def call(
        self,
        path: str,
        args: dict | None = None,
        body: bytes | None = None,
        raw_response: bool = False,
        timeout: float | None = None,
        stream: bool = False,
    ):
        """POST base/path. args -> msgpack body (or query when body given).
        Returns the msgpack-decoded object, raw bytes if raw_response, or
        the live response when stream=True (caller iterates + closes)."""
        url = self.base_url + path
        try:
            if body is not None:
                r = self.session.post(
                    url,
                    params={k: str(v) for k, v in (args or {}).items()},
                    data=body,
                    timeout=timeout or self.timeout,
                    stream=stream,
                )
            else:
                r = self.session.post(
                    url,
                    data=msgpack.packb(args or {}, use_bin_type=True),
                    headers={"Content-Type": "application/x-msgpack"},
                    timeout=timeout or self.timeout,
                    stream=stream,
                )
        except requests.RequestException as e:
            self._mark(False)
            raise errors.DiskNotFound(f"{url}: {e}")
        self._mark(True)
        if r.status_code != 200:
            name = r.headers.get(ERROR_HEADER, "StorageError")
            text = r.text[:200]
            r.close()
            raise name_to_error(name, text)
        if stream:
            return r
        if raw_response:
            return r.content
        if not r.content:
            return None
        return msgpack.unpackb(r.content, raw=False, strict_map_key=False)

    def stream_guard(self):
        """Context for consuming a streamed response body: translates
        transport failures into the typed wire error and marks the peer
        offline, matching call()'s contract."""
        return _StreamGuard(self)


class _StreamGuard:
    def __init__(self, client: "RestClient"):
        self._client = client

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and isinstance(exc, requests.RequestException):
            self._client._mark(False)
            raise errors.DiskNotFound(f"stream aborted: {exc}") from exc
        return False
