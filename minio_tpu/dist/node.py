"""Node bootstrap: endpoints -> drives -> format consensus -> full server.

Role of the reference's server-main.go serverMain (:422) + endpoint.go
CreateEndpoints (:538) + prepare-storage.go waitForFormatErasure: each node
is given the SAME ordered endpoint list; it opens local paths directly and
remote paths through the storage REST proxy, reaches format.json quorum
(creating fresh formats when the whole cluster is unformatted and this node
is the leader = owner of the first endpoint), then assembles the erasure
pools and serves S3 + storage/lock/peer REST on one port.
"""

from __future__ import annotations

import os
import time
import urllib.parse
import weakref
from dataclasses import dataclass

from aiohttp import web

from ..api.auth import Credentials
from ..api.server import S3Server
from ..control.iam import IAMSys
from ..object import codec as codec_mod
from ..object.pools import ServerPools
from ..object.sets import ErasureSets
from ..storage import format as fmt_mod
from ..storage.interface import StorageAPI
from ..storage.local import LocalDrive
from ..utils import errors
from .locks import LOCK_PREFIX, LocalLocker, NamespaceLock, RemoteLocker, make_lock_app
from .peer import PEER_PREFIX, NotificationSys, PeerClient, make_peer_app
from .storage_rest import PREFIX as STORAGE_PREFIX
from .storage_rest import RemoteDrive, make_storage_app
from .transport import cluster_token


@dataclass
class Endpoint:
    url: str  # "" for pure-local path endpoints
    path: str

    @property
    def is_local_path(self) -> bool:
        return not self.url

    @classmethod
    def parse(cls, raw: str) -> "Endpoint":
        if raw.startswith(("http://", "https://")):
            u = urllib.parse.urlparse(raw)
            return cls(url=f"{u.scheme}://{u.netloc}", path=u.path)
        return cls(url="", path=raw)


class Node:
    # Every constructed node, for close_all() (weak: an abandoned node that
    # never built threads may simply be collected).
    _live: "weakref.WeakSet[Node]" = weakref.WeakSet()

    def __init__(
        self,
        endpoints: list[str],
        url: str = "",
        root_user: str = "minioadmin",
        root_password: str = "minioadmin",
        set_drive_count: int | None = None,
        parity: int | None = None,
        rrs_parity: int | None = None,
        region: str = "us-east-1",
        codec: codec_mod.BlockCodec | None = None,
        check_skew: bool = False,
    ):
        Node._live.add(self)
        self.url = url.rstrip("/")
        # endpoints: flat list (one pool) or list of lists (server pools --
        # each argument group is an independent pool, the reference's
        # `minio server poolA{1...n} poolB{1...n}` expansion,
        # cmd/endpoint-ellipses.go multi-arg pools).
        if endpoints and isinstance(endpoints[0], (list, tuple)):
            pool_specs = [list(p) for p in endpoints]
        else:
            pool_specs = [list(endpoints)]
        self.pool_endpoints = [[Endpoint.parse(e) for e in pool] for pool in pool_specs]
        self.endpoints = [ep for pool in self.pool_endpoints for ep in pool]
        self.token = cluster_token(root_password)
        self.creds = Credentials(root_user, root_password)
        self.region = region
        self.codec = codec

        # Drive construction: local paths open directly, remote via REST.
        self.local_drives: dict[str, StorageAPI] = {}
        self.pool_drives: list[list[StorageAPI]] = []
        peer_urls: set[str] = set()
        from ..chaos.disk import FaultyDisk
        from ..control.pubsub import GLOBAL_TRACE
        from ..storage.breaker import HealthGatedDrive
        from ..storage.metered import MeteredDrive

        for pool in self.pool_endpoints:
            drives: list[StorageAPI] = []
            for ep in pool:
                if ep.is_local_path or ep.url == self.url:
                    # Local drives are metered (per-API latency EWMAs +
                    # storage traces, xl-storage-disk-id-check.go role) over
                    # the circuit breaker + admission gate (storage/breaker.py)
                    # over the fault-injection seam (admin /chaos arms faults
                    # in the process-global registry; disarmed, FaultyDisk
                    # resolves to the inner bound method -- no extra frame).
                    # Breaker INSIDE the meter so fail-fast refusals are
                    # timed/counted; FaultyDisk inside the breaker so injected
                    # faults trip it exactly like kernel EIOs.
                    d = MeteredDrive(
                        HealthGatedDrive(FaultyDisk(LocalDrive(ep.path))),
                        trace=GLOBAL_TRACE,
                    )
                    self.local_drives[ep.path] = d
                    drives.append(d)
                else:
                    peer_urls.add(ep.url)
                    drives.append(RemoteDrive(ep.url, ep.path, self.token))
            self.pool_drives.append(drives)
        self.drives = [d for pool in self.pool_drives for d in pool]
        self.peer_urls = sorted(peer_urls)

        # One set size must fit every pool (the reference requires per-pool
        # divisibility too; set count may differ per pool).
        self.set_drive_count = set_drive_count or _default_set_count(len(self.pool_drives[0]))
        for pi, drives in enumerate(self.pool_drives):
            if len(drives) % self.set_drive_count:
                raise ValueError(
                    f"pool {pi}: {len(drives)} drives not divisible into "
                    f"sets of {self.set_drive_count}"
                )
        self.parity = parity
        self.rrs_parity = rrs_parity
        # Leader = the node owning the first endpoint (server-main.go:507
        # "first local" orchestrates format).
        first = self.endpoints[0]
        self.is_leader = first.is_local_path or first.url == self.url

        self.locker = LocalLocker()
        self.iam: IAMSys | None = None
        self.s3: S3Server | None = None
        self.pools: ServerPools | None = None
        self.ns_lock: NamespaceLock | None = None
        self.notification: NotificationSys | None = None
        self._quota_cache = None  # leader-persisted usage tree (non-leaders)
        self._quota_cache_ts = 0.0

    # -- format consensus ----------------------------------------------------

    def _read_formats(self, drives) -> list[fmt_mod.DriveFormat | None]:
        out: list[fmt_mod.DriveFormat | None] = []
        for d in drives:
            try:
                raw = d.read_all(fmt_mod.SYS_DIR, fmt_mod.FORMAT_FILE)
                out.append(fmt_mod.DriveFormat.from_json(raw.decode()))
            except (errors.DiskError, errors.FileCorrupt):
                out.append(None)
        return out

    def wait_for_format(
        self,
        timeout: float = 30.0,
        drives: list | None = None,
        deployment_id: str | None = None,
    ) -> fmt_mod.DriveFormat:
        """Reach format quorum for one pool's drives, creating fresh formats
        if the whole pool is unformatted and this node leads
        (prepare-storage.go role). Pools after the first inherit the
        cluster deployment id."""
        drives = self.drives if drives is None else drives
        deadline = time.monotonic() + timeout
        while True:
            formats = self._read_formats(drives)
            n_fmt = sum(1 for f in formats if f is not None)
            if n_fmt == 0 and self.is_leader:
                n_sets = len(drives) // self.set_drive_count
                fresh = fmt_mod.init_format(
                    n_sets, self.set_drive_count, deployment_id=deployment_id
                )
                for d, f in zip(drives, fresh):
                    try:
                        d.write_all(fmt_mod.SYS_DIR, fmt_mod.FORMAT_FILE, f.to_json().encode())
                    except errors.DiskError:
                        pass
                continue
            if n_fmt > 0:
                try:
                    quorum = fmt_mod.quorum_format(formats)
                except errors.StorageError:
                    quorum = None
                if quorum is not None:
                    # Heal format onto unformatted drives that we can reach:
                    # give each missing slot the id the quorum expects.
                    flat_ids = [i for s in quorum.sets for i in s]
                    for d, f in zip(drives, formats):
                        if f is None and d.is_online():
                            # Which slot is this drive? By position in the
                            # endpoint list (the reference heals by position
                            # too, format-erasure.go:783).
                            idx = drives.index(d)
                            if idx < len(flat_ids):
                                healed = fmt_mod.DriveFormat(
                                    deployment_id=quorum.deployment_id,
                                    this_id=flat_ids[idx],
                                    sets=quorum.sets,
                                    distribution_algo=quorum.distribution_algo,
                                )
                                try:
                                    d.write_all(
                                        fmt_mod.SYS_DIR,
                                        fmt_mod.FORMAT_FILE,
                                        healed.to_json().encode(),
                                    )
                                    # Fresh drive joined: mark it for a
                                    # background heal sweep (the reference
                                    # drops .healing.bin at format-heal,
                                    # background-newdisks-heal-ops.go:48).
                                    from ..control.healmgr import mark_drive_for_healing

                                    mark_drive_for_healing(d, healed.this_id)
                                except errors.DiskError:
                                    pass
                    return quorum
            if time.monotonic() > deadline:
                raise errors.UnformattedDisk("format quorum not reached")
            time.sleep(0.25)

    # -- assembly ------------------------------------------------------------

    def build(self) -> "Node":
        layer_codec = self.codec
        if self.codec is None:
            # Install the served data-plane codec: the cross-request batching
            # device pipeline when an accelerator is reachable, host C++
            # otherwise (the reference's always-on fast codec,
            # erasure-coding.go:63). Probed with a bounded timeout on a
            # background thread so a wedged device tunnel cannot hang boot;
            # the layer is built with codec=None so it resolves the process
            # default lazily and picks up the async device upgrade.
            from ..runtime import install_data_plane_codec

            self.codec = install_data_plane_codec(background=True)
            layer_codec = None
        else:
            codec_mod.set_default_codec(self.codec)
        # One ErasureSets per pool; pools after the first share the cluster
        # deployment id (erasure-server-pool.go newErasureServerPools role).
        pool_sets: list[ErasureSets] = []
        dep_id: str | None = None
        for pi, drives in enumerate(self.pool_drives):
            quorum = self.wait_for_format(drives=drives, deployment_id=dep_id)
            if dep_id is not None and quorum.deployment_id != dep_id:
                # A pre-formatted pool from a DIFFERENT cluster must not be
                # silently merged into this namespace (the reference rejects
                # mismatched deployment ids at startup).
                raise errors.UnformattedDisk(
                    f"pool {pi} belongs to deployment {quorum.deployment_id}, "
                    f"cluster is {dep_id}"
                )
            dep_id = dep_id or quorum.deployment_id
            pool_sets.append(
                ErasureSets.from_drives(
                    list(drives), quorum, parity=self.parity, codec=layer_codec,
                    pool_index=pi, rrs_parity=self.rrs_parity,
                )
            )
        self.pools = ServerPools(pool_sets)
        lockers: list = [self.locker] + [RemoteLocker(u, self.token) for u in self.peer_urls]
        self.ns_lock = NamespaceLock(lockers)
        self.pools.ns_lock = self.ns_lock
        for sets in pool_sets:
            for s in sets.sets:
                s.ns_lock = self.ns_lock
        self.iam = IAMSys(self.creds.access_key, self.creds.secret_key)
        from ..control import kms as kms_mod
        from ..control.kms import StaticKeyKMS, kms_from_env

        # An explicitly configured KMS (env) is honored even if the crypto
        # backend is missing -- failing loudly beats silently dropping the
        # operator's encryption intent. The implicit ephemeral key, though,
        # only exists to make SSE work out of the box; without the backend
        # it can't, so run as a KMS-less node (SSE -> NotImplemented,
        # config secrets stored unsealed) instead of erroring every
        # replication-target / tier registration.
        self.kms = kms_from_env()
        if self.kms is None and kms_mod.AESGCM is not None:
            self.kms = StaticKeyKMS()
        self.notification = NotificationSys(
            [PeerClient(u, self.token) for u in self.peer_urls]
        )
        # Pool lifecycle manager: owns attach/decommission/rebalance and the
        # persisted pool-config epoch. load_config() here picks up pools that
        # were attached at runtime before this process (re)started -- built
        # BEFORE the subsystems below so they all see the full pool set.
        from ..object.poolmgr import PoolManager

        self.poolmgr = PoolManager(
            self.pools, notification=self.notification, node=self
        )
        self.poolmgr.load_config()

        # Control plane assembly (newAllSubsystems role, server-main.go:451).
        from ..control.config import ConfigStore, ConfigSys
        from ..control.events import EventNotifier
        from ..control.healmgr import HealManager, MRFQueue
        from ..control.logging import GLOBAL_LOGGER
        from ..control.metrics import MetricsSys
        from ..control.pubsub import GLOBAL_TRACE
        from ..control.scanner import DataScanner

        store = ConfigStore(self.pools)
        self.config = ConfigSys(store)
        try:
            self.config.load()
        except errors.StorageError:
            pass
        # IAM durability (iam-object-store.go role): users/policies persist
        # through the erasure-backed config store (sealed with the root
        # credential) and reload on boot — or through etcd when configured
        # (iam-etcd-store.go role; the reference prefers etcd whenever it
        # is set, which is how federated/gateway deployments share IAM).
        # A FAILED load (degraded quorum) disables persistence for this
        # process instead of risking an empty snapshot overwriting the
        # real one on the next mutation.
        from ..control.etcd import etcd_store_from_env

        self.iam.store = etcd_store_from_env() or store
        self.iam.ns_lock = self.ns_lock
        try:
            self.iam.load()
        except errors.FileCorrupt:
            # Unseal failure = wrong root credential, not a flaky drive.
            # Booting anyway would silently serve with ZERO identities;
            # fail loudly instead so the operator restores the credential.
            raise
        except errors.StorageError as e:
            backend = "etcd" if self.iam.store is not store else "erasure config store"
            self.iam.store = None
            self.iam.ns_lock = None
            import logging

            logging.getLogger("minio_tpu").error(
                "IAM store (%s) unreadable at boot (%s); IAM persistence "
                "DISABLED for this process — identities created now will "
                "not survive a restart. Restore the %s and restart.",
                backend, e, backend,
            )
        # Optional SSD read-cache in front of the object layer for the S3
        # serving path only — background subsystems keep the raw layer
        # (the reference interposes CacheObjectLayer at the handler level,
        # object-handlers.go:1722-1724).
        from ..object.cache import CacheConfig, CacheObjectLayer

        cache_cfg = CacheConfig.from_env()
        self.cache = CacheObjectLayer(self.pools, cache_cfg) if cache_cfg else None
        # Hot tier above the disk cache: an in-memory coherent LRU
        # (MTPU_MEMCACHE_MB). Writes through the serving layer invalidate
        # every peer's memcache BEFORE acking, via the same peer channel
        # bucket metadata rides (object/memcache.py).
        from ..object.memcache import (
            MemCacheConfig,
            MemCacheObjectLayer,
            MemObjectCache,
        )

        mem_cfg = MemCacheConfig.from_env()
        self.memcache = MemObjectCache(mem_cfg) if mem_cfg else None
        serving_layer = self.cache if cache_cfg else self.pools
        if self.memcache is not None:
            serving_layer = MemCacheObjectLayer(
                serving_layer,
                self.memcache,
                on_invalidate=(
                    lambda b, o: self.notification.invalidate_memcache_all(b, o)
                ),
            )
        self.s3 = S3Server(
            serving_layer,
            self.iam,
            region=self.region,
            check_skew=False,
            kms=self.kms,
            config=self.config,
        )
        self.metrics = MetricsSys()
        self.metrics.layer = self.pools
        self.trace = GLOBAL_TRACE
        self.logger = GLOBAL_LOGGER
        self.notifier = EventNotifier()
        from ..control.event_targets import configure_targets
        from ..storage.format import SYS_DIR

        # Durable event spool on the first local drive (queuestore.go keeps
        # its spool under the local config dir too).
        spool_root = ""
        if self.local_drives:
            first = next(iter(self.local_drives))
            spool_root = os.path.join(first, SYS_DIR, "notify-spool")
        self.notify_target_errors: dict[str, str] = {}

        def _target_err(tid, e):
            self.notify_target_errors[tid] = str(e)
            GLOBAL_LOGGER.error(f"notify target {tid} disabled: {e}", exc=e)

        configure_targets(self.notifier, self.config, spool_root, on_error=_target_err)
        self.healmgr = HealManager(self.pools)
        self.mrf = MRFQueue(self.pools)
        # Feed the MRF from every erasure set: a put that met quorum but
        # missed drives queues an async repair instead of waiting for the
        # scanner sweep (erasure-object.go:1430 addPartial -> mrf queue).
        for pool in self.pools.pools:
            for s in pool.sets:
                s.on_partial = self.mrf.add
        # Crash-consistency plane: arm any boot-time crash schedule
        # (pre-fork workers and crashcheck victims arm via MTPU_CRASH since
        # the admin API isn't up yet), then sweep crash debris off the local
        # drives before serving. Every pre-fork worker re-runs build(), so a
        # respawned worker re-runs this scan -- a dead sibling's pid-scoped
        # stage files are GC'd here, and partially committed versions are
        # fed to the MRF heal queue.
        from ..chaos import crash as _crash
        from ..storage import recovery as _recovery

        _crash.arm_from_env()
        if os.environ.get("MTPU_RECOVERY", "1") != "0":
            for path in self.local_drives:
                try:
                    _recovery.recover_drive(LocalDrive(path))
                except Exception as e:  # noqa: BLE001 - boot must not die on a sweep
                    GLOBAL_LOGGER.error(f"recovery scan failed on {path}: {e}", exc=e)
            for pool in self.pools.pools:
                for s in pool.sets:
                    if all(d is None or d.is_local() for d in s.disks):
                        try:
                            _recovery.recover_set(s, heal=self.mrf.add)
                        except Exception as e:  # noqa: BLE001
                            GLOBAL_LOGGER.error(f"set recovery scan failed: {e}", exc=e)
        from ..control.healmgr import DiskHealMonitor

        self.disk_heal = DiskHealMonitor(self.pools)
        from ..control.tiering import TierConfigMgr

        self.tiering = TierConfigMgr(store, kms=self.kms)
        self.s3.tiering = self.tiering
        # Scanner leadership via a never-released dsync lock (runDataScanner
        # :99-111); only one node in the cluster scans at a time.
        self.scanner = DataScanner(
            self.pools,
            bucket_meta=self.s3.bucket_meta,
            notifier=self.notifier,
            leader_lock=self.ns_lock.new(".minio_tpu.sys", "leader/data-scanner"),
            store=store,
            tiering=self.tiering,
        )
        self.s3.metrics = self.metrics
        self.s3.trace = self.trace
        self.s3.logger = self.logger
        self.s3.notifier = self.notifier
        # Metrics sources for the node exposition (drive series come through
        # metrics.layer; these feed heal/scanner progress and cluster fan-out).
        self.metrics.node_url = self.url
        self.metrics.notification = self.notification
        self.metrics.scanner = self.scanner
        self.metrics.healmgr = self.healmgr
        self.metrics.mrf = self.mrf
        self.metrics.disk_heal = self.disk_heal
        self.metrics.memcache = self.memcache
        self.metrics.poolmgr = self.poolmgr
        self.metrics.notifier = self.notifier
        # Rehydrate notification rules from persisted bucket metadata: the
        # notifier starts empty, and without this pass a restart silently
        # stops event delivery for every configured bucket until an
        # operator re-PUTs the config. Parallel: serial per-bucket quorum
        # reads would add O(buckets) to boot on large namespaces.
        from ..object import metadata as _meta_mod

        _meta_mod.parallel_map(
            lambda b: self.refresh_bucket_notification(b.name),
            self.pools.list_buckets(),
        )
        # Cluster-wide watcher streams: listen/trace responses merge every
        # peer's records (ListenNotification + admin trace peer subscription).
        self.s3.peer_notification = self.notification
        # Every durable bucket-meta mutation (from ANY writer: S3 handlers,
        # site replication, target registry, quota admin) broadcasts the
        # peer invalidation — the meta cache has no TTL.
        self.s3.bucket_meta.on_change = (
            lambda b: self.notification.reload_bucket_meta_all(b)
        )
        # Hard bucket quotas read the scanner's usage tree
        # (enforceBucketQuota, cmd/bucket-quota.go:112).
        self.s3.quota_usage = self._quota_usage
        from ..control.replication import BucketTargetSys, ReplicationSys

        self.replication = ReplicationSys(
            self.pools,
            self.s3.bucket_meta,
            BucketTargetSys(self.s3.bucket_meta, kms=self.kms),
            kms=self.kms,
        )
        self.s3.replication = self.replication
        self.metrics.replication = self.replication
        from ..control.site_replication import SiteReplicationSys

        self.site_repl = SiteReplicationSys(
            self.pools,
            self.s3.bucket_meta,
            self.iam,
            self.replication.targets,
            self.replication,
            store,
            self_endpoint=self.url,
            notifier=self.notifier,
        )
        self.s3.site_repl = self.site_repl
        # Arm the always-on profiling plane (continuous stack sampler +
        # GIL probe; MTPU_PROFILE=0 vetoes). Process-wide singleton:
        # idempotent across the nodes of an in-process cluster, stopped by
        # close_all().
        from ..control.profiler import GLOBAL_PROFILER

        GLOBAL_PROFILER.ensure_started()
        # Arm the flight recorder's trigger engine (control/flight.py;
        # MTPU_FLIGHT=0 vetoes -- tests default it off in conftest.py) and
        # wire this node's identity + incident fanout into the process
        # singleton. Last node registered wins: one node per process in
        # production; in-process cluster peers still capture under their
        # own tags via the flightcapture peer verb.
        from ..control.flight import GLOBAL_FLIGHT

        GLOBAL_FLIGHT.register_node(
            self.url,
            fanout=self.notification.flight_capture_all,
            pool_status_fn=(
                self.poolmgr.status if self.poolmgr is not None else None
            ),
        )
        GLOBAL_FLIGHT.ensure_started()
        # Resume any drain the previous process left running (the leader
        # drives drains, like format orchestration; MTPU_POOL_RESUME=0
        # vetoes for surgical restarts).
        if self.is_leader and os.environ.get("MTPU_POOL_RESUME", "1") != "0":
            self.poolmgr.resume_pending()
        return self

    def refresh_bucket_notification(self, bucket: str) -> None:
        """Load one bucket's notifier rules from its persisted metadata —
        the single implementation boot rehydration and the peer reload
        handler share. Error policy: bucket gone -> clear the rules;
        transient read failure or malformed XML -> KEEP the current rules
        (silently dropping events on a flap would be worse than serving
        one stale rule set)."""
        if self.s3 is None or self.notifier is None:
            return
        try:
            xml = self.s3.bucket_meta.get(bucket).notification_xml or ""
        except (errors.ObjectNotFound, errors.BucketNotFound):
            xml = ""  # bucket deleted: no rules
        except errors.StorageError:
            return  # transient: keep what we have
        try:
            self.notifier.set_bucket_rules_from_xml(bucket, xml)
        except Exception as e:  # noqa: BLE001 - malformed persisted XML
            self.logger.error(f"notification rules for {bucket} unparsable", exc=e)
            return

    def _quota_usage(self, bucket: str) -> int | None:
        """Bucket usage bytes for quota enforcement, or None when unknown.

        Only the scan leader populates its in-memory tree; every other node
        reads the tree the leader persists (scanner/data-usage.json),
        TTL-cached ~1s like the reference's bucketStorageCache
        (cmd/bucket-quota.go:72-78). No tree anywhere -> None (enforcement
        skipped until a first scan completes)."""
        sc = self.scanner
        if sc is not None and sc.usage.last_update:
            return sc.usage.bucket_usage(bucket).size
        import time as _t

        now = _t.monotonic()
        if now - self._quota_cache_ts > 1.0:
            self._quota_cache_ts = now
            self._quota_cache = None
            store = getattr(sc, "store", None)
            if store is not None:
                try:
                    raw = store.get("scanner/data-usage.json")
                    if raw:
                        from ..control.usage import DataUsageCache

                        self._quota_cache = DataUsageCache.from_bytes(raw)
                except Exception as e:  # noqa: BLE001 - unreadable tree = unknown
                    self.logger.log_once(
                        f"usage tree unreadable, quota enforcement skipped: {e}",
                        key="quota-usage-tree",
                    )
                    self._quota_cache = None
        cache = self._quota_cache
        if cache is None or not cache.last_update:
            return None
        return cache.bucket_usage(bucket).size

    # -- pool expansion -------------------------------------------------------

    def build_pool_from_endpoints(self, raw_endpoints: list[str]) -> ErasureSets:
        """Construct (and register) the drive stacks + erasure sets for one
        new pool at runtime. Formats the drives with the cluster deployment
        id when ALL of them are unformatted (the attach orchestrator
        formats regardless of boot leadership -- wait_for_format only
        auto-inits for the leader); a pre-formatted foreign pool is
        rejected. Called by attach_pool on the orchestrating node and by
        PoolManager.load_config on peers replaying the persisted config."""
        if self.pools is None:
            raise errors.StorageError("node not built yet")
        from ..chaos.disk import FaultyDisk
        from ..control.pubsub import GLOBAL_TRACE
        from ..storage.breaker import HealthGatedDrive
        from ..storage.metered import MeteredDrive

        eps = [Endpoint.parse(e) for e in raw_endpoints]
        drives: list[StorageAPI] = []
        for ep in eps:
            if ep.is_local_path or ep.url == self.url:
                d = MeteredDrive(
                    HealthGatedDrive(FaultyDisk(LocalDrive(ep.path))),
                    trace=GLOBAL_TRACE,
                )
                # Registering here makes the drive instantly peer-servable:
                # make_storage_app resolves this dict at request time.
                self.local_drives[ep.path] = d
                drives.append(d)
            else:
                drives.append(RemoteDrive(ep.url, ep.path, self.token))
        if len(drives) % self.set_drive_count:
            raise ValueError(
                f"attached pool: {len(drives)} drives not divisible into "
                f"sets of {self.set_drive_count}"
            )
        dep_id = self.pools.pools[0].deployment_id
        if not any(f is not None for f in self._read_formats(drives)):
            n_sets = len(drives) // self.set_drive_count
            fresh = fmt_mod.init_format(
                n_sets, self.set_drive_count, deployment_id=dep_id
            )
            for d, f in zip(drives, fresh):
                try:
                    d.write_all(
                        fmt_mod.SYS_DIR, fmt_mod.FORMAT_FILE, f.to_json().encode()
                    )
                except errors.DiskError:
                    pass
        quorum = self.wait_for_format(
            timeout=10.0, drives=drives, deployment_id=dep_id
        )
        if quorum.deployment_id != dep_id:
            raise errors.UnformattedDisk(
                f"attached pool belongs to deployment {quorum.deployment_id}, "
                f"cluster is {dep_id}"
            )
        sets = ErasureSets.from_drives(
            list(drives), quorum, parity=self.parity,
            pool_index=len(self.pools.pools), rrs_parity=self.rrs_parity,
        )
        self.pool_endpoints.append(eps)
        self.endpoints.extend(eps)
        self.pool_drives.append(drives)
        self.drives.extend(drives)
        return sets

    def _wire_new_pool(self, sets: ErasureSets) -> None:
        """Give a runtime-attached pool the same plumbing build() gives boot
        pools: the namespace lock and the partial-write -> MRF feed."""
        mrf = getattr(self, "mrf", None)
        for s in sets.sets:
            s.ns_lock = self.ns_lock
            if mrf is not None:
                s.on_partial = mrf.add

    def attach_pool(self, raw_endpoints: list[str]) -> int:
        """Runtime attach-pool expansion: build drives + sets, wire them,
        then run the manager's two-phase (suspended -> fanout -> active ->
        fanout) attach. Returns the new pool index."""
        sets = self.build_pool_from_endpoints(list(raw_endpoints))
        self._wire_new_pool(sets)
        return self.poolmgr.attach(sets, endpoints=list(raw_endpoints))

    def reload_pools(self) -> bool:
        """Peer-RPC entry: re-read the persisted pool config (epoch-gated)."""
        pm = getattr(self, "poolmgr", None)
        if pm is None:
            return False
        return pm.load_config()

    # -- shutdown ------------------------------------------------------------

    def close(self) -> None:
        """Stop every background worker this node started, reverse build
        order (consumers before their feeds). Idempotent, and safe on a
        node that never completed build() -- each subsystem is stopped only
        if it exists. The bounded joins inside each stop path keep a wedged
        worker from hanging teardown. mtpusan's leaked-thread detector is
        the check that this list stays complete."""
        for sub in ("site_repl", "replication"):
            s = getattr(self, sub, None)
            if s is not None:
                s.close()
        for sub in ("poolmgr", "scanner", "disk_heal", "mrf", "healmgr"):
            s = getattr(self, sub, None)
            if s is not None:
                s.stop()
        notifier = getattr(self, "notifier", None)
        if notifier is not None:
            for t in list(notifier.targets.values()):
                t.close()
        Node._live.discard(self)

    @classmethod
    def close_all(cls) -> None:
        """Close every live node in the process -- the teardown hook for
        test sessions (tests/conftest.py) and embedded multi-node setups,
        where nodes are built ad hoc and nothing else owns their
        lifetime."""
        for node in list(cls._live):
            node.close()
        # The profiling and flight planes are process-wide (not per-node),
        # so they stop here -- after the last node -- rather than in
        # close(); buffering log targets flush for the same reason.
        from ..control.profiler import GLOBAL_PROFILER

        GLOBAL_PROFILER.stop()
        from ..control.flight import GLOBAL_FLIGHT

        GLOBAL_FLIGHT.stop()
        from ..control.logging import GLOBAL_LOGGER

        GLOBAL_LOGGER.close()

    def make_app(self) -> web.Application:
        """One aiohttp app: internode routers first, S3 catch-all last
        (routers.go:65 ordering). Servable BEFORE build() -- the S3 handler
        503s until the object layer is up, so peers can reach this node's
        storage REST during the format handshake (the reference starts its
        dist routers before waitForFormatErasure too, server-main.go:495-521).
        """
        app = web.Application(client_max_size=1 << 31)
        app.add_subapp(STORAGE_PREFIX, make_storage_app(self.local_drives, self.token))
        app.add_subapp(LOCK_PREFIX, make_lock_app(self.locker, self.token))
        app.add_subapp(PEER_PREFIX, make_peer_app(self, self.token))
        from ..api.admin import ADMIN_PREFIX, make_admin_app

        app.add_subapp(ADMIN_PREFIX, make_admin_app(_LazyAdminContext(self)))
        from ..api.console import CONSOLE_PREFIX, make_console_app

        app.add_subapp(CONSOLE_PREFIX, make_console_app(_LazyAdminContext(self)))

        async def s3_entry(request: web.Request):
            if self.s3 is None:
                return web.Response(status=503, text="server initializing")
            return await self.s3._entry(request)

        app.router.add_route("*", "/{tail:.*}", s3_entry)
        return app


class _LazyAdminContext:
    """Admin context resolving node components at request time, so the admin
    router can be mounted before build() completes (it 503s until ready)."""

    def __init__(self, node: "Node"):
        self._node = node

    @property
    def ready(self) -> bool:
        return self._node.s3 is not None

    @property
    def layer(self):
        return self._node.pools

    @property
    def iam(self):
        return self._node.iam

    @property
    def verifier(self):
        return self._node.s3.verifier

    @property
    def config(self):
        return getattr(self._node, "config", None)

    @property
    def scanner(self):
        return getattr(self._node, "scanner", None)

    @property
    def healmgr(self):
        return getattr(self._node, "healmgr", None)

    @property
    def metrics(self):
        return getattr(self._node, "metrics", None)

    @property
    def trace(self):
        return getattr(self._node, "trace", None)

    @property
    def locker(self):
        return self._node.locker

    @property
    def notification(self):
        return self._node.notification

    @property
    def replication(self):
        return getattr(self._node, "replication", None)

    @property
    def tiering(self):
        return getattr(self._node, "tiering", None)

    @property
    def site_repl(self):
        return getattr(self._node, "site_repl", None)

    @property
    def notifier(self):
        return getattr(self._node, "notifier", None)

    @property
    def bucket_meta(self):
        s3 = self._node.s3
        return s3.bucket_meta if s3 is not None else None

    @property
    def kms(self):
        return getattr(self._node, "kms", None)

    @property
    def local_drives(self):
        # The selftest drive probe walks the PRODUCTION drive stacks
        # (metered/health-gated wrappers included), keyed by drive path.
        return self._node.local_drives

    @property
    def node_url(self):
        return self._node.url

    @property
    def poolmgr(self):
        return getattr(self._node, "poolmgr", None)


def _default_set_count(n: int) -> int:
    """Largest set size in [4..16] dividing n; else n itself (small rigs).

    The reference computes symmetric set sizes from the ellipses pattern
    (endpoint-ellipses.go:68 possibleSetCounts); this is the same idea for
    explicit endpoint lists.
    """
    for size in range(16, 3, -1):
        if n % size == 0:
            return size
    return n
