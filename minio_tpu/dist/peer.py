"""Peer REST: node-to-node control plane.

Role of the reference's peer REST v16 (cmd/peer-rest-{client,server}.go,
notification.go NotificationSys): config/IAM/bucket-metadata propagation,
health pings, lock listing, and admin fan-out. Data never rides this channel
-- it is DCN-latency-tolerant control traffic.
"""

from __future__ import annotations

import hmac
import time

import msgpack
from aiohttp import web

from ..control import tracing
from ..utils import deadline, errors
from .transport import ERROR_HEADER, TOKEN_HEADER, RestClient

PEER_PREFIX = "/mtpu/peer/v1"
START_TIME = time.time()


def make_peer_app(node, token: str) -> web.Application:
    app = web.Application()

    def check_token(request: web.Request) -> bool:
        # Constant-time: equality timing must not leak matched prefixes.
        return hmac.compare_digest(request.headers.get(TOKEN_HEADER, ""), token)

    def handler(fn):
        async def wrapped(request: web.Request):
            import asyncio

            if not check_token(request):
                return web.Response(status=403)
            body = await request.read()
            a = msgpack.unpackb(body, raw=False) if body else {}
            try:
                with tracing.bind_header(request.headers.get(tracing.TRACE_HEADER)), \
                        deadline.bind_header(request.headers.get(deadline.DEADLINE_HEADER)):
                    result = await asyncio.to_thread(fn, a)
                return web.Response(
                    body=msgpack.packb(result, use_bin_type=True),
                    content_type="application/x-msgpack",
                )
            except Exception as e:  # noqa: BLE001
                return web.Response(
                    status=500, headers={ERROR_HEADER: type(e).__name__}, text=str(e)
                )

        return wrapped

    def h_ping(a):
        return {"pong": True, "node": node.url}

    def h_server_info(a):
        drives = []
        for d in node.local_drives.values():
            try:
                di = d.disk_info()
                drives.append(
                    {"path": di.mount_path, "total": di.total, "free": di.free, "ok": True}
                )
            except errors.DiskError:
                drives.append({"path": d.root, "ok": False})
        return {
            "node": node.url,
            "uptime": time.time() - START_TIME,
            "drives": drives,
            "version": "0.1.0",
        }

    def h_reload_iam(a):
        if node.iam is not None:
            node.iam.load()
        return {"ok": True}

    def h_reload_bucket_meta(a):
        bucket = a.get("bucket", "")
        if node.s3 is not None:
            node.s3.bucket_meta.invalidate(bucket)
            # Refresh this node's notifier rules from the re-fetched
            # metadata (event config changed on a peer would otherwise
            # keep firing by this node's stale rule set).
            if bucket:
                node.refresh_bucket_notification(bucket)
        # Also drop the object layer's bucket-EXISTENCE cache: a peer that
        # deleted the bucket must not leave this node serving PUTs into the
        # removed namespace for the cache TTL.
        if node.pools is not None:
            node.pools.invalidate_bucket_cache(bucket)
        return {"ok": True}

    def h_memcache_invalidate(a):
        """Per-object memcache invalidation (the hot-read tier's coherence
        channel): a peer that just acked a PUT/DELETE/COPY drops OUR cached
        entries before its client sees the ack. Empty object = whole bucket."""
        mc = getattr(node, "memcache", None)
        if mc is not None:
            bucket = a.get("bucket", "")
            obj = a.get("object", "")
            if obj:
                mc.invalidate_object(bucket, obj)
            elif bucket:
                mc.invalidate_bucket(bucket)
        return {"ok": True}

    def h_top_locks(a):
        return node.locker.top_locks()

    def h_pools_reload(a):
        """Pool-config epoch fanout target: re-read the persisted pool set
        (object/poolmgr.py). The attaching/draining node bumps the epoch,
        persists, then broadcasts this verb so every node agrees on the
        pool set before new writes can land on it."""
        reload_fn = getattr(node, "reload_pools", None)
        if reload_fn is None:
            return {"ok": False}
        return {"ok": True, "applied": bool(reload_fn())}

    def h_pools_status(a):
        """This node's view of the pool set (epoch + per-pool gauges)."""
        pm = getattr(node, "poolmgr", None)
        if pm is None:
            return {}
        return pm.status()

    def h_speedtest(a):
        """Self-benchmark PUT+GET through the object layer
        (peer-rest-server.go:1137 selfSpeedtest)."""
        import os as _os
        import time as _time

        size = int(a.get("size", 1 << 20))
        count = int(a.get("count", 4))
        bucket = ".minio_tpu.sys"
        payload = _os.urandom(size)
        t0 = _time.perf_counter()
        for i in range(count):
            node.pools.pools[0].put_object(bucket, f"speedtest/obj-{i}", payload)
        put_t = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        for i in range(count):
            node.pools.pools[0].get_object(bucket, f"speedtest/obj-{i}")
        get_t = _time.perf_counter() - t0
        for i in range(count):
            try:
                node.pools.pools[0].delete_object(bucket, f"speedtest/obj-{i}")
            except errors.StorageError:
                pass
        return {
            "put_bytes_per_s": size * count / put_t if put_t else 0,
            "get_bytes_per_s": size * count / get_t if get_t else 0,
        }

    # Peer side of the live-cluster self-measurement plane
    # (control/selftest.py; the reference's peer-rest selfSpeedtest /
    # netperf verbs): the admin node fans a probe round out to every peer
    # so all nodes drive load AT THE SAME TIME.

    def h_selftest_object(a):
        """Run one object PUT+GET round locally at the requested
        concurrency (this node's contribution to a cluster speedtest)."""
        from ..control import selftest

        return selftest.run_object_round(
            node.pools,
            size=int(a.get("size", 1 << 20)),
            n_ops=int(a.get("ops", 8)),
            workers=int(a.get("workers", 4)),
            tag=node.url.replace("://", "-").replace(":", "-").replace("/", "-"),
        )

    def h_netperf_run(a):
        """Stream payloads from THIS node to all ITS peers: one row of the
        full-mesh bandwidth/latency matrix."""
        from ..control import selftest

        peers = list(getattr(node.notification, "peers", []) or [])
        return {
            "row": selftest.netperf_row(
                peers,
                size=int(a.get("size", 1 << 20)),
                rounds=int(a.get("rounds", 4)),
            )
        }

    def h_timeseries(a):
        """This node's raw ops/s ring snapshot; the admin
        /timeseries?cluster=1 endpoint merges rings second-by-second."""
        from ..control.perf import GLOBAL_PERF

        return {"timeseries": GLOBAL_PERF.timeseries.snapshot()}

    # Flight-recorder plane (control/flight.py): an incident detected on
    # any node broadcasts here so EVERY node freezes the same wall-clock
    # window -- one correlated black-box dump per incident, not N skewed
    # snapshots.

    def h_flight_capture(a):
        """Capture THIS node's bundle for the originator's incident (same
        t0/t1 cluster-wide). Idempotent per (incident, node); also arms the
        local cooldown so this node's own trigger won't re-open it."""
        from ..control.flight import GLOBAL_FLIGHT

        incident = a.get("incident", {}) or {}
        return {"id": GLOBAL_FLIGHT.capture(incident, node=node.url)}

    def h_flight_list(a):
        """This node's bundle metas + recorder counters; the admin
        /flight?cluster=1 endpoint merges peer lists."""
        from ..control.flight import GLOBAL_FLIGHT

        return {"bundles": GLOBAL_FLIGHT.list(), "stats": GLOBAL_FLIGHT.stats()}

    def h_flight_get(a):
        """One full bundle by id (or newest bundle of an incident id)."""
        from ..control.flight import GLOBAL_FLIGHT

        return {"bundle": GLOBAL_FLIGHT.get(str(a.get("id", "")))}

    # Per-node profiling (peer side of the admin start/download broadcast,
    # cmd/admin-handlers.go:511-716: every node profiles itself with a
    # whole-process sampler; the admin node collects one dump per node).
    def h_profile_start(a):
        from ..control.profiler import SamplingProfiler

        old = getattr(node, "_peer_profiler", None)
        if old is not None:
            # A lost stop call (peer timeout, admin crash) must not wedge
            # profiling forever: discard the orphan and start fresh.
            old.stop()
        p = SamplingProfiler()
        p.start()
        node._peer_profiler = p
        return {"ok": True}

    def h_profile_stop(a):
        p = getattr(node, "_peer_profiler", None)
        node._peer_profiler = None
        if p is None:
            return {"text": ""}
        p.stop()
        return {"text": p.report()}

    def h_profile(a):
        """This node's continuous-profiling snapshot (rotating stack windows,
        GIL load, copy ledger): the admin /profile?cluster=1 endpoint merges
        these into the cluster view (merge_profiles)."""
        from ..control.profiler import GLOBAL_PROFILER

        return {
            "profile": GLOBAL_PROFILER.snapshot(top=int(a.get("top", 40)))
        }

    def h_bandwidth(a):
        """This node's replication bandwidth monitor (merged cluster-wide by
        the admin endpoint; each node throttles its own replica traffic)."""
        repl = getattr(node, "replication", None)
        if repl is None:
            return {}
        return repl.bandwidth.report(a.get("bucket", ""))

    def h_node_metrics(a):
        """This node's Prometheus exposition text; the serving node merges
        peer texts into /minio/v2/metrics/cluster with a server label."""
        metrics = getattr(node, "metrics", None)
        if metrics is None:
            return {"text": ""}
        return {"text": metrics.render_node()}

    def h_perf(a):
        """This node's stage-ledger snapshot (and optionally a reset): the
        admin /perf?cluster=1 endpoint merges these into the cluster view."""
        from ..control.perf import GLOBAL_PERF

        if a.get("reset"):
            GLOBAL_PERF.ledger.reset()
            GLOBAL_PERF.slow.reset()
        return {"snapshot": GLOBAL_PERF.ledger.snapshot(),
                "slow": GLOBAL_PERF.slow.stats()}

    def h_chaos(a):
        """Peer side of the admin chaos fanout: arm/disarm/list faults in
        THIS node's process-global registries (chaos/faults.py for error
        injection, chaos/crash.py for kind="crash" process-death points).
        The arming admin node passes the fault_id through so a later
        cluster-wide disarm removes the same fault everywhere."""
        from ..chaos import crash as crash_mod
        from ..chaos.faults import REGISTRY, FaultSpec

        op = a.get("op", "list")
        if op == "arm":
            spec = a.get("spec", {})
            if spec.get("kind") == crash_mod.CRASH_KIND:
                fid = crash_mod.REGISTRY.arm(crash_mod.CrashSpec.from_dict(spec))
            else:
                fid = REGISTRY.arm(FaultSpec.from_dict(spec))
            return {"fault_id": fid}
        if op == "disarm":
            fid = a.get("fault_id", "")
            if fid:
                removed = int(REGISTRY.disarm(fid)) + int(crash_mod.REGISTRY.disarm(fid))
            else:
                removed = REGISTRY.disarm_all() + crash_mod.REGISTRY.disarm_all()
            return {"removed": int(removed)}
        return {"faults": REGISTRY.list() + crash_mod.REGISTRY.list()}

    # Streaming endpoints: this node's live event / trace records as NDJSON
    # (peer-rest-server.go:985 role) -- the serving node merges these into
    # its watcher responses so `mc watch` / `mc admin trace` see the whole
    # cluster, not one node.
    async def h_listen_stream(request: web.Request):
        if not check_token(request):
            return web.Response(status=403)
        notifier = getattr(node, "notifier", None)
        if notifier is None:
            return web.Response(status=501)
        import json as _json

        from ..api.streams import stream_hub_response

        return await stream_hub_response(request, notifier.listen_hub, _json.dumps)

    async def h_trace_stream(request: web.Request):
        if not check_token(request):
            return web.Response(status=403)
        trace = getattr(node, "trace", None)
        if trace is None:
            return web.Response(status=501)
        import json as _json

        from ..api.streams import stream_hub_response

        return await stream_hub_response(request, trace.hub, _json.dumps)

    for name, fn in {
        "ping": h_ping,
        "serverinfo": h_server_info,
        "reloadiam": h_reload_iam,
        "reloadbucketmeta": h_reload_bucket_meta,
        "memcacheinv": h_memcache_invalidate,
        "toplocks": h_top_locks,
        "poolsreload": h_pools_reload,
        "poolsstatus": h_pools_status,
        "speedtest": h_speedtest,
        "profilestart": h_profile_start,
        "profilestop": h_profile_stop,
        "profile": h_profile,
        "bandwidth": h_bandwidth,
        "metrics": h_node_metrics,
        "perf": h_perf,
        "chaos": h_chaos,
        "selftestobject": h_selftest_object,
        "netperfrun": h_netperf_run,
        "timeseries": h_timeseries,
        "flightcapture": h_flight_capture,
        "flightlist": h_flight_list,
        "flightget": h_flight_get,
    }.items():
        app.router.add_post(f"/{name}", handler(fn))
    app.router.add_post("/listen", h_listen_stream)
    app.router.add_post("/trace", h_trace_stream)

    async def h_netperf_sink(request: web.Request):
        """Netperf receive side: drain the raw payload, acknowledge its
        length. Raw body on purpose -- a msgpack round-trip would price the
        codec, not the link."""
        if not check_token(request):
            return web.Response(status=403)
        body = await request.read()
        return web.Response(
            body=msgpack.packb({"received": len(body)}, use_bin_type=True),
            content_type="application/x-msgpack",
        )

    app.router.add_post("/netperf", h_netperf_sink)
    return app


class PeerClient:
    def __init__(self, node_url: str, token: str):
        self.url = node_url
        self.client = RestClient(node_url.rstrip("/") + PEER_PREFIX, token, timeout=10.0)

    def ping(self) -> bool:
        try:
            r = self.client.call("/ping", {})
            return bool(r and r.get("pong"))
        except errors.StorageError:
            return False

    def server_info(self) -> dict:
        return self.client.call("/serverinfo", {})

    def reload_iam(self, timeout: float | None = None) -> None:
        self.client.call("/reloadiam", {}, timeout=timeout)

    def reload_bucket_meta(
        self, bucket: str = "", timeout: float | None = None
    ) -> None:
        self.client.call("/reloadbucketmeta", {"bucket": bucket}, timeout=timeout)

    def invalidate_memcache(
        self, bucket: str, object_name: str = "", timeout: float | None = None
    ) -> None:
        self.client.call(
            "/memcacheinv", {"bucket": bucket, "object": object_name},
            timeout=timeout,
        )

    def pools_reload(self, timeout: float | None = None) -> bool:
        r = self.client.call("/poolsreload", {}, timeout=timeout)
        return bool(r and r.get("applied"))

    def pools_status(self, timeout: float | None = None) -> dict:
        return self.client.call("/poolsstatus", {}, timeout=timeout) or {}

    def node_metrics(self, timeout: float | None = None) -> str:
        r = self.client.call("/metrics", {}, timeout=timeout)
        return r.get("text", "") if r else ""

    def perf_snapshot(self, reset: bool = False, timeout: float | None = None) -> dict:
        return self.client.call("/perf", {"reset": bool(reset)}, timeout=timeout) or {}

    def top_locks(self) -> list:
        return self.client.call("/toplocks", {})

    def speedtest(self, size: int = 1 << 20, count: int = 4) -> dict:
        return self.client.call("/speedtest", {"size": size, "count": count}, timeout=120.0)

    def selftest_object(self, size: int, ops: int, workers: int) -> dict:
        """One object PUT+GET round on the peer (control/selftest.py)."""
        return self.client.call(
            "/selftestobject",
            {"size": size, "ops": ops, "workers": workers},
            timeout=120.0,
        )

    def netperf_run(self, size: int = 1 << 20, rounds: int = 4) -> dict:
        """Ask the peer to stream to ITS peers: its row of the mesh."""
        return self.client.call(
            "/netperfrun", {"size": size, "rounds": rounds}, timeout=120.0
        )

    def netperf_payload(self, payload) -> dict:
        """Send one raw payload to the peer's netperf sink."""
        return self.client.call("/netperf", body=payload, timeout=60.0)

    def timeseries_snapshot(self, timeout: float | None = None) -> dict:
        return self.client.call("/timeseries", {}, timeout=timeout) or {}

    def flight_capture(self, incident: dict, timeout: float | None = None) -> dict:
        """Ask the peer to capture ITS bundle for this incident's window."""
        return self.client.call(
            "/flightcapture", {"incident": incident}, timeout=timeout
        ) or {}

    def flight_list(self, timeout: float | None = None) -> dict:
        return self.client.call("/flightlist", {}, timeout=timeout) or {}

    def flight_get(self, bundle_id: str, timeout: float | None = None) -> dict:
        return self.client.call("/flightget", {"id": bundle_id}, timeout=timeout) or {}

    def bandwidth(self, bucket: str = "") -> dict:
        return self.client.call("/bandwidth", {"bucket": bucket})

    def chaos(self, op: str, spec: dict | None = None, fault_id: str = "",
              timeout: float | None = None) -> dict:
        return self.client.call(
            "/chaos",
            {"op": op, "spec": spec or {}, "fault_id": fault_id},
            timeout=timeout,
        )

    def profile_start(self) -> dict:
        return self.client.call("/profilestart", {})

    def profile_stop(self) -> dict:
        return self.client.call("/profilestop", {}, timeout=60.0)

    def profile_snapshot(self, top: int = 40, timeout: float | None = None) -> dict:
        return self.client.call("/profile", {"top": top}, timeout=timeout) or {}

    def listen_stream(self):
        """Live event stream from this peer (caller iterates lines + closes).
        No static timeout: the endpoint's DynamicTimeout tuner sizes the
        time-to-headers wait, and the peer's ~1s keep-alives hold the
        connection open far under the 5s tuner floor."""
        return self.client.call("/listen", {}, stream=True)

    def trace_stream(self):
        """Live trace stream from this peer."""
        return self.client.call("/trace", {}, stream=True)


class NotificationSys:
    """Fan-out helper to all peers (cmd/notification.go:50 role)."""

    def __init__(self, peers: list[PeerClient]):
        self.peers = peers

    # Peers whose health flag says offline still get ONE quick attempt
    # with this timeout: the flag can be stale (transient blip already
    # healed), and a skipped invalidation is a silent consistency hole.
    OFFLINE_ATTEMPT_TIMEOUT = 2.0

    def _fanout(self, call) -> None:
        """Best-effort broadcast to EVERY peer. Peers believed online use
        the endpoint's tuned timeout; peers marked offline are still tried
        with a short one so a stale is_online() flag can't drop the
        invalidation, while a genuinely dead peer costs at most ~2s of a
        concurrent worker, not the caller's whole request."""
        if not self.peers:
            return

        def one(p):
            timeout = None if p.client.is_online() else self.OFFLINE_ATTEMPT_TIMEOUT
            try:
                call(p, timeout)
            except errors.StorageError:
                pass

        if len(self.peers) == 1:
            one(self.peers[0])
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(8, len(self.peers))) as pool:
            list(pool.map(one, self.peers))

    def reload_iam_all(self) -> None:
        self._fanout(lambda p, t: p.reload_iam(timeout=t))

    def chaos_all(self, op: str, spec: dict | None = None, fault_id: str = "") -> None:
        """Cluster-wide fault arm/disarm (the admin /chaos handlers call
        this after applying locally)."""
        self._fanout(lambda p, t: p.chaos(op, spec=spec, fault_id=fault_id, timeout=t))

    def flight_capture_all(self, incident: dict) -> None:
        """Incident broadcast (control/flight.py trigger/dump): every peer
        captures its bundle for the SAME wall-clock window, so the cluster
        yields one correlated dump per incident."""
        self._fanout(lambda p, t: p.flight_capture(incident, timeout=t))

    def reload_bucket_meta_all(self, bucket: str = "") -> None:
        self._fanout(lambda p, t: p.reload_bucket_meta(bucket, timeout=t))

    def pools_reload_all(self) -> None:
        """Pool-config epoch broadcast: every peer re-reads the persisted
        pool set. Called under the attach/decommission transition so the
        cluster agrees on pool membership before writes route to it."""
        self._fanout(lambda p, t: p.pools_reload(timeout=t))

    def invalidate_memcache_all(self, bucket: str, object_name: str = "") -> None:
        """Synchronous cross-node memcache invalidation: the writing node
        calls this BEFORE acking its client, so a subsequent read on any
        peer misses (or revalidates) instead of serving the old bytes."""
        self._fanout(lambda p, t: p.invalidate_memcache(bucket, object_name, timeout=t))

    def server_info_all(self) -> list[dict]:
        out = []
        for p in self.peers:
            try:
                out.append(p.server_info())
            except errors.StorageError:
                out.append({"node": p.url, "offline": True})
        return out
