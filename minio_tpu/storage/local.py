"""Local drive backend -- one POSIX directory tree per drive.

Role of the reference's xlStorage (cmd/xl-storage.go): implements the
StorageAPI-shaped per-drive contract in storage/interface.py. On-disk layout
per drive root:

    .minio_tpu.sys/
        format.json          drive identity + erasure topology (storage/format.py)
        tmp/<uuid>/...       staging area; renamed into place on commit
        buckets/...          system volume for object-layer bookkeeping
    <bucket>/<object>/xl.meta                 versioned metadata (+inline data)
    <bucket>/<object>/<data-dir-uuid>/part.N  bitrot-protected shard files

Commit is the reference's renameData discipline (cmd/xl-storage.go RenameData,
cmd/erasure-object.go:990): shard files are staged under tmp/ and the whole
data dir is os.rename()d into the object dir, then xl.meta is replaced via a
tmp-file + os.replace -- readers never observe a half-written object.

Durability is a knob, `MTPU_FSYNC={always,commit,never}` (default `commit`),
mirroring the reference's drive-sync discipline:

  * ``commit``  -- fdatasync staged shard data BEFORE the xl.meta that names
                   it exists (rename_data), fdatasync the staged xl.meta image
                   before os.replace publishes it, and fsync the parent dirs
                   so the rename itself is durable. Acked writes survive a
                   crash at any boundary; the staging appends stay unsynced.
  * ``always``  -- additionally fdatasync every shard append as it lands
                   (the O_DSYNC-style mode; what `LocalDrive(fsync=True)`
                   always did, now metered).
  * ``never``   -- no barriers anywhere: the PR-9 throughput profile, for
                   benchmarking the sync cost and for tests on tmpfs.

Every barrier is metered as the ("storage", "drive-sync") perf-ledger stage
so bench JSON shows what durability costs. Crash points
(chaos/crash.py) sit on the two storage-internal boundaries -- after the
data-dir rename / before xl.meta, and after the staged xl.meta / before
os.replace -- plus the mid-writev torn-write hook in append_iov.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from dataclasses import dataclass

from ..chaos import crash
from ..control.perf import GLOBAL_PERF
from ..utils import errors
from .format import SYS_DIR, DriveFormat
from .interface import StorageAPI
from .types import DiskInfo, FileInfo, VolInfo, now
from .xlmeta import XLMeta
from ..control.sanitizer import san_lock, san_rlock

TMP_DIR = os.path.join(SYS_DIR, "tmp")
BUCKETS_META_DIR = os.path.join(SYS_DIR, "buckets")
XL_META_FILE = "xl.meta"

FSYNC_ALWAYS = "always"
FSYNC_COMMIT = "commit"
FSYNC_NEVER = "never"


def fsync_mode() -> str:
    """The process-wide durability mode from MTPU_FSYNC (default: commit)."""
    mode = os.environ.get("MTPU_FSYNC", FSYNC_COMMIT).strip().lower()
    return mode if mode in (FSYNC_ALWAYS, FSYNC_COMMIT, FSYNC_NEVER) else FSYNC_COMMIT


def _sync_fd(fd: int, *, datasync: bool = True) -> None:
    """Metered sync barrier: every fdatasync/fsync the durability discipline
    issues lands in the ("storage", "drive-sync") ledger stage."""
    t0 = time.perf_counter()
    (os.fdatasync if datasync else os.fsync)(fd)
    GLOBAL_PERF.ledger.record("storage", "drive-sync", time.perf_counter() - t0)


def _sync_path(p: str, *, datasync: bool = True) -> None:
    try:
        fd = os.open(p, os.O_RDONLY)
    except OSError:
        return  # vanished or unsyncable: the rename/commit will surface it
    try:
        _sync_fd(fd, datasync=datasync)
    finally:
        os.close(fd)


def _sync_dir(p: str) -> None:
    """fsync a directory so renames/creates inside it are durable (dir
    entries are metadata: full fsync, not fdatasync)."""
    _sync_path(p, datasync=False)


def _sync_tree(root: str) -> None:
    """fdatasync every file under root, then fsync the dirs bottom-up: the
    pre-commit barrier that makes a staged data dir durable before the
    xl.meta naming it can exist."""
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for n in filenames:
            _sync_path(os.path.join(dirpath, n))
        _sync_dir(dirpath)

# Volumes (buckets) must not collide with the system dir or look like paths.
_RESERVED_VOLS = {SYS_DIR, "", ".", ".."}


def _check_vol_name(volume: str) -> None:
    if volume in _RESERVED_VOLS and not volume.startswith(SYS_DIR):
        raise errors.VolumeNotFound()


# Files at or above this size take the native O_DIRECT path (the reference
# switches off buffered IO above smallFileThreshold, xl-storage.go:59).
ODIRECT_THRESHOLD = 128 * 1024


class LocalDrive(StorageAPI):
    """A single local drive. Thread-safe; xl.meta read-modify-writes are
    serialized per drive (coarse; the object layer's namespace lock is the
    real concurrency gate, as in the reference)."""

    def __init__(self, root: str, fsync: bool = False):
        self.root = os.path.abspath(root)
        self.fsync = fsync
        # RLock: delete_version (marker path) re-enters write_metadata.
        self._meta_lock = san_rlock("LocalDrive._meta_lock")
        self._disk_id: str | None = None
        os.makedirs(os.path.join(self.root, TMP_DIR), exist_ok=True)
        os.makedirs(os.path.join(self.root, BUCKETS_META_DIR), exist_ok=True)
        # Native O_DIRECT path for large shard files (xl-storage.go:1708
        # CopyAligned; probed per drive like internal/disk's O_DIRECT check).
        self._odirect: bool | None = None

    def _mode(self) -> str:
        """Effective durability mode: LocalDrive(fsync=True) pins `always`
        (the pre-knob behaviour); otherwise MTPU_FSYNC decides."""
        return FSYNC_ALWAYS if self.fsync else fsync_mode()

    def _use_native_io(self, size: int) -> bool:
        if size < ODIRECT_THRESHOLD:
            return False
        from ..ops import native

        if not native.io_available():
            return False
        if self._odirect is None:
            try:
                self._odirect = native.odirect_supported(self.root)
            except OSError:
                self._odirect = False
        return True  # native writer handles the no-O_DIRECT fallback itself

    # -- identity ----------------------------------------------------------

    def endpoint(self) -> str:
        return self.root

    def is_online(self) -> bool:
        return os.path.isdir(self.root)

    def is_local(self) -> bool:
        return True

    def disk_id(self) -> str:
        if self._disk_id is None:
            fmt = DriveFormat.load(self.root)
            self._disk_id = fmt.this_id if fmt else ""
        return self._disk_id or ""

    def set_disk_id(self, disk_id: str) -> None:
        self._disk_id = disk_id

    def disk_info(self) -> DiskInfo:
        try:
            st = os.statvfs(self.root)
        except OSError as e:
            raise errors.DiskNotFound(str(e))
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        return DiskInfo(
            total=total,
            free=free,
            used=total - free,
            endpoint=self.root,
            mount_path=self.root,
            disk_id=self.disk_id(),
        )

    # -- path helpers --------------------------------------------------------

    def _vol_path(self, volume: str) -> str:
        _check_vol_name(volume)
        p = os.path.normpath(os.path.join(self.root, volume))
        if not (p + os.sep).startswith(self.root + os.sep):
            raise errors.VolumeNotFound()
        return p

    def _file_path(self, volume: str, path: str) -> str:
        vol = self._vol_path(volume)
        p = os.path.normpath(os.path.join(vol, path))
        if not (p + os.sep).startswith(vol + os.sep) and p != vol:
            raise errors.FileAccessDenied()
        return p

    # -- volumes -------------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        p = self._vol_path(volume)
        if os.path.isdir(p):
            raise errors.VolumeExists()
        os.makedirs(p, exist_ok=True)

    def stat_vol(self, volume: str) -> VolInfo:
        p = self._vol_path(volume)
        try:
            st = os.stat(p)
        except FileNotFoundError:
            raise errors.VolumeNotFound()
        return VolInfo(name=volume, created=st.st_mtime)

    def list_vols(self) -> list[VolInfo]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name == SYS_DIR or not os.path.isdir(os.path.join(self.root, name)):
                continue
            out.append(self.stat_vol(name))
        return out

    def delete_vol(self, volume: str, force: bool = False) -> None:
        p = self._vol_path(volume)
        if not os.path.isdir(p):
            raise errors.VolumeNotFound()
        if force:
            shutil.rmtree(p)
            return
        try:
            os.rmdir(p)
        except OSError:
            raise errors.VolumeNotEmpty()

    # -- small whole files (config, format, system state) --------------------

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        # Plain small-file writes (config, bookkeeping) only barrier in
        # `always` mode; xl.meta commits go through _write_xl below.
        self._write_all(
            self._file_path(volume, path), data,
            barrier=self._mode() == FSYNC_ALWAYS,
        )

    def _write_all(
        self, p: str, data: bytes, barrier: bool, commit_point: str | None = None
    ) -> None:
        """Atomic whole-file write: stage `<p>.tmp<rand>`, optionally
        fdatasync it, os.replace into place, optionally fsync the parent so
        the replace is durable. `commit_point` names the crash point fired
        between the durable staged image and the publishing replace."""
        tmp = p + ".tmp" + os.urandom(4).hex()
        try:
            f = open(tmp, "wb")
        except FileNotFoundError:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            f = open(tmp, "wb")
        with f:
            f.write(data)
            if barrier:
                f.flush()
                _sync_fd(f.fileno())
        if commit_point is not None:
            crash.crash_point(commit_point, self.root)
        os.replace(tmp, p)
        if barrier:
            _sync_dir(os.path.dirname(p))

    def read_all(self, volume: str, path: str) -> bytes:
        p = self._file_path(volume, path)
        try:
            with open(p, "rb") as f:
                return f.read()
        except FileNotFoundError:
            if not os.path.isdir(self._vol_path(volume)):
                raise errors.VolumeNotFound()
            raise errors.FileNotFound()
        except IsADirectoryError:
            raise errors.FileNotFound()

    def delete(self, volume: str, path: str, recursive: bool = False) -> None:
        p = self._file_path(volume, path)
        try:
            if os.path.isdir(p):
                if recursive:
                    shutil.rmtree(p)
                else:
                    os.rmdir(p)
            else:
                os.remove(p)
        except FileNotFoundError:
            raise errors.FileNotFound()
        except OSError:
            raise errors.PathNotEmpty()
        # Prune now-empty parent dirs up to the volume root (the reference
        # deletes parent prefixes too, cmd/xl-storage.go deleteFile).
        parent = os.path.dirname(p)
        vol = self._vol_path(volume)
        while parent != vol and parent.startswith(vol):
            try:
                os.rmdir(parent)
            except OSError:
                break
            parent = os.path.dirname(parent)

    # -- shard files ---------------------------------------------------------

    def create_file(self, volume: str, path: str, data: bytes) -> None:
        """Write a (bitrot-protected) shard file. Callers stage under tmp
        volume then rename_data into place. Large files take the native
        O_DIRECT aligned path (xl-storage.go:1708); small ones buffered
        (<=128 KiB uses O_DSYNC-style buffered writes in the reference)."""
        p = self._file_path(volume, path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        if self._use_native_io(len(data)):
            from ..ops import native

            try:
                native.write_file(
                    p, data, use_odirect=bool(self._odirect),
                    fsync=self._mode() == FSYNC_ALWAYS,
                )
                return
            except OSError:
                pass  # native path failed; buffered fallback below
        with open(p, "wb") as f:
            f.write(data)
            if self._mode() == FSYNC_ALWAYS:
                f.flush()
                _sync_fd(f.fileno())

    # (append_file below opens first and only mkdirs on ENOENT; create_file
    # keeps the eager makedirs because its native O_DIRECT branch reports a
    # missing parent the same way as other failures.)

    def append_file(self, volume: str, path: str, data: bytes) -> None:
        p = self._file_path(volume, path)
        try:
            f = open(p, "ab")
        except FileNotFoundError:
            # First append on this staged file: make the parent then. The
            # happy path (every subsequent group) skips the makedirs stat
            # walk — it was ~5 syscalls per drive per 16 MiB group.
            os.makedirs(os.path.dirname(p), exist_ok=True)
            f = open(p, "ab")
        with f:
            f.write(data)
            if self._mode() == FSYNC_ALWAYS:
                f.flush()
                _sync_fd(f.fileno())

    def append_iov(self, volume: str, path: str, iovecs: list) -> None:
        """Gathered append: the whole group's digest/chunk views go down in
        one os.writev (releases the GIL) instead of per-block appends.

        The torn-write crash point lives here: an armed spec truncates the
        LAST iovec at a seeded offset before the writev -- the at-rest state
        a power-cut / SIGKILL mid-writev leaves -- then either dies
        (torn-kill) or returns normally (torn: silent corruption the bitrot
        digests must catch on read)."""
        p = self._file_path(volume, path)
        try:
            fd = os.open(p, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        except FileNotFoundError:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            fd = os.open(p, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
        try:
            vecs = [memoryview(v) for v in iovecs if len(v)]
            torn_kill = False
            if vecs:
                hint = crash.torn_hint(
                    "storage.append-iov.torn", self.root, len(vecs[-1])
                )
                if hint is not None:
                    cut, torn_kill = hint
                    vecs[-1] = vecs[-1][:cut]
                    vecs = [v for v in vecs if len(v)]
            while vecs:
                written = os.writev(fd, vecs)
                # Short writev: drop fully-written vecs, trim the partial one.
                while vecs and written >= len(vecs[0]):
                    written -= len(vecs[0])
                    vecs.pop(0)
                if written:
                    vecs[0] = vecs[0][written:]
            if torn_kill:
                crash.die()
            if self._mode() == FSYNC_ALWAYS:
                _sync_fd(fd)
        finally:
            os.close(fd)

    def read_file(self, volume: str, path: str, offset: int = 0, length: int = -1) -> bytes:
        p = self._file_path(volume, path)
        if self._use_native_io(length):
            from ..ops import native

            try:
                return native.read_file(
                    p, length, offset, use_odirect=bool(self._odirect)
                )
            except OSError as e:
                import errno as errno_mod

                if e.errno == errno_mod.ENOENT:
                    raise errors.FileNotFound()
                # other native failure: buffered fallback below
        try:
            with open(p, "rb") as f:
                f.seek(offset)
                return f.read() if length < 0 else f.read(length)
        except FileNotFoundError:
            raise errors.FileNotFound()
        except IsADirectoryError:
            raise errors.FileNotFound()

    def read_file_into(
        self, volume: str, path: str, offset: int, buf: memoryview
    ) -> int:
        """readinto a caller-owned (pooled) window: bytes land in the
        destination storage once, with no intermediate bytes object."""
        p = self._file_path(volume, path)
        try:
            with open(p, "rb", buffering=0) as f:
                f.seek(offset)
                total = 0
                want = len(buf)
                while total < want:
                    n = f.readinto(buf[total:])
                    if not n:
                        break  # EOF short read
                    total += n
                return total
        except FileNotFoundError:
            raise errors.FileNotFound()
        except IsADirectoryError:
            raise errors.FileNotFound()

    def stat_file(self, volume: str, path: str) -> int:
        p = self._file_path(volume, path)
        try:
            st = os.stat(p)
        except FileNotFoundError:
            raise errors.FileNotFound()
        if not os.path.isfile(p):
            raise errors.IsNotRegular()
        return st.st_size

    # -- object metadata (xl.meta) -------------------------------------------

    def _meta_path(self, volume: str, path: str) -> str:
        return self._file_path(volume, os.path.join(path, XL_META_FILE))

    def _write_xl(self, volume: str, path: str, data: bytes) -> None:
        """Publish a new xl.meta image: the commit point of every version
        change. Barriered in `commit` and `always` modes, with the
        storage.xlmeta.pre-replace crash point between the durable staged
        image and the os.replace that makes it visible."""
        self._write_all(
            self._meta_path(volume, path), data,
            barrier=self._mode() != FSYNC_NEVER,
            commit_point="storage.xlmeta.pre-replace",
        )

    def read_xl(self, volume: str, path: str) -> XLMeta:
        try:
            raw = self.read_all(volume, os.path.join(path, XL_META_FILE))
        except errors.FileNotFound:
            raise errors.FileNotFound()
        return XLMeta.from_bytes(raw)

    def read_version(self, volume: str, path: str, version_id: str = "") -> FileInfo:
        fi = self.read_xl(volume, path).file_info(version_id)
        fi.volume = volume
        fi.name = path
        return fi

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        """Add/replace one version in the object's xl.meta."""
        with self._meta_lock:
            try:
                meta = self.read_xl(volume, path)
            except errors.FileNotFound:
                meta = XLMeta()
            meta.add_version(fi)
            # mtpulint: disable=lock-blocking-io -- the read-modify-write of
            # xl.meta IS the critical section; dropping the lock before the
            # write would let a concurrent writer interleave a stale image.
            self._write_xl(volume, path, meta.to_bytes())

    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        with self._meta_lock:
            meta = self.read_xl(volume, path)
            meta.find_version(fi.version_id)  # must exist
            meta.add_version(fi)
            # mtpulint: disable=lock-blocking-io -- see write_metadata
            self._write_xl(volume, path, meta.to_bytes())

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        """Remove a version; drop data dir; remove object dir when empty.

        If fi.deleted is set, a delete-marker version is ADDED instead
        (versioned delete), matching the reference DeleteVersion semantics.
        """
        with self._meta_lock:
            if fi.deleted:
                self.write_metadata(volume, path, fi)
                return
            meta = self.read_xl(volume, path)
            removed = meta.delete_version(fi.version_id)
            if removed.data_dir:
                try:
                    self.delete(volume, os.path.join(path, removed.data_dir), recursive=True)
                except errors.DiskError:
                    pass
            if meta.versions:
                # mtpulint: disable=lock-blocking-io -- see write_metadata
                self._write_xl(volume, path, meta.to_bytes())
            else:
                try:
                    self.delete(volume, os.path.join(path, XL_META_FILE))
                except errors.FileNotFound:
                    pass

    # -- atomic object commit ------------------------------------------------

    def rename_data(
        self, src_volume: str, src_path: str, fi: FileInfo, dst_volume: str, dst_path: str
    ) -> None:
        """Commit a staged object: move tmp data dir into the object dir and
        publish the new version in xl.meta (reference RenameData,
        cmd/xl-storage.go; called from erasure putObject :990).

        Barrier order (commit/always modes): fdatasync the staged shards +
        dirs FIRST, then rename, then fsync the object dir, and only then
        write xl.meta -- so no xl.meta can ever name shard bytes the kernel
        hasn't been told to keep."""
        dst_obj_dir = self._file_path(dst_volume, dst_path)
        os.makedirs(dst_obj_dir, exist_ok=True)
        barrier = self._mode() != FSYNC_NEVER
        src_parent = None
        if fi.data_dir:
            src = self._file_path(src_volume, src_path)
            if not os.path.isdir(src):
                raise errors.FileNotFound()
            if barrier:
                _sync_tree(src)
            dst = os.path.join(dst_obj_dir, fi.data_dir)
            if os.path.isdir(dst):
                shutil.rmtree(dst)
            os.rename(src, dst)
            if barrier:
                _sync_dir(dst_obj_dir)
            src_parent = os.path.dirname(src)
        crash.crash_point("storage.rename-data.pre-meta", self.root)
        self.write_metadata(dst_volume, dst_path, fi)
        # The rename consumed tmp/<stage-id>/<i>; drop the now-empty
        # <stage-id> parent so committed PUTs leave tmp/ clean (it used to
        # leak one empty dir per upload per drive -- the recovery scan would
        # count each as an orphan).
        if src_parent is not None:
            try:
                os.rmdir(src_parent)
            except OSError:
                pass  # other shards still staging, or already gone

    def rename_file(self, src_volume: str, src_path: str, dst_volume: str, dst_path: str) -> None:
        src = self._file_path(src_volume, src_path)
        dst = self._file_path(dst_volume, dst_path)
        if not os.path.exists(src):
            raise errors.FileNotFound()
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if self._mode() != FSYNC_NEVER and os.path.isfile(src):
            # Publish-by-rename (multipart part promote): the named bytes
            # must be durable before the durable name exists.
            _sync_path(src)
        os.replace(src, dst)
        if self._mode() != FSYNC_NEVER:
            _sync_dir(os.path.dirname(dst))

    # -- listing / walking ---------------------------------------------------

    def list_dir(self, volume: str, path: str) -> list[str]:
        """Immediate children; dirs get a trailing slash (ListDir contract)."""
        p = self._file_path(volume, path) if path else self._vol_path(volume)
        try:
            names = os.listdir(p)
        except FileNotFoundError:
            raise errors.FileNotFound()
        except NotADirectoryError:
            raise errors.FileNotFound()
        out = []
        for n in sorted(names):
            if os.path.isdir(os.path.join(p, n)):
                out.append(n + "/")
            else:
                out.append(n)
        return out

    def walk_dir(self, volume: str, base: str = "", recursive: bool = True):
        """Yield (object_path, xl.meta bytes) for every object under base,
        in sorted order (the WalkDir streamer, cmd/metacache-walk.go:62).

        An "object" is any directory containing an xl.meta file; walking does
        not descend into data dirs.
        """
        vol = self._vol_path(volume)
        if not os.path.isdir(vol):
            raise errors.VolumeNotFound()
        start = os.path.join(vol, base) if base else vol

        def emit(dir_path: str):
            meta_p = os.path.join(dir_path, XL_META_FILE)
            rel = os.path.relpath(dir_path, vol).replace(os.sep, "/")
            if os.path.isfile(meta_p):
                with open(meta_p, "rb") as f:
                    yield rel, f.read()
                return  # do not descend into data dirs
            try:
                children = sorted(os.listdir(dir_path))
            except (FileNotFoundError, NotADirectoryError):
                return
            for c in children:
                sub = os.path.join(dir_path, c)
                if os.path.isdir(sub):
                    if recursive:
                        yield from emit(sub)
                    else:
                        meta_c = os.path.join(sub, XL_META_FILE)
                        rel_c = os.path.relpath(sub, vol).replace(os.sep, "/")
                        if os.path.isfile(meta_c):
                            with open(meta_c, "rb") as f:
                                yield rel_c, f.read()
                        else:
                            yield rel_c + "/", b""

        if not os.path.isdir(start):
            return
        yield from emit(start)

    # -- bitrot verification -------------------------------------------------

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        """Deep bitrot scan of all part files for a version
        (reference VerifyFile, cmd/xl-storage.go)."""
        from ..ops import bitrot as bitrot_mod

        if fi.inline_data or not fi.data_dir:
            return
        shard_size = fi.erasure.shard_size()
        for part in fi.parts:
            part_path = os.path.join(path, fi.data_dir, f"part.{part.number}")
            data = self.read_file(volume, part_path)
            part_shard_size = fi.erasure.shard_file_size(part.size)
            try:
                bitrot_mod.verify_stream(data, part_shard_size, shard_size)
            except bitrot_mod.BitrotCorrupt as e:
                raise errors.FileCorrupt(str(e))
