"""Drive format file -- cluster topology consensus record.

Role of the reference's format.json v3 (cmd/format-erasure.go:139
newFormatErasureV3): every drive carries
    {deployment id, its own drive id, the full sets layout, distribution algo}
so any quorum of drives can reconstruct the topology, misplaced drives are
detected, and replaced drives are recognized as unformatted.

Stored as JSON at <drive>/.minio_tpu.sys/format.json.
"""

from __future__ import annotations

import json
import os
import uuid
from dataclasses import dataclass, field

from ..utils import errors

SYS_DIR = ".minio_tpu.sys"
FORMAT_FILE = "format.json"

DISTRIBUTION_ALGO_V3 = "SIPMOD+PARITY"  # sipHashMod placement (the modern algo)


@dataclass
class DriveFormat:
    deployment_id: str
    this_id: str
    sets: list[list[str]]  # set -> ordered drive uuids
    distribution_algo: str = DISTRIBUTION_ALGO_V3
    version: int = 1

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": self.version,
                "format": "erasure",
                "id": self.deployment_id,
                "erasure": {
                    "this": self.this_id,
                    "sets": self.sets,
                    "distributionAlgo": self.distribution_algo,
                },
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, raw: str) -> "DriveFormat":
        d = json.loads(raw)
        e = d["erasure"]
        return cls(
            deployment_id=d["id"],
            this_id=e["this"],
            sets=e["sets"],
            distribution_algo=e.get("distributionAlgo", DISTRIBUTION_ALGO_V3),
            version=d.get("version", 1),
        )

    # -- per-drive persistence ----------------------------------------------

    @staticmethod
    def path(drive_root: str) -> str:
        return os.path.join(drive_root, SYS_DIR, FORMAT_FILE)

    def save(self, drive_root: str) -> None:
        p = self.path(drive_root)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        # format.json is the drive's identity: lose it to a torn write and
        # the drive is unformatted on restart. Written once at init, so the
        # barrier is unconditional (not gated on MTPU_FSYNC).
        with open(tmp, "w") as f:
            f.write(self.to_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
        dfd = os.open(os.path.dirname(p), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    @classmethod
    def load(cls, drive_root: str) -> "DriveFormat | None":
        try:
            with open(cls.path(drive_root)) as f:
                return cls.from_json(f.read())
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, KeyError) as e:
            raise errors.FileCorrupt(f"bad format.json: {e}")

    def find_disk(self, disk_id: str) -> tuple[int, int]:
        for s, drive_ids in enumerate(self.sets):
            for d, did in enumerate(drive_ids):
                if did == disk_id:
                    return s, d
        raise errors.DiskIDMismatch(f"disk {disk_id} not in format")


def init_format(
    n_sets: int, set_drive_count: int, deployment_id: str | None = None
) -> list[DriveFormat]:
    """Fresh formats for n_sets x set_drive_count drives
    (initFormatErasure equivalent, cmd/format-erasure.go:818)."""
    dep = deployment_id or str(uuid.uuid4())
    sets = [[str(uuid.uuid4()) for _ in range(set_drive_count)] for _ in range(n_sets)]
    out = []
    for s in range(n_sets):
        for d in range(set_drive_count):
            out.append(DriveFormat(deployment_id=dep, this_id=sets[s][d], sets=sets))
    return out


def quorum_format(formats: list[DriveFormat | None]) -> DriveFormat:
    """Pick the format agreed by a majority of drives
    (getFormatErasureInQuorum, cmd/format-erasure.go:583)."""
    counts: dict[str, int] = {}
    rep: dict[str, DriveFormat] = {}
    for f in formats:
        if f is None:
            continue
        key = f.deployment_id + ":" + json.dumps(f.sets, sort_keys=True)
        counts[key] = counts.get(key, 0) + 1
        rep[key] = f
    if not counts:
        raise errors.UnformattedDisk("no formatted drives")
    key = max(counts, key=lambda k: counts[k])
    n_drives = sum(len(s) for s in rep[key].sets)
    if counts[key] <= n_drives // 2:
        raise errors.ErasureReadQuorum(msg="format.json quorum not reached")
    return rep[key]
