"""StorageAPI -- the per-drive contract every backend implements.

Role of the reference's StorageAPI interface (cmd/storage-interface.go:27-87):
the seam that makes drives interchangeable -- a local directory (LocalDrive),
a remote drive over the storage REST protocol (dist/storage_rest.py client),
or an injected faulty drive in tests. The object layer only ever talks to
this interface.
"""

from __future__ import annotations

import abc
from typing import Iterator

from .types import DiskInfo, FileInfo, VolInfo
from .xlmeta import XLMeta


class StorageAPI(abc.ABC):
    # identity / health
    @abc.abstractmethod
    def endpoint(self) -> str: ...

    @abc.abstractmethod
    def is_online(self) -> bool: ...

    @abc.abstractmethod
    def is_local(self) -> bool: ...

    @abc.abstractmethod
    def disk_id(self) -> str: ...

    @abc.abstractmethod
    def set_disk_id(self, disk_id: str) -> None: ...

    @abc.abstractmethod
    def disk_info(self) -> DiskInfo: ...

    # volumes
    @abc.abstractmethod
    def make_vol(self, volume: str) -> None: ...

    @abc.abstractmethod
    def stat_vol(self, volume: str) -> VolInfo: ...

    @abc.abstractmethod
    def list_vols(self) -> list[VolInfo]: ...

    @abc.abstractmethod
    def delete_vol(self, volume: str, force: bool = False) -> None: ...

    # whole small files
    @abc.abstractmethod
    def write_all(self, volume: str, path: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def read_all(self, volume: str, path: str) -> bytes: ...

    @abc.abstractmethod
    def delete(self, volume: str, path: str, recursive: bool = False) -> None: ...

    # shard files
    @abc.abstractmethod
    def create_file(self, volume: str, path: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def append_file(self, volume: str, path: str, data: bytes) -> None: ...

    def append_iov(self, volume: str, path: str, iovecs: list) -> None:
        """Append a sequence of buffers as one logical write.

        The coalesced shard fan-out hands each drive its whole group as
        digest/chunk views; LocalDrive turns this into a single os.writev.
        The default keeps remote/test drives working through append_file
        (one join, one append)."""
        self.append_file(volume, path, b"".join(iovecs))

    @abc.abstractmethod
    def read_file(self, volume: str, path: str, offset: int = 0, length: int = -1) -> bytes: ...

    def read_file_into(
        self, volume: str, path: str, offset: int, buf: memoryview
    ) -> int:
        """Read up to len(buf) bytes at `offset` directly into `buf`.

        The zero-copy GET pipeline hands each drive a writable window over a
        pooled shard buffer; LocalDrive services this with readinto so the
        bytes land in pooled storage once. The default keeps remote/test
        drives working through read_file (one read, one copy into the view).
        Returns the byte count actually read (short at EOF)."""
        data = self.read_file(volume, path, offset, len(buf))
        n = len(data)
        buf[:n] = data
        return n

    @abc.abstractmethod
    def stat_file(self, volume: str, path: str) -> int: ...

    # object metadata
    @abc.abstractmethod
    def read_xl(self, volume: str, path: str) -> XLMeta: ...

    @abc.abstractmethod
    def read_version(self, volume: str, path: str, version_id: str = "") -> FileInfo: ...

    @abc.abstractmethod
    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None: ...

    @abc.abstractmethod
    def update_metadata(self, volume: str, path: str, fi: FileInfo) -> None: ...

    @abc.abstractmethod
    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None: ...

    # commit / rename
    @abc.abstractmethod
    def rename_data(
        self, src_volume: str, src_path: str, fi: FileInfo, dst_volume: str, dst_path: str
    ) -> None: ...

    @abc.abstractmethod
    def rename_file(
        self, src_volume: str, src_path: str, dst_volume: str, dst_path: str
    ) -> None: ...

    # listing
    @abc.abstractmethod
    def list_dir(self, volume: str, path: str) -> list[str]: ...

    @abc.abstractmethod
    def walk_dir(
        self, volume: str, base: str = "", recursive: bool = True
    ) -> Iterator[tuple[str, bytes]]: ...

    # integrity
    @abc.abstractmethod
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None: ...
