"""Wire/storage datatypes shared across the drive and object layers.

The role of cmd/storage-datatypes.go (FileInfo/DiskInfo/VolInfo msgp structs):
plain dataclasses with msgpack-dict codecs. These cross the storage REST wire
(dist/storage_rest.py) and land in xl.meta (storage/xlmeta.py), so every field
has a stable short key.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field


@dataclass
class ErasureInfo:
    """Erasure geometry + placement for one object version on one drive.

    Mirrors the reference's ErasureInfo (cmd/storage-datatypes.go): the
    distribution is the 1-based drive order from hash_order, and `index` is
    this drive's position in it.
    """

    algorithm: str = "reedsolomon-vandermonde"
    data_blocks: int = 0
    parity_blocks: int = 0
    block_size: int = 1 << 20
    index: int = 0  # 1-based shard index held by this drive
    distribution: list[int] = field(default_factory=list)
    checksums: list[dict] = field(default_factory=list)  # whole-bitrot only

    def shard_size(self) -> int:
        return -(-self.block_size // self.data_blocks)

    def shard_file_size(self, total_length: int) -> int:
        """Final erasure shard size for an object of total_length bytes
        (cmd/erasure-coding.go:127-138 formula)."""
        if total_length == 0:
            return 0
        if total_length < 0:
            return -1
        num_blocks = total_length // self.block_size
        last = total_length % self.block_size
        last_shard = -(-last // self.data_blocks) if last else 0
        return num_blocks * self.shard_size() + last_shard

    def to_dict(self) -> dict:
        return {
            "al": self.algorithm,
            "d": self.data_blocks,
            "p": self.parity_blocks,
            "bs": self.block_size,
            "ix": self.index,
            "ds": self.distribution,
            "cs": self.checksums,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ErasureInfo":
        return cls(
            algorithm=d.get("al", "reedsolomon-vandermonde"),
            data_blocks=d.get("d", 0),
            parity_blocks=d.get("p", 0),
            block_size=d.get("bs", 1 << 20),
            index=d.get("ix", 0),
            distribution=list(d.get("ds", [])),
            checksums=list(d.get("cs", [])),
        )


@dataclass
class ObjectPartInfo:
    number: int
    size: int
    actual_size: int = -1  # pre-compression size; -1 = same as size
    mod_time: float = 0.0
    etag: str = ""

    def to_dict(self) -> dict:
        return {"n": self.number, "s": self.size, "as": self.actual_size,
                "mt": self.mod_time, "e": self.etag}

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectPartInfo":
        return cls(d["n"], d["s"], d.get("as", -1), d.get("mt", 0.0), d.get("e", ""))


@dataclass
class FileInfo:
    """Everything known about one object version on one drive
    (cmd/storage-datatypes.go FileInfo equivalent)."""

    volume: str = ""
    name: str = ""
    version_id: str = ""  # "" = null version
    is_latest: bool = True
    deleted: bool = False  # delete marker
    data_dir: str = ""  # uuid dir holding part files; "" when inline
    mod_time: float = 0.0
    size: int = 0
    metadata: dict[str, str] = field(default_factory=dict)
    parts: list[ObjectPartInfo] = field(default_factory=list)
    erasure: ErasureInfo = field(default_factory=ErasureInfo)
    inline_data: bytes = b""  # small-object data embedded in xl.meta
    fresh: bool = False  # first write of this object
    num_versions: int = 0
    successor_mod_time: float = 0.0

    @property
    def etag(self) -> str:
        return self.metadata.get("etag", "")

    def write_quorum(self, default_parity: int) -> int:
        """data (+1 if data == parity) -- cmd/erasure-object.go:810-813."""
        d = self.erasure.data_blocks
        p = self.erasure.parity_blocks or default_parity
        return d + 1 if d == p else d

    def to_dict(self, with_inline: bool = True) -> dict:
        d = {
            "v": self.volume,
            "n": self.name,
            "vid": self.version_id,
            "del": self.deleted,
            "dd": self.data_dir,
            "mt": self.mod_time,
            "sz": self.size,
            "meta": self.metadata,
            "parts": [p.to_dict() for p in self.parts],
            "ei": self.erasure.to_dict(),
        }
        if with_inline and self.inline_data:
            d["inl"] = self.inline_data
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FileInfo":
        return cls(
            volume=d.get("v", ""),
            name=d.get("n", ""),
            version_id=d.get("vid", ""),
            deleted=d.get("del", False),
            data_dir=d.get("dd", ""),
            mod_time=d.get("mt", 0.0),
            size=d.get("sz", 0),
            metadata=dict(d.get("meta", {})),
            parts=[ObjectPartInfo.from_dict(p) for p in d.get("parts", [])],
            erasure=ErasureInfo.from_dict(d.get("ei", {})),
            inline_data=d.get("inl", b""),
        )


@dataclass
class VolInfo:
    name: str
    created: float = 0.0


@dataclass
class DiskInfo:
    total: int = 0
    free: int = 0
    used: int = 0
    fs_type: str = ""
    root_disk: bool = False
    healing: bool = False
    endpoint: str = ""
    mount_path: str = ""
    disk_id: str = ""
    error: str = ""

    def to_dict(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_dict(cls, d: dict) -> "DiskInfo":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})


def new_uuid() -> str:
    return str(uuid.uuid4())


def now() -> float:
    return time.time()
