"""Restart recovery: sweep crash debris off the drives before serving.

The other half of the durability contract (the barriers in local.py are the
first half). A process death at any commit-path boundary leaves one of a
small set of states on disk, each swept here at node start (and again each
time a pre-fork worker respawns, since every worker re-runs Node.build):

  * ``tmp/<pid>.<uuid>/...``         -- staged PUT / heal shards whose owner
                                        died pre-commit. GC'd once the owner
                                        pid is dead; a LIVE sibling worker's
                                        staging is left alone.
  * ``.../part.N.tmp.<pid>.<hex>``   -- multipart part stage files (the part
                                        was never published). Same pid rule.
  * ``<p>.tmp<rand>``                -- atomic write_all staging that never
                                        reached os.replace. Always safe to GC
                                        (the replace either happened or the
                                        final file is untouched).
  * unreferenced data dirs           -- rename_data died between the data-dir
                                        rename and the xl.meta publish: shard
                                        files exist under the object dir but
                                        no version names them.
  * partial versions                 -- a version committed on j < n drives.
                                        At or above read quorum it is fed to
                                        heal (MRF); below quorum -- the ack
                                        can never have been sent -- it is
                                        rolled back, but ONLY when every
                                        drive in the set is visible (a drive
                                        missing during a rolling restart must
                                        not trigger a mass rollback).

Everything swept is counted (minio_tpu_crash_recovery_* in /metrics) so a
fleet where workers die often shows up as a recovery-rate signal, not as
silently shrinking free space.
"""

from __future__ import annotations

import os
import re

from ..control.sanitizer import san_lock
from ..utils import errors
from .xlmeta import XLMeta

# Matches the atomic-write staging suffix local.py's _write_all uses
# (`<final>.tmp<8 hex chars>`) and the multipart part stage infix.
_TMP_SUFFIX_RE = re.compile(r"\.tmp[0-9a-f]{8}$")
_STAGE_INFIX_RE = re.compile(r"\.tmp\.(\d+)\.[0-9a-f]+$")
_PART_FILE_RE = re.compile(r"^part\.\d+$")

_COUNTER_KEYS = (
    "scans",            # recover_drive passes completed
    "tmp_dirs",         # dead-owner tmp/<stage-id> trees GC'd
    "stage_files",      # dead-owner multipart .tmp. part stages GC'd
    "tmp_files",        # orphaned atomic-write .tmp<rand> files GC'd
    "orphan_data_dirs", # data dirs no xl.meta version references, GC'd
    "corrupt_meta",     # xl.meta that failed to parse (left for heal)
    "partial_healed",   # sub-set-width versions queued for heal
    "partial_gc",       # below-quorum versions rolled back
    "selftest_debris",  # aborted-speedtest scratch volumes dropped
)

# Mirrors control/selftest.py SCRATCH_BUCKET -- kept as a literal so the
# storage layer never imports the control plane (test_selftest pins the two
# constants equal). An aborted speedtest (admin node died mid-ramp) leaves
# probe objects here; they are debris by definition, never client data.
_SELFTEST_BUCKET = ".mtpu-speedtest"

_lock = san_lock("recovery.counters")
_counters: dict = {k: 0 for k in _COUNTER_KEYS}


def counters() -> dict:
    with _lock:
        return dict(_counters)


def _bump(key: str, by: int = 1) -> None:
    if by:
        with _lock:
            _counters[key] += by


def reset_counters() -> None:
    with _lock:
        for k in _COUNTER_KEYS:
            _counters[k] = 0


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


def _owner_pid(name: str) -> int:
    """Owner pid from a `<pid>.<uuid>` stage-dir name; 0 (= dead/unknown,
    always collectable) when the name predates pid-scoped staging."""
    head = name.split(".", 1)[0]
    return int(head) if head.isdigit() else 0


# ---------------------------------------------------------------------------
# per-drive sweep
# ---------------------------------------------------------------------------


def recover_drive(drive, meta_bucket: str = ".minio_tpu.sys") -> dict:
    """Sweep one drive's crash debris. Returns this pass's deltas."""
    before = counters()
    _sweep_tmp(drive, meta_bucket)
    _sweep_multipart_stages(drive, meta_bucket)
    _sweep_selftest(drive)
    for vol in _safe_vols(drive):
        _sweep_volume(drive, vol.name)
    _bump("scans")
    after = counters()
    return {k: after[k] - before[k] for k in _COUNTER_KEYS}


def _safe_vols(drive):
    try:
        return drive.list_vols()
    except errors.StorageError:
        return []


def _sweep_tmp(drive, meta_bucket: str) -> None:
    """GC tmp/<stage-id> trees whose owner pid is dead."""
    try:
        names = drive.list_dir(meta_bucket, "tmp")
    except errors.StorageError:
        return
    for name in names:
        entry = name.rstrip("/")
        if name.endswith("/") and _pid_alive(_owner_pid(entry)):
            continue  # a live worker is still staging here
        try:
            drive.delete(meta_bucket, f"tmp/{entry}", recursive=True)
            _bump("tmp_dirs")
        except errors.StorageError:
            pass


def _sweep_selftest(drive) -> None:
    """Drop the whole speedtest scratch volume if a dead probe left it
    behind (a completed probe already removed it)."""
    try:
        drive.delete_vol(_SELFTEST_BUCKET, force=True)
        _bump("selftest_debris")
    except errors.VolumeNotFound:
        pass
    except errors.StorageError:
        pass


def _sweep_multipart_stages(drive, meta_bucket: str) -> None:
    """GC `.tmp.<pid>.<hex>` part stage files with dead owners. Upload dirs
    themselves are NOT debris -- in-progress multipart uploads survive
    restarts by design (abort/expiry owns their lifecycle)."""

    def recurse(path: str) -> None:
        try:
            names = drive.list_dir(meta_bucket, path)
        except errors.StorageError:
            return
        for name in names:
            child = f"{path}/{name.rstrip('/')}"
            if name.endswith("/"):
                recurse(child)
                continue
            m = _STAGE_INFIX_RE.search(name)
            if m and not _pid_alive(int(m.group(1))):
                try:
                    drive.delete(meta_bucket, child)
                    _bump("stage_files")
                except errors.StorageError:
                    pass

    recurse("multipart")


def _sweep_volume(drive, volume: str) -> None:
    """Walk a bucket tree directly, GC'ing stale atomic-write staging files
    and data dirs no xl.meta version references.

    Walks the filesystem rather than walk_dir because the debris is exactly
    what walk_dir is designed to skip (non-object files, dirs without
    xl.meta)."""
    root = drive._vol_path(volume)  # recovery is a LocalDrive-family concern

    def recurse(dir_path: str) -> None:
        try:
            names = sorted(os.listdir(dir_path))
        except OSError:
            return
        has_meta = "xl.meta" in names
        referenced: set | None = None
        if has_meta:
            try:
                with open(os.path.join(dir_path, "xl.meta"), "rb") as f:
                    meta = XLMeta.from_bytes(f.read())
                referenced = {v.data_dir for v in meta.versions if v.data_dir}
            except (OSError, errors.StorageError):
                # Unreadable commit record: nothing under this dir can be
                # proven orphan. Count it and let bitrot/heal judge.
                _bump("corrupt_meta")
                return
        for name in names:
            p = os.path.join(dir_path, name)
            if os.path.isfile(p):
                m = _STAGE_INFIX_RE.search(name)
                if _TMP_SUFFIX_RE.search(name) or (
                    m and not _pid_alive(int(m.group(1)))
                ):
                    try:
                        os.remove(p)
                        _bump("tmp_files")
                    except OSError:
                        pass
                continue
            if not os.path.isdir(p):
                continue
            if referenced is not None:
                # Child dirs of an object dir are data dirs: keep only the
                # ones a version names.
                if name not in referenced:
                    import shutil

                    try:
                        shutil.rmtree(p)
                        _bump("orphan_data_dirs")
                    except OSError:
                        pass
                continue
            if _is_orphan_data_dir(p):
                # part.N files with no xl.meta beside them: rename_data died
                # before the metadata publish. The version never reached
                # this drive's xl.meta, so the shards are unreachable.
                import shutil

                try:
                    shutil.rmtree(p)
                    _bump("orphan_data_dirs")
                except OSError:
                    pass
                continue
            recurse(p)
        if dir_path != root and not has_meta:
            # A prefix dir left empty by the GC above (or by a rename that
            # died after makedirs) is a phantom prefix in listings; rmdir
            # only succeeds when it is actually empty, so a dir that still
            # holds live children is untouched.
            try:
                os.rmdir(dir_path)
            except OSError:
                pass

    recurse(root)


def _is_orphan_data_dir(dir_path: str) -> bool:
    try:
        names = os.listdir(dir_path)
    except OSError:
        return False
    return bool(names) and all(
        _PART_FILE_RE.match(n) and os.path.isfile(os.path.join(dir_path, n))
        for n in names
    )


# ---------------------------------------------------------------------------
# cross-drive reconciliation
# ---------------------------------------------------------------------------


def recover_set(eo, heal=None) -> dict:
    """Reconcile partially committed versions across one erasure set.

    For every version present on fewer than all drives: at or above its own
    read quorum (data_blocks from its erasure info) it is handed to `heal`
    (MRF signature: heal(bucket, object, version_id)); below quorum it is
    rolled back -- but rollback requires EVERY drive in the set online and
    readable, so a rolling restart can only ever queue heals, never GC."""
    before = counters()
    disks = list(eo.disks)
    n = len(disks)
    all_visible = all(d is not None and d.is_online() for d in disks)

    buckets: set[str] = set()
    for d in disks:
        if d is None:
            continue
        try:
            buckets.update(v.name for v in d.list_vols())
        except errors.StorageError:
            all_visible = False

    for bucket in sorted(buckets):
        # (object, version_id) -> [k_of_version, holder drive indices]
        seen: dict = {}
        visible = all_visible
        for i, d in enumerate(disks):
            if d is None:
                continue
            try:
                for obj_path, raw in d.walk_dir(bucket):
                    if not raw:
                        continue
                    try:
                        meta = XLMeta.from_bytes(raw)
                    except errors.StorageError:
                        _bump("corrupt_meta")
                        continue
                    for v in meta.versions:
                        key = (obj_path, v.version_id)
                        ent = seen.setdefault(key, [v.erasure.data_blocks or 0, []])
                        ent[1].append(i)
            except errors.StorageError:
                visible = False
        for (obj_path, vid), (k_of, holders) in seen.items():
            if len(holders) >= n:
                continue
            quorum = k_of if k_of > 0 else (n - getattr(eo, "parity", 0))
            if len(holders) >= quorum:
                if heal is not None:
                    heal(bucket, obj_path, vid)
                    _bump("partial_healed")
                continue
            if not visible:
                continue  # can't prove it never reached quorum: leave it
            from .types import FileInfo

            for i in holders:
                try:
                    disks[i].delete_version(
                        bucket, obj_path, FileInfo(version_id=vid)
                    )
                except errors.StorageError:
                    pass
            _bump("partial_gc")
    after = counters()
    return {k: after[k] - before[k] for k in _COUNTER_KEYS}
