"""Per-drive circuit breaker + admission control: HealthGatedDrive.

Role of the reference's disk health tracking inside xlStorageDiskIDCheck
(cmd/xl-storage-disk-id-check.go:174 diskHealthTracker: consecutive-failure
counting, the drive taken OFFLINE and probed back with a monitor goroutine)
merged with its per-disk concurrency clamp (errDiskOngoingReq). Layered in
dist/node.py between MeteredDrive and FaultyDisk --
MeteredDrive(HealthGatedDrive(FaultyDisk(LocalDrive))) -- so injected chaos
faults trip the breaker exactly like kernel EIOs would, and the metered
EWMAs time the breaker's fail-fast refusals like any other outcome.

Breaker states:
  CLOSED    -- healthy; calls flow through, outcomes are scored.
  OPEN      -- tripped after N consecutive health-relevant errors or a
               sustained latency EWMA blowout. Every gated call fails fast
               with errors.CircuitOpen (quorum-countable: the erasure layer
               routes around the drive). is_online() reports False so
               reads/writes stop selecting the drive at all.
  HALF_OPEN -- a background probe thread (jittered cool-down between
               attempts, transport.jitter discipline) tries a real
               disk_info() against the inner drive; success re-closes the
               breaker, failure re-opens it with a grown cool-down.

Admission: a bounded in-flight semaphore per drive. When the window is
full the call is refused immediately with errors.DriveBusy instead of
queueing unboundedly -- shed load surfaces as a quorum-countable error the
caller can route around, and the node-level gate (api/server.py) turns
sustained shedding into SlowDown 503s with Retry-After.
"""

from __future__ import annotations

import threading
import time

from ..control.degrade import GLOBAL_DEGRADE
from ..utils import errors
from .metered import _METERED
from ..control.sanitizer import san_lock, san_rlock

# Gate the same call set MeteredDrive times: everything that hits the disk.
_GATED = _METERED

# Errors that count against drive HEALTH. Application-level outcomes
# (FileNotFound on a missing object, VolumeNotFound on a fresh bucket) are
# the drive answering correctly and must never trip the breaker.
_HEALTH_ERRORS = (
    errors.FaultyDisk,
    errors.DiskNotFound,
    errors.DiskAccessDenied,
    errors.DiskFull,
)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_EWMA_ALPHA = 0.3


class CircuitBreaker:
    """Trip/probe state machine for one drive.

    Separable from the StorageAPI wrapper so transport-level health (a
    RemoteDrive's RestClient) could reuse it; HealthGatedDrive owns one.
    """

    def __init__(
        self,
        name: str = "",
        error_threshold: int = 5,
        latency_limit_ms: float = 30_000.0,
        latency_min_samples: int = 16,
        cooldown: float = 2.0,
        max_cooldown: float = 30.0,
        probe=None,
        clock=time.monotonic,
    ):
        self.name = name
        self.error_threshold = error_threshold
        self.latency_limit_ms = latency_limit_ms
        self.latency_min_samples = latency_min_samples
        self.cooldown = cooldown
        self.max_cooldown = max_cooldown
        self._probe = probe  # zero-arg callable; raising = still unhealthy
        self._clock = clock
        self._lock = san_lock("CircuitBreaker._lock")
        self.state = CLOSED
        self.consecutive_errors = 0
        self.trips = 0
        self.ewma_ms: float | None = None
        self.samples = 0
        self._current_cooldown = cooldown
        self._probe_thread: threading.Thread | None = None
        self._closed_evt = threading.Event()  # probe thread exit signal

    # -- outcome scoring -----------------------------------------------------

    def record_success(self, duration_ms: float) -> None:
        with self._lock:
            self.consecutive_errors = 0
            self._score_latency_locked(duration_ms)

    def record_error(self, exc: Exception, duration_ms: float) -> None:
        """Score a failed call. Only health-relevant errors count toward the
        trip threshold; a FileNotFound still proves the drive is answering
        and RESETS the consecutive counter like a success."""
        health = isinstance(exc, _HEALTH_ERRORS) or not isinstance(
            exc, errors.StorageError
        )
        with self._lock:
            if not health:
                self.consecutive_errors = 0
                return
            self.consecutive_errors += 1
            if self.state == CLOSED and self.consecutive_errors >= self.error_threshold:
                self._trip_locked(f"{self.consecutive_errors} consecutive errors")

    def _score_latency_locked(self, duration_ms: float) -> None:
        prev = self.ewma_ms
        self.ewma_ms = (
            duration_ms if prev is None else prev + _EWMA_ALPHA * (duration_ms - prev)
        )
        self.samples += 1
        if (
            self.state == CLOSED
            and self.samples >= self.latency_min_samples
            and self.ewma_ms > self.latency_limit_ms
        ):
            self._trip_locked(f"latency EWMA {self.ewma_ms:.0f}ms over limit")

    # -- state machine -------------------------------------------------------

    def _trip_locked(self, why: str) -> None:
        self.state = OPEN
        self.trips += 1
        self._current_cooldown = self.cooldown
        GLOBAL_DEGRADE.record_breaker(tripped=True)
        import logging

        logging.getLogger("minio_tpu.breaker").warning(
            "circuit OPEN for drive %s: %s", self.name, why
        )
        self._start_probe_locked()

    def _start_probe_locked(self) -> None:
        if self._probe is None:
            return
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return
        self._closed_evt.clear()
        t = threading.Thread(
            target=self._probe_loop, name=f"breaker-probe:{self.name}", daemon=True
        )
        self._probe_thread = t
        t.start()

    def _probe_loop(self) -> None:
        """Background half-open probing: after a jittered cool-down, try one
        real call against the inner drive. Success closes the breaker;
        failure re-opens with a grown cool-down (capped), so a dead drive
        is probed ever more lazily instead of hammered."""
        from ..dist.transport import jitter

        while not self._closed_evt.wait(jitter(self._current_cooldown)):
            with self._lock:
                if self.state == CLOSED:
                    return
                self.state = HALF_OPEN
            try:
                self._probe()
            except Exception:  # noqa: BLE001 - any failure = still sick
                with self._lock:
                    self.state = OPEN
                    self._current_cooldown = min(
                        self._current_cooldown * 2, self.max_cooldown
                    )
                continue
            self.reset()
            return

    def reset(self) -> None:
        """Close the breaker (probe success, or an operator override)."""
        with self._lock:
            was_open = self.state != CLOSED
            self.state = CLOSED
            self.consecutive_errors = 0
            self.ewma_ms = None
            self.samples = 0
            self._current_cooldown = self.cooldown
        self._closed_evt.set()
        if was_open:
            GLOBAL_DEGRADE.record_breaker(tripped=False)
            import logging

            logging.getLogger("minio_tpu.breaker").info(
                "circuit CLOSED for drive %s", self.name
            )

    def close(self) -> None:
        """Teardown: stop probing WITHOUT closing the circuit state (an open
        breaker at shutdown stays open; reset() is the operator path)."""
        self._closed_evt.set()
        t = self._probe_thread
        if t is not None:
            t.join(5.0)

    def allows(self) -> bool:
        return self.state == CLOSED

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "trips": self.trips,
                "consecutive_errors": self.consecutive_errors,
                "ewma_ms": round(self.ewma_ms, 3) if self.ewma_ms is not None else None,
            }


class HealthGatedDrive:
    """Transparent StorageAPI decorator: circuit breaker + bounded in-flight
    admission in front of the inner drive (the MeteredDrive/FaultyDisk
    __dict__-assignment decorator idiom)."""

    # Class-level defaults; dist/node.py or tests may pass overrides.
    MAX_INFLIGHT = 64

    def __init__(
        self,
        inner,
        breaker: CircuitBreaker | None = None,
        max_inflight: int | None = None,
    ):
        self.__dict__["inner"] = inner
        if breaker is None:
            breaker = CircuitBreaker(
                name=inner.endpoint(),
                probe=lambda: inner.disk_info(),
            )
        elif breaker._probe is None:
            breaker._probe = lambda: inner.disk_info()
        if not breaker.name:
            breaker.name = inner.endpoint()
        self.__dict__["breaker"] = breaker
        self.__dict__["_sem"] = threading.BoundedSemaphore(
            max_inflight if max_inflight is not None else self.MAX_INFLIGHT
        )

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name not in _GATED or not callable(attr):
            return attr
        breaker: CircuitBreaker = self.breaker
        sem: threading.BoundedSemaphore = self._sem

        def gated(*args, **kwargs):
            if not breaker.allows():
                raise errors.CircuitOpen(f"breaker open: {breaker.name}")
            if not sem.acquire(blocking=False):
                GLOBAL_DEGRADE.record_shed("drive")
                raise errors.DriveBusy(f"drive in-flight window full: {breaker.name}")
            t0 = time.perf_counter()
            try:
                out = attr(*args, **kwargs)
            except Exception as e:
                breaker.record_error(e, (time.perf_counter() - t0) * 1e3)
                raise
            finally:
                sem.release()
            breaker.record_success((time.perf_counter() - t0) * 1e3)
            return out

        return gated

    # walk_dir stays a REAL generator function so MeteredDrive's
    # isgeneratorfunction check keeps timing full iterations through this
    # wrapper (the FaultyDisk discipline). The breaker gates creation and
    # scores the complete walk; admission covers only the iteration window.
    def walk_dir(self, volume: str, base: str = "", recursive: bool = True):
        breaker: CircuitBreaker = self.breaker
        if not breaker.allows():
            raise errors.CircuitOpen(f"breaker open: {breaker.name}")
        if not self._sem.acquire(blocking=False):
            GLOBAL_DEGRADE.record_shed("drive")
            raise errors.DriveBusy(f"drive in-flight window full: {breaker.name}")
        t0 = time.perf_counter()
        try:
            yield from self.inner.walk_dir(volume, base, recursive)
        except Exception as e:
            breaker.record_error(e, (time.perf_counter() - t0) * 1e3)
            raise
        finally:
            self._sem.release()
        breaker.record_success((time.perf_counter() - t0) * 1e3)

    def __setattr__(self, name, value):
        if name in self.__dict__:
            self.__dict__[name] = value
        else:
            setattr(self.inner, name, value)

    # -- health surface ------------------------------------------------------

    def is_online(self) -> bool:
        """Offline while the breaker is anything but CLOSED: half-open
        recovery rides the background probe, not live traffic, so one
        flapping drive can't keep poisoning reads while it convalesces."""
        return self.breaker.allows() and self.inner.is_online()

    def breaker_state(self) -> dict:
        """Snapshot for metrics/admin: state, trips, consecutive errors."""
        return self.breaker.snapshot()
