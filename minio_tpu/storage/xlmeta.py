"""xl.meta -- the per-object versioned metadata file.

Role of the reference's xlMetaV2 (cmd/xl-storage-format-v2.go:779): one file
per object per drive holding every version (objects + delete markers), with
optional inline data for small objects (cmd/xl-storage-meta-inline.go), the
whole thing integrity-checked. Format here is fresh (not wire-compatible):

    magic   b"XLTP"                (4 bytes)
    version u8 = 1
    len     u32-le of msgpack body
    body    msgpack map {"versions": [version-dict, ...]}
    sum     xxh64-le of body       (8 bytes)
    inline  concatenated inline-data blobs referenced by (offset, length)
            from each version dict ("ioff"/"ilen")

Versions are kept sorted newest-first by (mod_time, version_id), matching the
reference's ordering contract (xl-storage-format-v2.go sorting by ModTime).
"""

from __future__ import annotations

import struct

import msgpack
import xxhash

from ..utils import errors
from .types import FileInfo

MAGIC = b"XLTP"
FORMAT_VERSION = 1

# Inline threshold: small objects embed shard bytes straight into xl.meta
# (reference smallFileThreshold = 128 KiB, cmd/xl-storage.go:59).
SMALL_FILE_THRESHOLD = 128 * 1024


class XLMeta:
    """In-memory versioned metadata for one object on one drive."""

    def __init__(self):
        self.versions: list[FileInfo] = []

    # -- version bookkeeping ------------------------------------------------

    def _sort(self) -> None:
        self.versions.sort(key=lambda f: (f.mod_time, f.version_id), reverse=True)

    def add_version(self, fi: FileInfo) -> None:
        """Insert or replace the version with fi.version_id."""
        self.versions = [v for v in self.versions if v.version_id != fi.version_id]
        self.versions.append(fi)
        self._sort()

    def delete_version(self, version_id: str) -> FileInfo:
        for i, v in enumerate(self.versions):
            if v.version_id == version_id:
                return self.versions.pop(i)
        raise errors.FileVersionNotFound(version_id)

    def find_version(self, version_id: str) -> FileInfo:
        if version_id == "":
            if not self.versions:
                raise errors.FileNotFound()
            return self.latest()
        for v in self.versions:
            if v.version_id == version_id:
                return v
        raise errors.FileVersionNotFound(version_id)

    def latest(self) -> FileInfo:
        if not self.versions:
            raise errors.FileNotFound()
        return self.versions[0]

    def file_info(self, version_id: str = "") -> FileInfo:
        fi = self.find_version(version_id)
        fi.is_latest = fi is self.versions[0]
        fi.num_versions = len(self.versions)
        return fi

    # -- serialization ------------------------------------------------------

    def to_bytes(self) -> bytes:
        inline_blobs: list[bytes] = []
        offset = 0
        vdicts = []
        for v in self.versions:
            d = v.to_dict(with_inline=False)
            if v.inline_data:
                d["ioff"] = offset
                d["ilen"] = len(v.inline_data)
                inline_blobs.append(v.inline_data)
                offset += len(v.inline_data)
            vdicts.append(d)
        body = msgpack.packb({"versions": vdicts}, use_bin_type=True)
        check = xxhash.xxh64(body).intdigest()
        return b"".join(
            [
                MAGIC,
                bytes([FORMAT_VERSION]),
                struct.pack("<I", len(body)),
                body,
                struct.pack("<Q", check),
                *inline_blobs,
            ]
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "XLMeta":
        if len(raw) < 17 or raw[:4] != MAGIC:
            raise errors.FileCorrupt("bad xl.meta magic")
        if raw[4] != FORMAT_VERSION:
            raise errors.FileCorrupt(f"unknown xl.meta version {raw[4]}")
        (body_len,) = struct.unpack_from("<I", raw, 5)
        body_start = 9
        body = raw[body_start : body_start + body_len]
        if len(body) != body_len:
            raise errors.FileCorrupt("truncated xl.meta body")
        (want,) = struct.unpack_from("<Q", raw, body_start + body_len)
        if xxhash.xxh64(body).intdigest() != want:
            raise errors.FileCorrupt("xl.meta checksum mismatch")
        inline_base = body_start + body_len + 8
        doc = msgpack.unpackb(body, raw=False, strict_map_key=False)
        meta = cls()
        for d in doc.get("versions", []):
            fi = FileInfo.from_dict(d)
            if "ilen" in d:
                off = inline_base + d["ioff"]
                fi.inline_data = raw[off : off + d["ilen"]]
                if len(fi.inline_data) != d["ilen"]:
                    raise errors.FileCorrupt("truncated inline data")
            meta.versions.append(fi)
        meta._sort()
        return meta
