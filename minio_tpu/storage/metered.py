"""Per-drive API metering: latency EWMAs + storage call tracing.

Role of the reference's xlStorageDiskIDCheck (cmd/xl-storage-disk-id-check.go
:68,:74,:585): every StorageAPI call through a drive is timed into a
per-API exponentially-weighted moving average, and published to the trace
hub when someone is watching (`mc admin trace --call storage`), at zero
cost otherwise (NumSubscribers guard, :580-588).
"""

from __future__ import annotations

import inspect
import threading
import time

from ..control.perf import GLOBAL_PERF
from ..control.profiler import COPIED, GLOBAL_PROFILER, MOVED
from ..control.sanitizer import san_lock, san_rlock

# StorageAPI methods that hit the disk (the metered set).
_METERED = frozenset(
    (
        "disk_info make_vol stat_vol list_vols delete_vol write_all read_all "
        "delete create_file append_file append_iov read_file read_file_into "
        "stat_file read_xl "
        "read_version write_metadata update_metadata delete_version "
        "rename_data rename_file list_dir walk_dir verify_file"
    ).split()
)

# Copy-ledger hop classification for the drive boundary: writes hand the
# caller's buffer straight to the OS (moved); reads materialize fresh bytes
# from the page cache (copied).
_WRITE_BYTES = frozenset({"write_all", "create_file", "append_file"})
_READ_BYTES = frozenset({"read_file", "read_all"})

_EWMA_ALPHA = 0.3  # same smoothing idea as the reference's diskMaxTimeout ewma


class MeteredDrive:
    """Transparent StorageAPI decorator. Everything delegates to the inner
    drive; metered methods are timed."""

    def __init__(self, inner, trace=None):
        # __dict__ assignment avoids recursing through __setattr__/__getattr__.
        self.__dict__["inner"] = inner
        self.__dict__["trace"] = trace
        self.__dict__["_lat"] = {}
        self.__dict__["_counts"] = {}
        self.__dict__["_errors"] = {}
        self.__dict__["_lock"] = san_lock("MeteredDrive._lock")

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name not in _METERED or not callable(attr):
            return attr

        def record(t0: float, c0: float, failed: bool) -> None:
            dt = time.perf_counter() - t0
            ms = dt * 1e3
            # Always-on attribution: storage calls feed the stage ledger
            # directly (one bucket increment) -- drive fan-out pool threads
            # have no span context, so Span.finish can't cover them. The
            # thread_time delta is valid because record runs on the calling
            # thread: wall >> cpu here means the drive (or page cache) is
            # the wait, not the interpreter.
            GLOBAL_PERF.ledger.record("storage", name, dt, time.thread_time() - c0)
            with self._lock:
                if failed:
                    self._errors[name] = self._errors.get(name, 0) + 1
                prev = self._lat.get(name)
                self._lat[name] = (
                    ms if prev is None else prev + _EWMA_ALPHA * (ms - prev)
                )
                self._counts[name] = self._counts.get(name, 0) + 1
            trace = self.trace
            if trace is not None and trace.enabled():
                from ..control import tracing

                # When a request trace is active, the storage call is a span
                # in its tree (per-drive children of the object-layer span);
                # otherwise it stays a flat storage record.
                cur = tracing.current()
                if cur is not None:
                    trace.publish(
                        "span",
                        name=f"storage.{name}",
                        layer="storage",
                        trace=cur.trace_id,
                        span=tracing._new_id(),
                        parent=cur.span_id,
                        call=name,
                        drive=self.inner.endpoint(),
                        duration_ms=round(ms, 3),
                        error=failed or None,
                    )
                else:
                    trace.publish(
                        "storage",
                        call=name,
                        drive=self.inner.endpoint(),
                        duration_ms=round(ms, 3),
                    )

        if inspect.isgeneratorfunction(getattr(type(self.inner), name, None)):
            # Generators (walk_dir): time the FULL iteration and count errors
            # raised mid-stream — timing creation alone would always read 0.
            def timed_gen(*args, **kwargs):
                t0 = time.perf_counter()
                c0 = time.thread_time()
                try:
                    yield from attr(*args, **kwargs)
                except Exception:
                    record(t0, c0, failed=True)
                    raise
                record(t0, c0, failed=False)

            return timed_gen

        def timed(*args, **kwargs):
            t0 = time.perf_counter()
            c0 = time.thread_time()
            try:
                out = attr(*args, **kwargs)
            except Exception:
                record(t0, c0, failed=True)
                raise
            record(t0, c0, failed=False)
            if name == "append_iov":
                iovecs = kwargs.get("iovecs") if len(args) < 3 else args[2]
                if iovecs:
                    GLOBAL_PROFILER.copy.record(
                        "drive-write", MOVED, sum(len(v) for v in iovecs)
                    )
            elif name in _WRITE_BYTES:
                data = kwargs.get("data") if len(args) < 3 else args[2]
                if data is not None:
                    GLOBAL_PROFILER.copy.record("drive-write", MOVED, len(data))
            elif name in _READ_BYTES and out is not None:
                GLOBAL_PROFILER.copy.record("drive-read", COPIED, len(out))
            elif name == "read_file_into" and out:
                # readinto lands bytes in the caller's pooled window: the
                # drive boundary moves them, nothing is materialized fresh.
                GLOBAL_PROFILER.copy.record("drive-read", MOVED, int(out))
            return out

        return timed

    def __setattr__(self, name, value):
        if name in self.__dict__:
            self.__dict__[name] = value  # wrapper-owned fields stay here
        else:
            setattr(self.inner, name, value)

    # -- metrics surface (healthinfo / admin info read these) ----------------

    def api_latencies(self) -> dict:
        with self._lock:
            return {
                name: {
                    "ewma_ms": round(self._lat[name], 3),
                    "count": self._counts.get(name, 0),
                    "errors": self._errors.get(name, 0),
                }
                for name in sorted(self._lat)
            }

    def reset_api_latencies(self) -> None:
        """Drop EWMAs/counts/errors (the /perf ?reset= knob): before/after
        measurements need a clean slate, not an average polluted by boot."""
        with self._lock:
            self._lat.clear()
            self._counts.clear()
            self._errors.clear()
