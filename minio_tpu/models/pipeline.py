"""The flagship device program: fused erasure-encode + bitrot-hash pipeline.

One jitted step turns a batch of 1 MiB-block data shards into parity shards
plus per-shard HighwayHash-256 bitrot digests -- the device-side fusion of the
reference's per-request hot loop (cmd/erasure-encode.go:73-109 feeding
cmd/bitrot-streaming.go:43-65), batched across concurrent uploads so the
host<->device transfer and kernel launches amortize (the BASELINE.json north
star). The decode/heal steps reuse the same GF(2) matmul with reconstruction
weights (cmd/erasure-decode.go:206, erasure-lowlevel-heal.go:31 equivalents).

With a mesh, the steps are pjit-sharded: encode runs with bytes sp-sharded
(pointwise in the byte axis), then the encode->hash boundary reshards streams
across (tp, sp) -- an all-to-all over ICI, the storage analogue of sequence
parallelism. See parallel/mesh.py.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

import os

from ..ops import bitmatrix
from ..ops import fused as fused_ops
from ..ops import highwayhash_jax as hhj
from ..ops import rs, rs_matrix, rs_pallas
from ..parallel import mesh as mesh_lib
from ..control.sanitizer import san_lock, san_rlock


# Per-backend hash-kernel selection, cached after one probe+timing pass:
# {"choice": "pallas"|"xla", "pallas_ok": bool, "pallas_gibs": float,
#  "xla_gibs": float, "detail": str}
_HASH_SELECT: dict[str, dict] = {}
# Guards the check-then-probe in hash_selection(): two threads racing the
# first call would otherwise both run the (expensive, jit-compiling) probe
# and clobber each other's verdict.
_HASH_SELECT_LOCK = san_lock("pipeline._HASH_SELECT_LOCK")

# Same shape, for the RS encode kernel (XOR-bitmatrix Pallas vs XLA bit-
# matmul). Separate lock: a hash probe and an rs probe may run concurrently.
_RS_SELECT: dict[str, dict] = {}
_RS_SELECT_LOCK = san_lock("pipeline._RS_SELECT_LOCK")

# Production chunk length: the per-shard slice a 1 MiB block / 12 data
# shards produces (cmd/erasure-utils.go shard math) — the length every
# serving PutObject actually hashes. Probing at toy sizes let a kernel
# that lowers at 8 packets but breaks at the real multi-step grid pass.
_PROBE_CHUNK = rs_matrix.shard_size(1 << 20, 12)


def _probe_and_time_hash(backend: str) -> dict:
    """Correctness-probe the Pallas hash at PRODUCTION chunk size, then time
    it against the XLA scan and select by measurement.

    The Pallas kernel must (a) lower on this backend (Mosaic op support
    varies by release) and (b) match the host oracle bit-for-bit at the
    real ~87 KiB serving chunk length — a multi-step grid, not the 8-packet
    toy shape round 3 probed — before it may serve. A kernel that fails
    either degrades to the XLA scan rather than crashing every PutObject.
    """
    sel = {"choice": "xla", "pallas_ok": False, "pallas_gibs": 0.0,
           "xla_gibs": 0.0, "detail": ""}
    if backend not in ("tpu", "axon"):
        # On CPU the Pallas kernel only runs in interpret mode — a pure-
        # Python emulation orders of magnitude slower than compiled XLA,
        # not a serving-grade candidate; timing it at 87 KiB would stall
        # server boot for minutes to confirm a foregone conclusion.
        sel["detail"] = f"backend={backend}: pallas=interpret-only, xla serves"
        return sel
    import time as _time

    from ..ops import highwayhash as hh_host
    from ..ops import highwayhash_pallas as hhp

    rng = np.random.default_rng(7)
    probe = rng.integers(0, 256, (2, _PROBE_CHUNK), dtype=np.uint8)
    try:
        got = np.asarray(hhp.hash256_batch(probe))
        want = hh_host.hash256_batch(probe)
        sel["pallas_ok"] = np.array_equal(got, want)
        if not sel["pallas_ok"]:
            sel["detail"] = f"pallas mismatch at L={_PROBE_CHUNK}"
            return sel
    except Exception as e:  # noqa: BLE001 - any lowering/runtime failure
        sel["detail"] = f"pallas probe failed: {type(e).__name__}: {e}"[:300]
        return sel

    # Both correct — pick by measured throughput at the serving shape.
    timing = rng.integers(0, 256, (16, _PROBE_CHUNK), dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(timing))
    nbytes = timing.size

    def _gibs(fn):
        jax.block_until_ready(fn(dev))  # compile
        t0 = _time.perf_counter()
        iters = 4
        for _ in range(iters):
            out = fn(dev)
        jax.block_until_ready(out)
        return nbytes * iters / (_time.perf_counter() - t0) / (1 << 30)

    try:
        sel["pallas_gibs"] = _gibs(jax.jit(hhp.hash256_batch))
        sel["xla_gibs"] = _gibs(jax.jit(hhj.hash256_batch))
    except Exception as e:  # noqa: BLE001
        sel["detail"] = f"timing failed: {type(e).__name__}: {e}"[:300]
        return sel
    sel["choice"] = "pallas" if sel["pallas_gibs"] >= sel["xla_gibs"] else "xla"
    sel["detail"] = (
        f"measured @L={_PROBE_CHUNK}: pallas={sel['pallas_gibs']:.2f} "
        f"xla={sel['xla_gibs']:.2f} GiB/s -> {sel['choice']}"
    )
    return sel


def hash_selection() -> dict:
    """The cached per-backend probe+timing verdict (for diagnostics/bench)."""
    backend = jax.default_backend()
    with _HASH_SELECT_LOCK:
        if backend not in _HASH_SELECT:
            _HASH_SELECT[backend] = _probe_and_time_hash(backend)
        return _HASH_SELECT[backend]


def _probe_and_time_rs(backend: str) -> dict:
    """Correctness-probe the XOR-bitmatrix Pallas encode at production shape,
    then time it against the XLA GF(2) bit-matmul and select by measurement.

    Mirrors _probe_and_time_hash: the kernel must lower on this backend AND
    match the XLA path bit-for-bit (which is itself pinned to the golden
    vectors) at the real (12, 4) x ~87 KiB serving shape before it may
    serve. Any failure degrades to the XLA matmul with the cause recorded --
    never a silent 0.0.
    """
    sel = {"choice": "xla", "pallas_ok": False, "pallas_gibs": 0.0,
           "xla_gibs": 0.0, "detail": ""}
    if backend not in ("tpu", "axon"):
        sel["detail"] = f"backend={backend}: pallas=interpret-only, xla serves"
        return sel
    import time as _time

    rng = np.random.default_rng(11)
    pc = rs_pallas.RSPallasCodec(12, 4)
    xc = rs.RSCodec(12, 4)
    probe = rng.integers(0, 256, (2, 12, _PROBE_CHUNK), dtype=np.uint8)
    try:
        got = np.asarray(pc.encode(probe))
        want = np.asarray(xc.encode(probe))
        sel["pallas_ok"] = np.array_equal(got, want)
        if not sel["pallas_ok"]:
            sel["detail"] = f"pallas encode mismatch at S={_PROBE_CHUNK}"
            return sel
    except Exception as e:  # noqa: BLE001 - any lowering/runtime failure
        sel["detail"] = f"pallas probe failed: {type(e).__name__}: {e}"[:300]
        return sel

    timing = rng.integers(0, 256, (16, 12, _PROBE_CHUNK), dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(timing))
    nbytes = timing.size

    def _gibs(fn):
        jax.block_until_ready(fn(dev))  # compile
        t0 = _time.perf_counter()
        iters = 4
        for _ in range(iters):
            out = fn(dev)
        jax.block_until_ready(out)
        return nbytes * iters / (_time.perf_counter() - t0) / (1 << 30)

    try:
        sel["pallas_gibs"] = _gibs(jax.jit(pc.encode))
        sel["xla_gibs"] = _gibs(jax.jit(xc.encode))
    except Exception as e:  # noqa: BLE001
        sel["detail"] = f"timing failed: {type(e).__name__}: {e}"[:300]
        return sel
    sel["choice"] = "pallas" if sel["pallas_gibs"] >= sel["xla_gibs"] else "xla"
    sel["detail"] = (
        f"measured @S={_PROBE_CHUNK}: pallas={sel['pallas_gibs']:.2f} "
        f"xla={sel['xla_gibs']:.2f} GiB/s -> {sel['choice']}"
    )
    return sel


def codec_selection() -> dict:
    """The cached per-backend RS-kernel probe+timing verdict."""
    backend = jax.default_backend()
    with _RS_SELECT_LOCK:
        if backend not in _RS_SELECT:
            _RS_SELECT[backend] = _probe_and_time_rs(backend)
        return _RS_SELECT[backend]


def rs_encode_mode() -> str:
    """Which RS encode kernel serves: "pallas" or "xla".

    MINIO_TPU_RS = xla | pallas | auto (default). Auto probes the
    XOR-bitmatrix kernel at production shape and serves with whichever
    measured faster -- cached per backend. XLA serves on CPU and whenever
    the probe or timing fails.
    """
    mode = os.environ.get("MINIO_TPU_RS", "auto").lower()
    if mode in ("xla", "pallas"):
        return mode
    return codec_selection()["choice"]


def kernel_status(k: int = 12, m: int = 4) -> dict:
    """Honest per-kernel status for bench/diagnostics: which kernel serves
    each stage, why, and what the XOR schedule costs. Never a silent 0.0 --
    a kernel that can't serve carries its cause in `detail`."""
    return {
        "backend": jax.default_backend(),
        "hash": dict(hash_selection()),
        "rs": dict(codec_selection()),
        "hash_mode": os.environ.get("MINIO_TPU_HASH", "auto").lower(),
        "rs_mode": rs_encode_mode(),
        "xor_schedule": bitmatrix.schedule_stats(k, m),
    }


def hash_batch_fn():
    """The device hash implementation the pipeline serves with.

    MINIO_TPU_HASH = xla | pallas | auto (default). Auto probes the Pallas
    VMEM-chain kernel at the production chunk size against the host oracle,
    times it against the XLA scan, and serves with whichever measured
    faster — cached per backend. The XLA scan serves on CPU (Pallas
    interpret mode is not a compiled candidate) and whenever the probe or
    timing fails.
    """
    mode = os.environ.get("MINIO_TPU_HASH", "auto").lower()
    if mode == "xla":
        return hhj.hash256_batch
    if mode == "pallas" or hash_selection()["choice"] == "pallas":
        from ..ops import highwayhash_pallas as hhp

        return hhp.hash256_batch
    return hhj.hash256_batch


@dataclass(frozen=True)
class Geometry:
    """Erasure geometry: K data + M parity shards over a block size."""

    data: int
    parity: int
    block_size: int = 1 << 20  # blockSizeV2, cmd/object-api-common.go:40

    @property
    def total(self) -> int:
        return self.data + self.parity

    @property
    def shard_size(self) -> int:
        return rs_matrix.shard_size(self.block_size, self.data)


class ErasurePipeline:
    """Batched encode/decode/heal steps for a fixed geometry.

    All steps take shard batches shaped [B, K(+M), S] u8 and are jitted once
    per (geometry, batch shape). `mesh` enables SPMD sharding over dp/tp/sp.
    """

    def __init__(self, geometry: Geometry, mesh=None):
        self.geom = geometry
        self.mesh = mesh
        self.codec = rs.RSCodec(geometry.data, geometry.parity)
        self.rs_impl = "xla"  # resolved for real in _build_encode
        self._encode_fn = self._build_encode()

    # -- encode ------------------------------------------------------------

    def _build_encode(self):
        geom = self.geom
        mesh = self.mesh
        # Resolved at build time so the probe+timing selection passes run
        # here, as plain device work — never inside a jit trace.
        hash_fn = hash_batch_fn()
        self.rs_impl = rs_encode_mode()
        dev_codec = (
            rs_pallas.RSPallasCodec(geom.data, geom.parity)
            if self.rs_impl == "pallas"
            else self.codec
        )
        # Parity-only step for the small-object coalescing path: those
        # batches are padded on the shard-byte axis, so their digests are
        # host-computed at true lengths and the device only owes parity.
        self._parity_fn = jax.jit(dev_codec.encode)

        if mesh is None:
            return jax.jit(fused_ops.make_step(dev_codec.encode_all, hash_fn))

        # Mesh path: explicit SPMD. The erasure matmul is pointwise in the
        # byte axis so it runs sp-sharded with no communication; the
        # encode->hash boundary is a REAL ICI all-to-all (lax.all_to_all
        # moves the sp byte shards into the stream axis) plus a tp slice of
        # the streams. Round 3 expressed this reshard as a
        # with_sharding_constraint, which GSPMD lowered as an involuntary
        # full rematerialization (replicate + slice); shard_map pins the
        # collective instead.
        tp, sp = mesh.shape["tp"], mesh.shape["sp"]
        if geom.total % (tp * sp):
            raise ValueError(
                f"shard streams ({geom.total}) must divide evenly over the "
                f"tp x sp grid ({tp}x{sp}); uneven stream sharding would "
                "silently drop digests"
            )
        w_parity = rs.parity_weights(geom.data, geom.parity)
        # hash_fn (resolved above, outside the shard_map trace) gives
        # multi-chip serving the same measured-fastest kernel as
        # single-device — round 4 hardcoded the XLA scan here, silently
        # dropping the Pallas kernel on the scaling path.

        def encode_local(data_local: jax.Array):
            # data_local: [B/dp, K, S/sp], replicated over tp. The RS kernel
            # choice rides into the shard_map body: the XOR-bitmatrix Pallas
            # kernel is pointwise in the byte axis exactly like the matmul,
            # so it runs sp-sharded with no extra communication.
            if self.rs_impl == "pallas":
                parity = dev_codec.encode(data_local)
            else:
                parity = rs.gf_matmul(data_local, jnp.asarray(w_parity))
            all_local = jnp.concatenate([data_local, parity], axis=1)
            # Barrier: without it XLA keeps the parameter-aliasing data rows
            # and the freshly computed parity rows in different layouts, and
            # the tiled all-to-all verifier rejects the mixed-layout chunks.
            all_local = jax.lax.optimization_barrier(all_local)
            # [B/dp, T, S/sp] -> all-to-all -> [B/dp, T/sp, S]: byte shards
            # ride ICI into full per-stream rows (sp-major stream order).
            x = jax.lax.all_to_all(all_local, "sp", split_axis=1, concat_axis=2, tiled=True)
            t_loc = x.shape[1] // tp
            ti = jax.lax.axis_index("tp")
            x = jax.lax.dynamic_slice_in_dim(x, ti * t_loc, t_loc, axis=1)
            digests = hash_fn(x.reshape(-1, x.shape[-1])).reshape(
                x.shape[0], t_loc, 32
            )
            return all_local, digests

        mapped = mesh_lib.shard_map_compat(
            encode_local,
            mesh=mesh,
            in_specs=mesh_lib.data_spec(),
            out_specs=(mesh_lib.shard_output_spec(), mesh_lib.digest_spec()),
        )
        return jax.jit(mapped)

    def encode(self, data_shards) -> tuple[jax.Array, jax.Array]:
        return self._encode_fn(data_shards)

    def encode_parity(self, data_shards) -> jax.Array:
        """[B, K, S] -> [B, M, S] parity only, no digests.

        The small-object coalescing path pads the shard-BYTE axis to a
        bucketed length; GF(2^8) math is per byte position, so the parity
        prefix at the true length is bit-exact, but digests of padded rows
        would be wrong -- the caller hashes host-side at true lengths.
        """
        return self._parity_fn(data_shards)

    # -- decode / heal -----------------------------------------------------

    @functools.lru_cache(maxsize=256)
    def _recon_weights(self, present: tuple[bool, ...], want: tuple[int, ...]):
        return np.asarray(
            rs_matrix.bit_expand(
                rs_matrix.reconstruct_rows(self.geom.data, self.geom.parity, present, want)
            ).astype(np.int8)
        )

    def reconstruct(
        self,
        survivors,
        present: tuple[bool, ...],
        want: tuple[int, ...],
        with_digests: bool = True,
    ):
        """[B, K, S] survivor shards (first K present rows, index order) ->
        [B, len(want), S] rebuilt shards + their digests (or None).

        Degraded GETs don't need digests of the rebuilt rows -- skipping the
        hash halves the device work on that path; heal keeps it fused.
        """
        # hash_fn resolved here (probe runs outside the trace) and passed as
        # a static arg: both candidates are stable module-level functions, so
        # the jit cache keys cleanly on the selection.
        hash_fn = hash_batch_fn() if with_digests else None
        if self.rs_impl == "pallas":
            # Reconstruct variant of the XOR-bitmatrix kernel: same kernel,
            # reconstruction coefficients compiled to their own cached
            # schedule (a static jit arg, like the hash selection).
            sched = bitmatrix.schedule_for_coeffs(
                rs_matrix.reconstruct_rows(self.geom.data, self.geom.parity, present, want)
            )
            return _reconstruct_sched_step(survivors, sched, hash_fn)
        w = jnp.asarray(self._recon_weights(present, want))
        return _reconstruct_step(survivors, w, hash_fn)

    def verify_digests(self, shards) -> jax.Array:
        """[B, T, S] shards -> [B, T, 32] digests (for bitrot deep-scan)."""
        b, t, s = shards.shape
        return hash_batch_fn()(shards.reshape(b * t, s)).reshape(b, t, 32)


@functools.partial(jax.jit, static_argnums=(2,))
def _reconstruct_step(survivors: jax.Array, w_bits: jax.Array, hash_fn):
    rebuilt = rs.gf_matmul(survivors, w_bits)
    if hash_fn is None:
        return rebuilt, None
    b, r, s = rebuilt.shape
    digests = hash_fn(rebuilt.reshape(b * r, s)).reshape(b, r, 32)
    return rebuilt, digests


@functools.partial(jax.jit, static_argnums=(1, 2))
def _reconstruct_sched_step(survivors: jax.Array, sched, hash_fn):
    rebuilt = rs_pallas._apply_sched(jnp.asarray(survivors), sched)
    if hash_fn is None:
        return rebuilt, None
    b, r, s = rebuilt.shape
    digests = hash_fn(rebuilt.reshape(b * r, s)).reshape(b, r, 32)
    return rebuilt, digests
