"""The flagship device program: fused erasure-encode + bitrot-hash pipeline.

One jitted step turns a batch of 1 MiB-block data shards into parity shards
plus per-shard HighwayHash-256 bitrot digests -- the device-side fusion of the
reference's per-request hot loop (cmd/erasure-encode.go:73-109 feeding
cmd/bitrot-streaming.go:43-65), batched across concurrent uploads so the
host<->device transfer and kernel launches amortize (the BASELINE.json north
star). The decode/heal steps reuse the same GF(2) matmul with reconstruction
weights (cmd/erasure-decode.go:206, erasure-lowlevel-heal.go:31 equivalents).

With a mesh, the steps are pjit-sharded: encode runs with bytes sp-sharded
(pointwise in the byte axis), then the encode->hash boundary reshards streams
across (tp, sp) -- an all-to-all over ICI, the storage analogue of sequence
parallelism. See parallel/mesh.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

import os

from ..ops import highwayhash_jax as hhj
from ..ops import rs, rs_matrix
from ..parallel import mesh as mesh_lib


_PALLAS_HASH_OK: bool | None = None


def _pallas_hash_works() -> bool:
    """One-time probe: the Pallas hash kernel must actually lower on this
    backend AND match the host oracle before the serving path may select
    it (Mosaic op support varies by release; a kernel that fails to lower
    must degrade to the XLA scan, not crash every PutObject)."""
    global _PALLAS_HASH_OK
    if _PALLAS_HASH_OK is None:
        try:
            from ..ops import highwayhash as hh_host
            from ..ops import highwayhash_pallas as hhp

            probe = np.arange(2 * 256, dtype=np.uint8).reshape(2, 256)  # 8 packets: kernel path
            got = np.asarray(hhp.hash256_batch(probe))
            want = hh_host.hash256_batch(probe)
            _PALLAS_HASH_OK = np.array_equal(got, want)
        except Exception:  # noqa: BLE001 - any lowering/runtime failure
            _PALLAS_HASH_OK = False
    return _PALLAS_HASH_OK


def hash_batch_fn():
    """The device hash implementation the pipeline serves with.

    MINIO_TPU_HASH = xla | pallas | auto (default). Auto picks the Pallas
    VMEM-chain kernel on real TPU (the scan version pays a while-loop
    dispatch per packet chunk) — but only after a live probe confirms it
    lowers and matches the oracle; the XLA scan serves elsewhere (Pallas
    interpret mode on CPU is far slower than compiled XLA).
    """
    mode = os.environ.get("MINIO_TPU_HASH", "auto").lower()
    if mode == "xla":
        return hhj.hash256_batch
    if mode == "pallas" or (
        jax.default_backend() in ("tpu", "axon") and _pallas_hash_works()
    ):
        from ..ops import highwayhash_pallas as hhp

        return hhp.hash256_batch
    return hhj.hash256_batch


@dataclass(frozen=True)
class Geometry:
    """Erasure geometry: K data + M parity shards over a block size."""

    data: int
    parity: int
    block_size: int = 1 << 20  # blockSizeV2, cmd/object-api-common.go:40

    @property
    def total(self) -> int:
        return self.data + self.parity

    @property
    def shard_size(self) -> int:
        return rs_matrix.shard_size(self.block_size, self.data)


class ErasurePipeline:
    """Batched encode/decode/heal steps for a fixed geometry.

    All steps take shard batches shaped [B, K(+M), S] u8 and are jitted once
    per (geometry, batch shape). `mesh` enables SPMD sharding over dp/tp/sp.
    """

    def __init__(self, geometry: Geometry, mesh=None):
        self.geom = geometry
        self.mesh = mesh
        self.codec = rs.RSCodec(geometry.data, geometry.parity)
        self._encode_fn = self._build_encode()

    # -- encode ------------------------------------------------------------

    def _build_encode(self):
        geom = self.geom
        mesh = self.mesh

        def encode_step(data_shards: jax.Array):
            """[B, K, S] -> ([B, K+M, S] shards, [B, K+M, 32] digests)."""
            all_shards = self.codec.encode_all(data_shards)
            b, t, s = all_shards.shape
            digests = hash_batch_fn()(all_shards.reshape(b * t, s)).reshape(b, t, 32)
            return all_shards, digests

        if mesh is None:
            return jax.jit(encode_step)

        # Mesh path: explicit SPMD. The erasure matmul is pointwise in the
        # byte axis so it runs sp-sharded with no communication; the
        # encode->hash boundary is a REAL ICI all-to-all (lax.all_to_all
        # moves the sp byte shards into the stream axis) plus a tp slice of
        # the streams. Round 3 expressed this reshard as a
        # with_sharding_constraint, which GSPMD lowered as an involuntary
        # full rematerialization (replicate + slice); shard_map pins the
        # collective instead.
        tp, sp = mesh.shape["tp"], mesh.shape["sp"]
        if geom.total % (tp * sp):
            raise ValueError(
                f"shard streams ({geom.total}) must divide evenly over the "
                f"tp x sp grid ({tp}x{sp}); uneven stream sharding would "
                "silently drop digests"
            )
        w_parity = rs.parity_weights(geom.data, geom.parity)

        def encode_local(data_local: jax.Array):
            # data_local: [B/dp, K, S/sp], replicated over tp.
            parity = rs.gf_matmul(data_local, jnp.asarray(w_parity))
            all_local = jnp.concatenate([data_local, parity], axis=1)
            # Barrier: without it XLA keeps the parameter-aliasing data rows
            # and the freshly computed parity rows in different layouts, and
            # the tiled all-to-all verifier rejects the mixed-layout chunks.
            all_local = jax.lax.optimization_barrier(all_local)
            # [B/dp, T, S/sp] -> all-to-all -> [B/dp, T/sp, S]: byte shards
            # ride ICI into full per-stream rows (sp-major stream order).
            x = jax.lax.all_to_all(all_local, "sp", split_axis=1, concat_axis=2, tiled=True)
            t_loc = x.shape[1] // tp
            ti = jax.lax.axis_index("tp")
            x = jax.lax.dynamic_slice_in_dim(x, ti * t_loc, t_loc, axis=1)
            digests = hhj.hash256_batch(x.reshape(-1, x.shape[-1])).reshape(
                x.shape[0], t_loc, 32
            )
            return all_local, digests

        mapped = jax.shard_map(
            encode_local,
            mesh=mesh,
            in_specs=mesh_lib.data_spec(),
            out_specs=(mesh_lib.shard_output_spec(), mesh_lib.digest_spec()),
            check_vma=False,
        )
        return jax.jit(mapped)

    def encode(self, data_shards) -> tuple[jax.Array, jax.Array]:
        return self._encode_fn(data_shards)

    # -- decode / heal -----------------------------------------------------

    @functools.lru_cache(maxsize=256)
    def _recon_weights(self, present: tuple[bool, ...], want: tuple[int, ...]):
        return np.asarray(
            rs_matrix.bit_expand(
                rs_matrix.reconstruct_rows(self.geom.data, self.geom.parity, present, want)
            ).astype(np.int8)
        )

    def reconstruct(
        self,
        survivors,
        present: tuple[bool, ...],
        want: tuple[int, ...],
        with_digests: bool = True,
    ):
        """[B, K, S] survivor shards (first K present rows, index order) ->
        [B, len(want), S] rebuilt shards + their digests (or None).

        Degraded GETs don't need digests of the rebuilt rows -- skipping the
        hash halves the device work on that path; heal keeps it fused.
        """
        w = jnp.asarray(self._recon_weights(present, want))
        return _reconstruct_step(survivors, w, with_digests)

    def verify_digests(self, shards) -> jax.Array:
        """[B, T, S] shards -> [B, T, 32] digests (for bitrot deep-scan)."""
        b, t, s = shards.shape
        return hash_batch_fn()(shards.reshape(b * t, s)).reshape(b, t, 32)


@functools.partial(jax.jit, static_argnums=(2,))
def _reconstruct_step(survivors: jax.Array, w_bits: jax.Array, with_digests: bool):
    rebuilt = rs.gf_matmul(survivors, w_bits)
    if not with_digests:
        return rebuilt, None
    b, r, s = rebuilt.shape
    digests = hash_batch_fn()(rebuilt.reshape(b * r, s)).reshape(b, r, 32)
    return rebuilt, digests
