"""Network fault hook: one seam under every internode RPC.

All inter-node traffic -- storage-REST (RemoteDrive), peer fanout
(PeerClient / NotificationSys), and RemoteLocker lock calls -- rides
dist/transport.py's RestClient.call, so a single check there covers the
whole control and data plane. transport.py guards the call with
`REGISTRY.net is None` (the zero-overhead check) and only then enters
before_rpc.

Kinds:
  partition  -- raise DiskNotFound before the request leaves the process
                (a blackholed peer as the caller experiences it: the typed
                error the requests-failure path would produce, minus the
                connect timeout). probability < 1 models a lossy link.
  slow-rpc   -- sleep delay_ms, then let the call proceed; combine with a
                probability for jittery/lossy links.
  lock-death -- partition semantics, but matched only against lock REST
                endpoints, so a node's LOCAL locker API dies while its
                storage and peer planes stay up (the lock-server-crash
                scenario dsync is designed around).
"""

from __future__ import annotations

import time

from ..utils import errors
from .faults import REGISTRY, SLOW_RPC


def before_rpc(base_url: str, path: str = "", registry=None) -> None:
    """Consult armed net faults for one outbound RPC; called by
    RestClient.call only when the net snapshot is armed."""
    reg = registry if registry is not None else REGISTRY
    spec = reg.match_net(base_url, path)
    if spec is None:
        return
    if spec.kind == SLOW_RPC:
        if spec.delay_ms > 0:
            time.sleep(spec.delay_ms / 1e3)
        return
    raise errors.DiskNotFound(f"chaos: {spec.kind} injected for {base_url}{path}")
