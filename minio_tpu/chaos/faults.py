"""Fault registry: seeded, scoped, budgeted fault schedules.

A fault is a FaultSpec armed in a FaultRegistry. Every spec carries:

  * kind        -- what breaks (see DISK_KINDS / NET_KINDS below);
  * target      -- substring match against the drive endpoint (disk kinds)
                   or the peer base-url + request path (net kinds);
                   "" matches everything;
  * path        -- "bucket/prefix" filter for disk faults ("" = any);
  * ops         -- restrict to specific StorageAPI methods / RPC paths;
  * probability -- per-matching-call fire chance, drawn from the fault's
                   OWN random.Random(seed) so a fixed seed replays the
                   exact schedule;
  * count       -- injection budget (-1 = unlimited); exhausted faults
                   drop out of the hot-path snapshot;
  * delay_ms    -- sleep for latency / slow-rpc / hang kinds.

Determinism: each armed fault owns a private RNG seeded from its spec, and
every probability draw is serialized under the registry lock, so the i-th
matching call always sees the i-th draw. With a fixed seed and the same
call sequence the fired/skipped pattern is identical run to run.

Hot path: the registry keeps `disk` and `net` attributes that are either a
tuple of armed faults or None. Wrappers check `REGISTRY.disk is None` /
`REGISTRY.net is None` and fall straight through -- no allocation, no lock.
"""

from __future__ import annotations

import random
import threading
import uuid
from dataclasses import dataclass, field

from ..control import tracing
from ..control.sanitizer import san_lock, san_rlock

DRIVE_ERROR = "drive-error"
DRIVE_HANG = "drive-hang"
DRIVE_LATENCY = "drive-latency"
BITROT = "bitrot"
PARTITION = "partition"
SLOW_RPC = "slow-rpc"
LOCK_DEATH = "lock-death"

DISK_KINDS = frozenset({DRIVE_ERROR, DRIVE_HANG, DRIVE_LATENCY, BITROT})
NET_KINDS = frozenset({PARTITION, SLOW_RPC, LOCK_DEATH})
KINDS = DISK_KINDS | NET_KINDS

# lock-death only blackholes lock REST traffic; matched against the client
# base-url (dist/locks.py LOCK_PREFIX; literal here to keep this module
# import-free of dist/*, which imports us via transport).
_LOCK_PATH_MARKER = "/mtpu/lock/"

# Kinds that default to a restricted op set when spec.ops is empty: bitrot
# flips bytes on the SHARD WRITE path (post-checksum -- the frame digests
# were computed before the wrapper sees the bytes), so the corruption is
# at-rest and every later read fails HighwayHash verify until heal rewrites
# the shard. Arm with explicit ops=("read_file",) for read-side flips.
_DEFAULT_OPS = {BITROT: ("create_file", "append_file", "append_iov")}


@dataclass
class FaultSpec:
    kind: str
    target: str = ""
    path: str = ""
    ops: tuple = ()
    probability: float = 1.0
    count: int = -1
    delay_ms: float = 0.0
    seed: int = 0
    fault_id: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (want one of {sorted(KINDS)})")
        if not (0.0 < self.probability <= 1.0):
            raise ValueError("probability must be in (0, 1]")
        self.ops = tuple(self.ops or _DEFAULT_OPS.get(self.kind, ()))

    @staticmethod
    def from_dict(doc: dict) -> "FaultSpec":
        if not isinstance(doc, dict) or "kind" not in doc:
            raise ValueError("fault spec must be an object with a 'kind'")
        return FaultSpec(
            kind=str(doc["kind"]),
            target=str(doc.get("target", "")),
            path=str(doc.get("path", "")),
            ops=tuple(doc.get("ops", ()) or ()),
            probability=float(doc.get("probability", 1.0)),
            count=int(doc.get("count", -1)),
            delay_ms=float(doc.get("delay_ms", 0.0)),
            seed=int(doc.get("seed", 0)),
            fault_id=str(doc.get("fault_id", "")),
        )

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "target": self.target,
            "path": self.path,
            "ops": list(self.ops),
            "probability": self.probability,
            "count": self.count,
            "delay_ms": self.delay_ms,
            "seed": self.seed,
            "fault_id": self.fault_id,
        }


class _Armed:
    __slots__ = ("spec", "rng", "remaining", "injected")

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.remaining = spec.count
        self.injected = 0


class FaultRegistry:
    def __init__(self):
        self._lock = san_lock("FaultRegistry._lock")
        self._armed: dict[str, _Armed] = {}
        self._injected: dict[tuple[str, str], int] = {}
        # Hot-path snapshots: tuple of live _Armed, or None when nothing of
        # that class is armed. Read without the lock (atomic attribute load).
        self.disk: tuple | None = None
        self.net: tuple | None = None

    # -- arm / disarm --------------------------------------------------------

    def arm(self, spec: FaultSpec) -> str:
        fid = spec.fault_id or uuid.uuid4().hex[:12]
        spec.fault_id = fid
        with self._lock:
            self._armed[fid] = _Armed(spec)
            self._refresh()
        return fid

    def disarm(self, fault_id: str) -> bool:
        with self._lock:
            found = self._armed.pop(fault_id, None) is not None
            self._refresh()
        return found

    def disarm_all(self) -> int:
        with self._lock:
            n = len(self._armed)
            self._armed.clear()
            self._refresh()
        return n

    def list(self) -> list[dict]:
        with self._lock:
            return [
                {**a.spec.to_dict(), "remaining": a.remaining, "injected": a.injected}
                for a in self._armed.values()
            ]

    def injected_counts(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self._injected)

    def _refresh(self) -> None:
        """Rebuild hot-path snapshots (caller holds the lock). Exhausted
        budgets drop out so the wrappers return to pure pass-through."""
        live = [a for a in self._armed.values() if a.remaining != 0]
        disk = tuple(a for a in live if a.spec.kind in DISK_KINDS)
        net = tuple(a for a in live if a.spec.kind in NET_KINDS)
        self.disk = disk or None
        self.net = net or None

    # -- decisions -----------------------------------------------------------

    def _decide(self, a: _Armed, target_key: str) -> bool:
        """Roll the fault's schedule for one matching call; on fire, burn
        budget, bump counters, and tag the active trace span."""
        with self._lock:
            if a.remaining == 0:
                return False
            if a.spec.probability < 1.0 and a.rng.random() >= a.spec.probability:
                return False
            if a.remaining > 0:
                a.remaining -= 1
                if a.remaining == 0:
                    self._refresh()
            a.injected += 1
            key = (a.spec.kind, a.spec.target or "*")
            self._injected[key] = self._injected.get(key, 0) + 1
        cur = tracing.current()
        if cur is not None:
            set_fn = getattr(cur, "set", None)  # _RemoteParent has no tags
            if set_fn is not None:
                set_fn(chaos_kind=a.spec.kind, chaos_target=target_key)
        return True

    def match_disk(self, endpoint: str, op: str, volume: str = "", path: str = ""):
        """First armed disk fault firing for this StorageAPI call, or None."""
        snap = self.disk
        if snap is None:
            return None
        where = f"{volume}/{path}" if path else volume
        for a in snap:
            spec = a.spec
            if spec.target and spec.target not in endpoint:
                continue
            if spec.ops and op not in spec.ops:
                continue
            if spec.path and not where.startswith(spec.path):
                continue
            if self._decide(a, f"{endpoint}:{op}"):
                return spec
        return None

    def match_net(self, url: str, path: str = ""):
        """First armed net fault firing for this RPC, or None."""
        snap = self.net
        if snap is None:
            return None
        full = url + path
        for a in snap:
            spec = a.spec
            if spec.kind == LOCK_DEATH and _LOCK_PATH_MARKER not in url:
                continue
            if spec.target and spec.target not in full:
                continue
            if spec.ops and path not in spec.ops:
                continue
            if self._decide(a, full):
                return spec
        return None


# Process-global registry (the GLOBAL_TRACE / GLOBAL_METRICS pattern): the
# admin chaos API arms it on every node via peer fanout; wrappers and the
# RestClient hook consult it. Tests that want isolation construct their own.
REGISTRY = FaultRegistry()
