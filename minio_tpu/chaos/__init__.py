"""Deterministic fault-injection plane.

The robustness analogue of the observability layer: seeded, scoped fault
schedules that wrap the existing StorageAPI / REST seams without forking
them, so the degraded-mode machinery (quorum writes, MRF re-drive, heal
sequences, dsync refresh loss) can be exercised on demand and failures
reproduce exactly under a fixed seed.

Layout:
  faults.py -- FaultSpec + FaultRegistry (the decision engine + budgets)
  disk.py   -- FaultyDisk, a StorageAPI decorator layered under MeteredDrive
  net.py    -- the RestClient hook (storage-REST, peer fanout, RemoteLocker)
  crash.py  -- CrashSpec + CrashRegistry: named process-death points on the
               commit path (kind "crash" on the same admin API)

Everything is disarmed by default; the only cost on the hot path is one
attribute-is-None check per call.
"""

from .crash import CRASH_KIND, KNOWN_POINTS, CrashRegistry, CrashSpec
from .crash import REGISTRY as CRASH_REGISTRY
from .faults import REGISTRY, FaultRegistry, FaultSpec

__all__ = [
    "REGISTRY",
    "FaultRegistry",
    "FaultSpec",
    "CRASH_KIND",
    "CRASH_REGISTRY",
    "CrashRegistry",
    "CrashSpec",
    "KNOWN_POINTS",
]
