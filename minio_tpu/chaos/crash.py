"""Deterministic crash-point registry: named process-death points on the
commit path.

PR 2's fault plane injects drive *errors*; this module injects *death*. A
crash point is a named call site at a stage boundary of the PUT / multipart /
commit path (`crash_point("put.mid-commit", ...)`). Disarmed, the call is one
attribute-is-None check. Armed (through the same admin /chaos API as
FaultSpec, with ``kind: "crash"``), the registry kills the process at the
point -- ``os._exit``, no cleanup, no atexit, exactly what a worker crash or
``kill -9`` leaves behind -- so the recovery scan (storage/recovery.py) and
the crashcheck harness (tools/crashcheck.py) can prove the durability
invariants against every boundary, not just the ones a stress test happens
to hit.

Determinism mirrors FaultSpec: a spec fires on the (skip+1)-th matching hit,
and the torn-write point draws its cut offset from a private
``random.Random(seed)``, so a fixed (point, skip, seed) replays the same
crash schedule run after run.

Modes:
  * ``kill``       -- die at the point (default; exit code 137 = SIGKILL'd).
  * ``raise``      -- raise errors.CrashInjected instead of dying: the
                      in-process stand-in for worker death used by loadgen
                      scenarios and unit tests that must survive the "crash".
  * ``torn-kill``  -- (torn-capable points) truncate the write at a seeded
                      offset inside the last iovec, then die: the mid-writev
                      kill that leaves a short shard frame at rest.
  * ``torn``       -- truncate the same way but keep running: silent at-rest
                      corruption for the bitrot-detect -> heal tests.
"""

from __future__ import annotations

import os
import random
import uuid
from dataclasses import dataclass

from ..control.sanitizer import san_lock
from ..utils import errors

CRASH_KIND = "crash"  # FaultSpec-style kind the admin /chaos API routes here

KILL = "kill"
RAISE = "raise"
TORN_KILL = "torn-kill"
TORN = "torn"
MODES = frozenset({KILL, RAISE, TORN_KILL, TORN})

# Every registered crash point, one per stage boundary of the data path.
# tools/crashcheck.py enumerates this tuple; a new boundary is a two-line
# diff (the crash_point() call and its entry here), same contract as the
# perf-ledger STAGES registry.
KNOWN_POINTS: tuple = (
    # single-PUT streaming path (object/erasure.py _put_streaming)
    "put.after-stage",         # group appended (post-append_iov), pre-sync/drain
    "put.before-commit",       # shards staged + drained, xl.meta not written
    "put.mid-commit",          # inside the commit fan-out (skip = drives done)
    "put.after-commit",        # quorum committed, response not yet written
    # multipart path (object/multipart.py)
    "multipart.part.staged",   # part shards staged, publish rename pending
    "multipart.part.published",  # part renamed, part.meta not yet written
    "multipart.complete.mid-rename",  # some parts moved to the commit dir
    "multipart.complete.partial",     # complete fan-out, subset of drives done
    # storage commit internals (storage/local.py)
    "storage.rename-data.pre-meta",   # data dir renamed, xl.meta not written
    "storage.xlmeta.pre-replace",     # new xl.meta staged, os.replace pending
    "storage.append-iov.torn",        # mid-writev torn write (torn modes)
)

TORN_POINTS = frozenset({"storage.append-iov.torn"})


@dataclass
class CrashSpec:
    """One armed crash schedule. `skip` passes that many matching hits
    before firing; `target` substring-matches the drive endpoint (torn /
    storage points) -- "" matches everything."""

    point: str
    mode: str = KILL
    target: str = ""
    skip: int = 0
    seed: int = 0
    exit_code: int = 137
    fault_id: str = ""

    def __post_init__(self):
        if self.point not in KNOWN_POINTS:
            raise ValueError(
                f"unknown crash point {self.point!r} (want one of {list(KNOWN_POINTS)})"
            )
        if self.mode not in MODES:
            raise ValueError(f"unknown crash mode {self.mode!r} (want one of {sorted(MODES)})")
        if self.mode in (TORN, TORN_KILL) and self.point not in TORN_POINTS:
            raise ValueError(f"point {self.point!r} is not torn-capable")
        if self.skip < 0:
            raise ValueError("skip must be >= 0")

    @staticmethod
    def from_dict(doc: dict) -> "CrashSpec":
        if not isinstance(doc, dict) or "point" not in doc:
            raise ValueError("crash spec must be an object with a 'point'")
        return CrashSpec(
            point=str(doc["point"]),
            mode=str(doc.get("mode", KILL)),
            target=str(doc.get("target", "")),
            skip=int(doc.get("skip", 0)),
            seed=int(doc.get("seed", 0)),
            exit_code=int(doc.get("exit_code", 137)),
            fault_id=str(doc.get("fault_id", "")),
        )

    def to_dict(self) -> dict:
        return {
            "kind": CRASH_KIND,
            "point": self.point,
            "mode": self.mode,
            "target": self.target,
            "skip": self.skip,
            "seed": self.seed,
            "exit_code": self.exit_code,
            "fault_id": self.fault_id,
        }


class _ArmedCrash:
    __slots__ = ("spec", "rng", "skipped", "fired")

    def __init__(self, spec: CrashSpec):
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.skipped = 0
        self.fired = 0


class CrashRegistry:
    """Same hot-path shape as FaultRegistry: `points` is a tuple of armed
    crashes or None, read without the lock; every skip/fire decision is
    serialized under the lock so the i-th matching hit is the i-th draw."""

    def __init__(self):
        self._lock = san_lock("CrashRegistry._lock")
        self._armed: dict[str, _ArmedCrash] = {}
        self._fired: dict[str, int] = {}
        self.points: tuple | None = None

    def arm(self, spec: CrashSpec) -> str:
        fid = spec.fault_id or uuid.uuid4().hex[:12]
        spec.fault_id = fid
        with self._lock:
            self._armed[fid] = _ArmedCrash(spec)
            self._refresh()
        return fid

    def disarm(self, fault_id: str) -> bool:
        with self._lock:
            found = self._armed.pop(fault_id, None) is not None
            self._refresh()
        return found

    def disarm_all(self) -> int:
        with self._lock:
            n = len(self._armed)
            self._armed.clear()
            self._refresh()
        return n

    def list(self) -> list[dict]:
        with self._lock:
            return [
                {**a.spec.to_dict(), "skipped": a.skipped, "fired": a.fired}
                for a in self._armed.values()
            ]

    def fired_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._fired)

    def _refresh(self) -> None:
        self.points = tuple(self._armed.values()) or None

    # -- decisions -----------------------------------------------------------

    def _match(self, point: str, target: str, torn: bool):
        """First armed spec firing at this hit, decided under the lock.
        A fired kill/raise spec stays armed (the process is dead / the
        request aborted); torn specs keep firing for repeatability."""
        snap = self.points
        if snap is None:
            return None
        with self._lock:
            for a in snap:
                spec = a.spec
                if spec.point != point:
                    continue
                if torn != (spec.mode in (TORN, TORN_KILL)):
                    continue
                if spec.target and spec.target not in target:
                    continue
                if a.skipped < spec.skip:
                    a.skipped += 1
                    continue
                a.fired += 1
                self._fired[point] = self._fired.get(point, 0) + 1
                return a
        return None

    def hit(self, point: str, target: str = "") -> None:
        """Fire-or-pass for a plain (non-torn) crash point."""
        a = self._match(point, target, torn=False)
        if a is None:
            return
        if a.spec.mode == RAISE:
            raise errors.CrashInjected(point)
        die(a.spec.exit_code)

    def torn_hint(self, point: str, target: str, last_len: int):
        """(cut_offset_in_last_iov, kill_after) when a torn spec fires for
        this write, else None. The offset is the spec's seeded draw -- the
        i-th firing write is always cut at the i-th draw."""
        if last_len <= 0:
            return None
        a = self._match(point, target, torn=True)
        if a is None:
            return None
        return a.rng.randrange(last_len), a.spec.mode == TORN_KILL


def die(exit_code: int = 137) -> None:
    """Die like a crash: no stack unwind, no atexit, no flush of anything
    Python still holds. Bytes already handed to the kernel survive in page
    cache -- exactly the state a SIGKILL'd worker leaves on disk."""
    os._exit(exit_code)


# Process-global registry, armed by the admin /chaos API (kind "crash"),
# tools/crashcheck.py child drivers, or MTPU_CRASH at boot.
REGISTRY = CrashRegistry()


def crash_point(point: str, target: str = "") -> None:
    """The instrumentation call sites use. Disarmed cost: one attribute
    load and a None check."""
    if REGISTRY.points is None:
        return
    REGISTRY.hit(point, target)


def torn_hint(point: str, target: str, last_len: int):
    """Torn-write decision for append_iov; None when disarmed."""
    if REGISTRY.points is None:
        return None
    return REGISTRY.torn_hint(point, target, last_len)


def arm_from_env(env: dict | None = None) -> list[str]:
    """Arm crash specs from ``MTPU_CRASH=point[:mode[:skip[:seed]]][,...]``.

    The env seam exists for processes the admin API can't reach in time:
    pre-fork workers arm at boot (every worker sees the same schedule), and
    crashcheck victim children arm before the workload starts. Malformed
    entries raise -- a crash schedule that silently half-arms would make a
    'passing' crashcheck run meaningless."""
    env = os.environ if env is None else env
    raw = str(env.get("MTPU_CRASH", "") or "").strip()
    if not raw:
        return []
    fids = []
    for entry in raw.split(","):
        parts = entry.strip().split(":")
        if not parts or not parts[0]:
            continue
        spec = CrashSpec(
            point=parts[0],
            mode=parts[1] if len(parts) > 1 and parts[1] else KILL,
            skip=int(parts[2]) if len(parts) > 2 and parts[2] else 0,
            seed=int(parts[3]) if len(parts) > 3 and parts[3] else 0,
        )
        fids.append(REGISTRY.arm(spec))
    return fids
