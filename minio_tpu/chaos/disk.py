"""FaultyDisk: a StorageAPI decorator that injects registry faults.

Layered UNDER storage/metered.py's MeteredDrive -- MeteredDrive(FaultyDisk(
LocalDrive)) -- so injected failures are timed and counted like real ones
(a chaos drive-error shows up in the per-drive error EWMAs exactly as a
kernel EIO would).

Disarmed fast path: `__getattr__` checks the registry's `disk` snapshot;
when it is None the INNER bound method is returned unchanged -- no wrapper
frame, no allocation, identical object to `inner.method`.

Fault semantics:
  drive-error   -- raise errors.FaultyDisk (a DiskError: quorum-countable);
  drive-hang    -- sleep delay_ms (default 100 ms -- a bounded stand-in for
                   a wedged spindle whose caller timed out), then raise
                   errors.FaultyDisk;
  drive-latency -- sleep delay_ms, then run the real call;
  bitrot        -- flip one byte of the shard payload post-checksum: on the
                   default write ops the corruption lands at rest, so every
                   later read fails HighwayHash verify until heal rewrites
                   the shard; with ops=("read_file","read_all") the returned
                   bytes are flipped instead.
"""

from __future__ import annotations

import time

from ..storage.metered import _METERED
from ..utils import errors
from . import faults as faults_mod

# Same seam as the metered set: every StorageAPI method that hits the disk.
_FAULTABLE = _METERED

_BITROT_WRITE_OPS = frozenset({"create_file", "append_file", "append_iov", "write_all"})
_BITROT_READ_OPS = frozenset({"read_file", "read_all"})

_DEFAULT_HANG_MS = 100.0


def flip_byte(buf: bytes) -> bytes:
    """One deterministic mid-buffer bit-complemented byte -- enough to fail
    any digest over the buffer, cheap enough for multi-MiB shards."""
    if not buf:
        return buf
    i = len(buf) // 2
    return b"%s%s%s" % (buf[:i], bytes([buf[i] ^ 0xFF]), buf[i + 1 :])


class FaultyDisk:
    """Transparent StorageAPI decorator consulting a FaultRegistry."""

    def __init__(self, inner, registry: faults_mod.FaultRegistry | None = None):
        # __dict__ assignment avoids recursing through __setattr__/__getattr__
        # (the MeteredDrive decorator idiom).
        self.__dict__["inner"] = inner
        self.__dict__["registry"] = registry if registry is not None else faults_mod.REGISTRY

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if self.registry.disk is None or name not in _FAULTABLE or not callable(attr):
            return attr

        def faulted(*args, **kwargs):
            spec = self._consult(name, args)
            if spec is None:
                return attr(*args, **kwargs)
            return self._inject(spec, name, attr, args, kwargs)

        return faulted

    def __setattr__(self, name, value):
        if name in self.__dict__:
            self.__dict__[name] = value
        else:
            setattr(self.inner, name, value)

    # walk_dir stays a REAL generator function so MeteredDrive's
    # isgeneratorfunction check keeps timing the full iteration when it
    # wraps a FaultyDisk instead of a bare LocalDrive.
    def walk_dir(self, volume: str, base: str = "", recursive: bool = True):
        if self.registry.disk is not None:
            spec = self._consult("walk_dir", (volume, base))
            if spec is not None:
                # Generators can't rewrite payloads; error/hang/latency only.
                out = self._inject(spec, "walk_dir", None, (volume, base), {})
                if out is not None:
                    yield from out
                    return
        yield from self.inner.walk_dir(volume, base, recursive)

    # -- internals -----------------------------------------------------------

    def _consult(self, op: str, args: tuple):
        volume = args[0] if args and isinstance(args[0], str) else ""
        path = args[1] if len(args) > 1 and isinstance(args[1], str) else ""
        return self.registry.match_disk(self.inner.endpoint(), op, volume, path)

    def _inject(self, spec, op: str, attr, args: tuple, kwargs: dict):
        kind = spec.kind
        ep = self.inner.endpoint()
        if kind == faults_mod.DRIVE_LATENCY:
            if spec.delay_ms > 0:
                time.sleep(spec.delay_ms / 1e3)
        elif kind == faults_mod.DRIVE_HANG:
            time.sleep((spec.delay_ms or _DEFAULT_HANG_MS) / 1e3)
            raise errors.FaultyDisk(f"chaos: drive hang on {ep}.{op}")
        elif kind == faults_mod.DRIVE_ERROR:
            raise errors.FaultyDisk(f"chaos: injected I/O error on {ep}.{op}")
        elif kind == faults_mod.BITROT:
            if op == "append_iov" and len(args) > 2 and isinstance(args[2], list):
                # Gathered write: corrupt the joined payload, keep the shape.
                args = (args[0], args[1], [flip_byte(b"".join(bytes(v) for v in args[2]))])
            elif op in _BITROT_WRITE_OPS and len(args) > 2 and isinstance(
                args[2], (bytes, bytearray, memoryview)
            ):
                args = (args[0], args[1], flip_byte(bytes(args[2]))) + args[3:]
            elif op in _BITROT_READ_OPS:
                return flip_byte(bytes(attr(*args, **kwargs)))
            elif op == "read_file_into":
                # In-place read: run the real call, then flip a byte inside
                # the caller's pooled window so verify fails downstream.
                n = attr(*args, **kwargs)
                buf = kwargs.get("buf") if len(args) < 4 else args[3]
                if n and buf is not None:
                    i = int(n) // 2
                    buf[i] ^= 0xFF
                return n
        if attr is None:  # walk_dir latency path
            return None
        return attr(*args, **kwargs)
