"""Deterministic workload generators: zipfian hot-sets, size distributions,
and full op sequences.

Replay identity is the contract: the same scenario + seed must produce the
byte-identical op sequence on every machine and every run, so a report
diff across PRs compares the system, not the dice. Everything here draws
from one `random.Random(seed)` in one fixed order; op generation is
pre-run (a list), never interleaved with execution timing.

The zipfian generator is the YCSB construction (Gray et al.'s bounded
zipfian via the zeta closed form): rank popularity follows 1/rank^theta,
and a seeded permutation scrambles ranks onto key ids so "hot" keys are
spread across the namespace instead of clustering at key_0..key_k (which
would alias with any prefix-sharded placement).
"""

from __future__ import annotations

import hashlib
import math
import random
from typing import NamedTuple

from .spec import Phase, Scenario


class ZipfianGenerator:
    """Bounded zipfian over ranks [0, n) with parameter theta in [0, 1).

    theta=0 degenerates to uniform; theta->1 concentrates mass on the head
    (YCSB default 0.99 gives ~10% of keys ~60% of traffic at n=256).
    """

    def __init__(self, n: int, theta: float, rng: random.Random,
                 perm_rng: random.Random | None = None):
        if n <= 0:
            raise ValueError("zipfian needs n > 0")
        if not (0.0 <= theta < 1.0):
            raise ValueError("theta must be in [0, 1)")
        self.n = n
        self.theta = theta
        self._rng = rng
        if theta > 0.0:
            self._zetan = sum(1.0 / (i + 1) ** theta for i in range(n))
            self._alpha = 1.0 / (1.0 - theta)
            zeta2 = sum(1.0 / (i + 1) ** theta for i in range(min(2, n)))
            self._eta = (1.0 - (2.0 / n) ** (1.0 - theta)) / (1.0 - zeta2 / self._zetan)
        # Scramble ranks -> key ids so the hot set is namespace-spread.
        # perm_rng (when given) decouples WHICH keys are hot from the draw
        # stream: phases seeded differently still agree on the hot set, so
        # a warmed cache phase actually re-reads the keys that warmed it.
        self._perm = list(range(n))
        (perm_rng or rng).shuffle(self._perm)

    def next_rank(self) -> int:
        """Next popularity rank (0 = hottest)."""
        if self.theta <= 0.0:
            return self._rng.randrange(self.n)
        u = self._rng.random()
        uz = u * self._zetan
        if uz < 1.0:
            return 0
        if uz < 1.0 + 0.5 ** self.theta:
            return 1
        return int(self.n * (self._eta * u - self._eta + 1.0) ** self._alpha)

    def next_key(self) -> int:
        """Next key id in [0, n) (rank scrambled through the permutation)."""
        rank = self.next_rank()
        if rank >= self.n:  # closed-form rounding can land exactly on n
            rank = self.n - 1
        return self._perm[rank]


class SizeDistribution:
    """Object-size sampler built from a validated `sizes` spec dict."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.kind = spec.get("kind", "fixed")
        if self.kind == "choice":
            self._choices = [int(c["bytes"]) for c in spec["choices"]]
            self._weights = [float(c.get("weight", 1.0)) for c in spec["choices"]]

    def sample(self, rng: random.Random) -> int:
        if self.kind == "fixed":
            return int(self.spec["bytes"])
        if self.kind == "uniform":
            return rng.randint(int(self.spec["min"]), int(self.spec["max"]))
        if self.kind == "lognormal":
            mean = float(self.spec["mean"])
            sigma = float(self.spec.get("sigma", 1.0))
            # Parameterized by the distribution MEAN (what operators state),
            # so mu = ln(mean) - sigma^2/2.
            mu = math.log(mean) - sigma * sigma / 2.0
            v = int(rng.lognormvariate(mu, sigma))
            lo = int(self.spec.get("min", 1))
            hi = int(self.spec.get("max", 1 << 30))
            return min(max(v, lo), hi)
        return rng.choices(self._choices, weights=self._weights, k=1)[0]


class Op(NamedTuple):
    index: int
    kind: str       # GET/PUT/DELETE/LIST/MULTIPART/SELECT
    key: str        # object key ("" for LIST)
    size: int       # payload bytes (PUT/MULTIPART total; 0 otherwise)
    prefix: str     # list prefix (LIST only)


def _key_name(scenario: Scenario, kid: int) -> str:
    return f"{scenario.prefix}key-{kid:06d}"


def generate_ops(scenario: Scenario, phase: Phase, count: int) -> list[Op]:
    """The deterministic op sequence for one phase.

    Maintains a model of which keys exist (prepopulated set, mutated by
    PUT/DELETE as generated) so GET/DELETE/SELECT target keys that should
    exist at that point of the replay -- a generator that GETs
    never-written keys measures the 404 path, not the read path. Zipf
    draws landing on absent keys redraw (bounded), then fall back to the
    hottest existing key; with an empty keyspace the op degrades to PUT.
    """
    seed = (scenario.seed * 1_000_003 + _phase_ordinal(scenario, phase)) & 0x7FFFFFFF
    rng = random.Random(seed)
    theta = scenario.zipf_theta if phase.zipf_theta is None else phase.zipf_theta
    # The rank->key permutation is scenario-seeded (NOT phase-seeded): the
    # hot set is a property of the workload, stable across phases.
    zipf = ZipfianGenerator(
        scenario.keys, theta, rng, perm_rng=random.Random(scenario.seed ^ 0x5A1F)
    )
    sizes = SizeDistribution(phase.sizes or scenario.sizes)
    kinds = sorted(phase.mix)
    weights = [phase.mix[k] for k in kinds]
    existing = set(range(min(scenario.prepopulate, scenario.keys)))
    ops: list[Op] = []
    for i in range(count):
        kind = rng.choices(kinds, weights=weights, k=1)[0]
        key = ""
        size = 0
        prefix = ""
        if kind == "LIST":
            prefix = scenario.prefix
        else:
            kid = zipf.next_key()
            if kind in ("PUT", "MULTIPART"):
                existing.add(kid)
            else:  # GET/DELETE/SELECT need a live key
                if kid not in existing:
                    for _ in range(8):
                        kid = zipf.next_key()
                        if kid in existing:
                            break
                    else:
                        kid = min(existing) if existing else -1
                if kid < 0:
                    kind, kid = "PUT", zipf.next_key()
                    existing.add(kid)
            if kind == "DELETE":
                existing.discard(kid)
            key = _key_name(scenario, kid)
            if kind == "PUT":
                size = sizes.sample(rng)
            elif kind == "MULTIPART":
                size = scenario.multipart_parts * scenario.multipart_part_size
        ops.append(Op(i, kind, key, size, prefix))
    return ops


def _phase_ordinal(scenario: Scenario, phase: Phase) -> int:
    for i, p in enumerate(scenario.phases):
        if p is phase or p.name == phase.name:
            return i
    return len(scenario.phases)


def op_sequence_hash(ops: list[Op]) -> str:
    """sha256 over the canonical op tuples -- the replay-identity witness
    two same-seed runs must agree on."""
    h = hashlib.sha256()
    for op in ops:
        h.update(f"{op.index}|{op.kind}|{op.key}|{op.size}|{op.prefix}\n".encode())
    return h.hexdigest()
