"""Report assembly + SLO judgment + Prometheus rendering for loadgen runs.

The report is the artifact the whole harness exists to produce: one JSON
object (BENCH-style -- tools/perf_gate.py consumes the same single-object
contract) that says what was driven, how the tails looked, where the time
went (cluster stage breakdown), what degraded (hedge/breaker/shed
counters), and whether the scenario's declared SLOs held.

Error-budget burn follows SRE convention: burn = observed error rate /
budgeted error rate. burn <= 1.0 means the run fit its budget; 2.0 means
it burned twice what the SLO allows. Latency SLOs compare the merged p99
per op against the spec's `p99_ms` target.
"""

from __future__ import annotations

from .runner import PhaseResult
from .spec import Scenario

# JSON has no Infinity: burn against a zero budget reports this sentinel.
BURN_CAP = 1e9


def _phase_ops(pr: PhaseResult) -> dict:
    """Per-kind stats for one phase: counts + tails + throughput."""
    from ..control.perf import summarize

    rows = summarize(pr.ledger.snapshot()).get("loadgen", {})
    out: dict = {}
    for kind, counters in sorted(pr.kinds.items()):
        row = dict(rows.get(kind, {}))
        errors = sum(counters["errors"].values())
        total = counters["ok"] + errors
        row.update(
            ok=counters["ok"],
            errors=dict(counters["errors"]),
            error_rate=round(errors / total, 6) if total else 0.0,
            bytes=counters["bytes"],
            ops_per_s=round(total / pr.wall_s, 3) if pr.wall_s else 0.0,
            bytes_per_s=round(counters["bytes"] / pr.wall_s, 1) if pr.wall_s else 0.0,
        )
        out[kind] = row
    return out


def _merged_ops(results: list[PhaseResult]) -> dict:
    """Run-wide per-kind stats: phase ledgers merged bucket-wise."""
    from ..control.perf import merge_snapshots, summarize

    merged = summarize(
        merge_snapshots([pr.ledger.snapshot() for pr in results])
    ).get("loadgen", {})
    wall = sum(pr.wall_s for pr in results)
    out: dict = {}
    kinds = sorted({k for pr in results for k in pr.kinds})
    for kind in kinds:
        ok = sum(pr.kinds.get(kind, {}).get("ok", 0) for pr in results)
        nbytes = sum(pr.kinds.get(kind, {}).get("bytes", 0) for pr in results)
        errors: dict[str, int] = {}
        for pr in results:
            for cls, n in pr.kinds.get(kind, {}).get("errors", {}).items():
                errors[cls] = errors.get(cls, 0) + n
        nerr = sum(errors.values())
        total = ok + nerr
        row = dict(merged.get(kind, {}))
        row.update(
            ok=ok,
            errors=errors,
            error_rate=round(nerr / total, 6) if total else 0.0,
            bytes=nbytes,
            ops_per_s=round(total / wall, 3) if wall else 0.0,
            bytes_per_s=round(nbytes / wall, 1) if wall else 0.0,
        )
        out[kind] = row
    return out


def evaluate_slo(scenario: Scenario, merged_ops: dict) -> dict:
    """Judge the run against the spec's declared per-op SLOs.

    Budget burn counts only server-attributable failures (transport + 5xx):
    a 4xx is the workload's shape (racing deletes yield NoSuchKey), not a
    broken promise by the store. The exception is `client_errors_burn: true`
    on the target: a scenario that never deletes and GETs only prepopulated
    keys declares that a NoSuchKey IS a broken promise (an acked object was
    lost), so 4xx burn too."""
    out: dict = {}
    for op, target in sorted(scenario.slo.items()):
        row = merged_ops.get(op)
        if row is None:
            out[op] = {"skipped": "op not exercised by any phase"}
            continue
        server_errors = sum(
            n for cls, n in row.get("errors", {}).items()
            if target.client_errors_burn or not cls.startswith("4xx")
        )
        total = row.get("ok", 0) + sum(row.get("errors", {}).values())
        err_rate = server_errors / total if total else 0.0
        if target.error_budget > 0:
            burn = min(err_rate / target.error_budget, BURN_CAP)
        else:
            burn = 0.0 if server_errors == 0 else BURN_CAP
        p99 = float(row.get("p99_ms", 0.0))
        p99_ok = target.p99_ms <= 0 or p99 <= target.p99_ms
        out[op] = {
            "p99_ms": p99,
            "target_p99_ms": target.p99_ms,
            "p99_ok": p99_ok,
            "error_rate": round(err_rate, 6),
            "error_budget": target.error_budget,
            "budget_burn": round(burn, 3),
            "burn_ok": burn <= 1.0,
            "ok": p99_ok and burn <= 1.0,
        }
    return out


def _evaluate_compare(
    scenario: Scenario, phases: dict
) -> dict | list | None:
    """Cross-phase ratio check(s): the historical single block (dict in,
    dict out) or a sweep (list in, list out -- e.g. put_scaling's one
    ratio per concurrency rung)."""
    cmp = scenario.compare
    if not cmp:
        return None
    if isinstance(cmp, list):
        return [_evaluate_one(c, phases) for c in cmp]
    return _evaluate_one(cmp, phases)


def _evaluate_one(cmp: dict, phases: dict) -> dict:
    op = str(cmp.get("op", "PUT")).upper()
    metric = str(cmp.get("metric", "bytes_per_s"))
    min_ratio = float(cmp.get("min_ratio", 1.0))
    va = phases.get(cmp["a"], {}).get("ops", {}).get(op, {}).get(metric, 0.0)
    vb = phases.get(cmp["b"], {}).get("ops", {}).get(op, {}).get(metric, 0.0)
    ratio = round(float(va) / float(vb), 3) if vb else 0.0
    return {
        "a": cmp["a"],
        "b": cmp["b"],
        "op": op,
        "metric": metric,
        "value_a": va,
        "value_b": vb,
        "ratio": ratio,
        "min_ratio": min_ratio,
        "reproduced": bool(vb) and ratio >= min_ratio,
    }


def evaluate_cache(scenario: Scenario, cache: dict, phases: dict) -> dict | None:
    """Judge the memcache hit ratio against the spec's `cache` block.

    The judged ratio is one phase's counter DELTA when the block names a
    phase (a cold sweep legitimately misses; only the hot storm is held to
    the promise), else the run-cumulative ratio. A spec that declares the
    gate but ran against a cluster without the tier fails loudly -- a
    hot-read scenario silently measuring the uncached path is the worst
    outcome."""
    gate = scenario.cache
    if gate is None:
        return None
    phase_name = gate.get("phase") or ""
    if phase_name:
        row = phases.get(phase_name, {}).get("cache", {})
    else:
        row = cache
    if not row:
        return {
            "min_hit_ratio": gate["min_hit_ratio"],
            "phase": phase_name,
            "error": "no memcache counters (tier disabled? MTPU_MEMCACHE_MB)",
            "ok": False,
        }
    ratio = float(row.get("hit_ratio", 0.0))
    return {
        "min_hit_ratio": gate["min_hit_ratio"],
        "phase": phase_name,
        "hit_ratio": ratio,
        "ok": ratio >= gate["min_hit_ratio"],
    }


def build_report(
    scenario: Scenario,
    results: list[PhaseResult],
    stage_breakdown: dict,
    degrade: dict,
    probe_cached: bool = False,
    lock_profile: dict | None = None,
    profile: dict | None = None,
    cache: dict | None = None,
) -> dict:
    phases: dict = {}
    for pr in results:
        phases[pr.name] = {
            "wall_s": round(pr.wall_s, 3),
            "concurrency": pr.concurrency,
            "executed": pr.executed,
            "generated": pr.generated,
            "truncated": pr.truncated,
            "op_sequence_sha256": pr.op_hash,
            "ops": _phase_ops(pr),
            "timeline": [
                {"t_s": sec, **counts} for sec, counts in sorted(pr.timeline.items())
            ],
            "chaos_windows": pr.chaos_windows,
        }
        if pr.cache:
            phases[pr.name]["cache"] = pr.cache
    merged = _merged_ops(results)
    report = {
        "loadgen_report": 1,
        "scenario": scenario.name,
        "description": scenario.description,
        "seed": scenario.seed,
        "probe_cached": probe_cached,
        "ops": merged,
        "slo": evaluate_slo(scenario, merged),
        "phases": phases,
        "stage_breakdown": stage_breakdown,
        "degrade": degrade,
    }
    if lock_profile:
        # Only present when the run was sanitized (MTPU_TSAN=1): per-lock
        # acquisition counts, contention, and hold/wait time over the phases.
        report["lock_profile"] = lock_profile
    if profile:
        # Only when the scenario asked for it (profile: true / --profile):
        # the continuous-profiling summary -- gil_load, top role-aggregated
        # stacks, sampler overhead, and the per-hop copy ledger -- so the
        # report names the bottleneck, not just the tails.
        report["profile"] = profile
    if cache:
        report["cache"] = dict(cache)
    cache_slo = evaluate_cache(scenario, cache or {}, phases)
    if cache_slo is not None:
        report["cache_slo"] = cache_slo
    cmp = _evaluate_compare(scenario, phases)
    if cmp is not None:
        report["compare"] = cmp
    if scenario.get_miss_is_loss:
        # The crash-consistency verdict: the spec promised every GET-able
        # key was prepopulated and nothing deletes, so a NoSuchKey means an
        # acked object vanished -- the one thing a crash plane must never
        # allow, however clean the tails look.
        misses = sum(
            n
            for cls, n in merged.get("GET", {}).get("errors", {}).items()
            if cls == "4xx:NoSuchKey"
        )
        report["acked_object_loss"] = {"get_miss_count": misses, "ok": misses == 0}
    return report


# -- Prometheus exposition -----------------------------------------------------

_QUANTS = ("p50_ms", "p95_ms", "p99_ms", "p999_ms", "max_ms")


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render_prometheus(report: dict) -> str:
    """The run as minio_tpu_loadgen_* series (tools/metrics_lint.py-clean),
    for pushing a CI run's outcome at a gateway/textfile collector."""
    sc = _esc(str(report.get("scenario", "")))
    lines: list[str] = []

    lines.append(
        "# HELP minio_tpu_loadgen_ops_total Ops executed by the load generator."
    )
    lines.append("# TYPE minio_tpu_loadgen_ops_total counter")
    for op, row in sorted(report.get("ops", {}).items()):
        opl = _esc(op)
        lines.append(
            f'minio_tpu_loadgen_ops_total{{scenario="{sc}",op="{opl}",result="ok"}} '
            f"{row.get('ok', 0)}"
        )
        nerr = sum(row.get("errors", {}).values())
        lines.append(
            f'minio_tpu_loadgen_ops_total{{scenario="{sc}",op="{opl}",result="error"}} '
            f"{nerr}"
        )

    lines.append(
        "# HELP minio_tpu_loadgen_latency_ms Per-op latency quantiles "
        "(bucket-scheme estimates, milliseconds)."
    )
    lines.append("# TYPE minio_tpu_loadgen_latency_ms gauge")
    for op, row in sorted(report.get("ops", {}).items()):
        for q in _QUANTS:
            if q in row:
                lines.append(
                    f'minio_tpu_loadgen_latency_ms{{scenario="{sc}",op="{_esc(op)}",'
                    f'quantile="{q[:-3]}"}} {row[q]}'
                )

    lines.append(
        "# HELP minio_tpu_loadgen_throughput_bytes_per_second Payload throughput per op."
    )
    lines.append("# TYPE minio_tpu_loadgen_throughput_bytes_per_second gauge")
    for op, row in sorted(report.get("ops", {}).items()):
        lines.append(
            "minio_tpu_loadgen_throughput_bytes_per_second"
            f'{{scenario="{sc}",op="{_esc(op)}"}} {row.get("bytes_per_s", 0.0)}'
        )

    lines.append(
        "# HELP minio_tpu_loadgen_slo_burn Error-budget burn per op "
        "(1.0 = exactly on budget)."
    )
    lines.append("# TYPE minio_tpu_loadgen_slo_burn gauge")
    for op, row in sorted(report.get("slo", {}).items()):
        if "budget_burn" in row:
            lines.append(
                f'minio_tpu_loadgen_slo_burn{{scenario="{sc}",op="{_esc(op)}"}} '
                f"{row['budget_burn']}"
            )

    cache = report.get("cache") or {}
    if cache:
        lines.append(
            "# HELP minio_tpu_loadgen_cache_hit_ratio Run-cumulative memcache "
            "hit ratio of the driven cluster."
        )
        lines.append("# TYPE minio_tpu_loadgen_cache_hit_ratio gauge")
        lines.append(
            f'minio_tpu_loadgen_cache_hit_ratio{{scenario="{sc}"}} '
            f"{cache.get('hit_ratio', 0.0)}"
        )
    return "\n".join(lines) + "\n"
