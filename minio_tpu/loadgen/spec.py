"""Scenario spec: declarative YAML/JSON workload descriptions, validated
into typed objects with typed errors.

A scenario is the unit of replay AND the unit of SLO accounting: the spec
declares both the traffic (phases of op mixes over a keyspace) and the
promise it is judged against (per-op p99 targets + error budgets). Bad
specs fail fast with SpecError carrying the offending path -- a loadgen
run that silently reinterprets a typo'd field measures the wrong thing.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

OP_KINDS = ("GET", "PUT", "DELETE", "LIST", "MULTIPART", "SELECT")

_SIZE_KINDS = ("fixed", "uniform", "lognormal", "choice")


class SpecError(ValueError):
    """A scenario spec failed validation; `path` names the bad field."""

    def __init__(self, path: str, message: str):
        self.path = path
        super().__init__(f"{path}: {message}")


def _require(doc: dict, path: str, key: str, types, default=None, required=False):
    if key not in doc:
        if required:
            raise SpecError(f"{path}.{key}", "required field missing")
        return default
    v = doc[key]
    if not isinstance(v, types) or isinstance(v, bool) and bool not in (
        types if isinstance(types, tuple) else (types,)
    ):
        want = "/".join(
            t.__name__ for t in (types if isinstance(types, tuple) else (types,))
        )
        raise SpecError(f"{path}.{key}", f"expected {want}, got {type(v).__name__}")
    return v


def _number(doc: dict, path: str, key: str, default=None, required=False, minimum=None):
    v = _require(doc, path, key, (int, float), default=default, required=required)
    if v is not None and minimum is not None and v < minimum:
        raise SpecError(f"{path}.{key}", f"must be >= {minimum}, got {v}")
    return v


@dataclass
class SloTarget:
    p99_ms: float = 0.0       # 0 = no latency target declared
    error_budget: float = 1.0  # allowed error fraction; 1.0 = anything goes
    client_errors_burn: bool = False  # 4xx burn budget too (no-delete
    #                                   scenarios: a NoSuchKey = data loss)


@dataclass
class ChaosWindow:
    at_s: float           # offset from phase start when the fault arms
    for_s: float          # how long it stays armed (0 for one-shot admin ops)
    fault: dict | None = None  # chaos/faults.py FaultSpec.from_dict payload
    admin: dict | None = None  # one-shot admin op instead of a fault, e.g.
    #                            {"op": "decommission", "pool": 0}: fired
    #                            once at at_s, never disarmed (exactly one
    #                            of fault/admin per window)


@dataclass
class Phase:
    name: str
    mix: dict[str, float]          # op kind -> weight (normalized)
    concurrency: int = 4
    ramp_s: float = 0.0            # worker start stagger across this window
    ops: int = 0                   # op count budget (0 = duration-bounded)
    duration_s: float = 0.0        # wall budget (0 = op-count-bounded)
    sizes: dict | None = None      # per-phase override of scenario sizes
    zipf_theta: float | None = None  # per-phase key-skew override (a cache
    #                                  scenario drives a uniform cold sweep
    #                                  then a zipfian hot storm)
    chaos: list[ChaosWindow] = field(default_factory=list)


@dataclass
class Scenario:
    name: str
    description: str = ""
    seed: int = 1
    bucket: str = "loadgen"
    nodes: int = 4                 # in-process cluster shape (ignored for live)
    drives_per_node: int = 4
    pools: int = 1                 # server pools in the in-process cluster
    pools_gate: dict | None = None  # {"require_drained": [pool...],
    #                                  "max_drain_s": s}: after the phases,
    #                                  wait for those pools to reach
    #                                  'decommissioned' and gate the run on it
    keys: int = 256                # keyspace size
    prefix: str = "lg/"
    prepopulate: int = 128         # objects PUT before the clock starts
    zipf_theta: float = 0.99       # 0 = uniform key popularity
    sizes: dict = field(default_factory=lambda: {"kind": "fixed", "bytes": 4096})
    multipart_parts: int = 3
    multipart_part_size: int = 5 << 20
    list_max_keys: int = 100
    slo: dict[str, SloTarget] = field(default_factory=dict)
    phases: list[Phase] = field(default_factory=list)
    compare: dict | None = None    # {"a": phase, "b": phase, "op": kind,
    #                                 "metric": ..., "min_ratio": r}
    cache: dict | None = None      # {"min_hit_ratio": r, "phase": name?}:
    #                                 judge the memcache hit ratio (of one
    #                                 phase's delta, or the whole run)
    flight: dict | None = None     # {"phase": name, "max_wait_s": s}: gate
    #                                 the run on the flight recorder -- the
    #                                 named (faulted) phase must auto-capture
    #                                 a bundle on EVERY node whose window
    #                                 overlaps that phase, and the healthy
    #                                 phases must produce none
    env: dict = field(default_factory=dict)  # env knobs the in-process
    #                                 cluster is built under (e.g.
    #                                 MTPU_MEMCACHE_MB); ignored for live
    get_miss_is_loss: bool = False  # scenario never deletes + GETs only
    #                                 prepopulated keys: a GET NoSuchKey is
    #                                 an acked object lost, a hard verdict
    profile: bool = False          # embed the continuous-profiling summary
    #                                (gil_load, role stacks, copy ledger)


def _parse_sizes(doc, path: str) -> dict:
    doc = dict(doc)
    kind = _require(doc, path, "kind", str, default="fixed")
    if kind not in _SIZE_KINDS:
        raise SpecError(f"{path}.kind", f"unknown size kind {kind!r} (want one of {_SIZE_KINDS})")
    doc["kind"] = kind
    if kind == "fixed":
        _number(doc, path, "bytes", required=True, minimum=0)
    elif kind == "uniform":
        lo = _number(doc, path, "min", required=True, minimum=0)
        hi = _number(doc, path, "max", required=True, minimum=0)
        if hi < lo:
            raise SpecError(f"{path}.max", f"max {hi} < min {lo}")
    elif kind == "lognormal":
        _number(doc, path, "mean", required=True, minimum=1)
        _number(doc, path, "sigma", default=1.0, minimum=0)
    else:  # choice
        choices = _require(doc, path, "choices", list, required=True)
        if not choices:
            raise SpecError(f"{path}.choices", "must not be empty")
        for i, c in enumerate(choices):
            if not isinstance(c, dict):
                raise SpecError(f"{path}.choices[{i}]", "expected object")
            _number(c, f"{path}.choices[{i}]", "bytes", required=True, minimum=0)
            _number(c, f"{path}.choices[{i}]", "weight", default=1.0, minimum=0)
    return doc


def _parse_mix(doc, path: str) -> dict[str, float]:
    if not isinstance(doc, dict) or not doc:
        raise SpecError(path, "mix must be a non-empty object of op -> weight")
    mix: dict[str, float] = {}
    for op, w in doc.items():
        opu = str(op).upper()
        if opu not in OP_KINDS:
            raise SpecError(f"{path}.{op}", f"unknown op kind (want one of {OP_KINDS})")
        if not isinstance(w, (int, float)) or isinstance(w, bool) or w < 0:
            raise SpecError(f"{path}.{op}", f"weight must be a number >= 0, got {w!r}")
        mix[opu] = float(w)
    total = sum(mix.values())
    if total <= 0:
        raise SpecError(path, "mix weights sum to zero")
    return {op: w / total for op, w in mix.items()}


def _parse_phase(doc, path: str) -> Phase:
    if not isinstance(doc, dict):
        raise SpecError(path, "phase must be an object")
    name = _require(doc, path, "name", str, required=True)
    mix = _parse_mix(doc.get("mix"), f"{path}.mix")
    ph = Phase(
        name=name,
        mix=mix,
        concurrency=int(_number(doc, path, "concurrency", default=4, minimum=1)),
        ramp_s=float(_number(doc, path, "ramp_s", default=0.0, minimum=0)),
        ops=int(_number(doc, path, "ops", default=0, minimum=0)),
        duration_s=float(_number(doc, path, "duration_s", default=0.0, minimum=0)),
    )
    if "sizes" in doc:
        ph.sizes = _parse_sizes(
            _require(doc, path, "sizes", dict, required=True), f"{path}.sizes"
        )
    if "zipf_theta" in doc:
        theta = float(_number(doc, path, "zipf_theta", required=True, minimum=0))
        if theta >= 1.0:
            raise SpecError(f"{path}.zipf_theta", f"must be < 1.0, got {theta}")
        ph.zipf_theta = theta
    if not ph.ops and not ph.duration_s:
        raise SpecError(path, "phase needs ops or duration_s (both zero)")
    for i, cw in enumerate(doc.get("chaos") or []):
        cpath = f"{path}.chaos[{i}]"
        if not isinstance(cw, dict):
            raise SpecError(cpath, "chaos window must be an object")
        fault = _require(cw, cpath, "fault", dict, default=None)
        admin = _require(cw, cpath, "admin", dict, default=None)
        if (fault is None) == (admin is None):
            raise SpecError(cpath, "chaos window needs exactly one of fault/admin")
        if fault is not None and "kind" not in fault:
            raise SpecError(f"{cpath}.fault", "fault spec needs a 'kind'")
        if admin is not None and "op" not in admin:
            raise SpecError(f"{cpath}.admin", "admin op needs an 'op'")
        ph.chaos.append(
            ChaosWindow(
                at_s=float(_number(cw, cpath, "at_s", default=0.0, minimum=0)),
                for_s=float(
                    _number(cw, cpath, "for_s",
                            required=fault is not None, default=0.0, minimum=0)
                ),
                fault=dict(fault) if fault is not None else None,
                admin=dict(admin) if admin is not None else None,
            )
        )
    return ph


def _parse_slo(doc, path: str) -> dict[str, SloTarget]:
    out: dict[str, SloTarget] = {}
    if doc is None:
        return out
    if not isinstance(doc, dict):
        raise SpecError(path, "slo must be an object of op -> targets")
    for op, t in doc.items():
        opu = str(op).upper()
        if opu not in OP_KINDS:
            raise SpecError(f"{path}.{op}", f"unknown op kind (want one of {OP_KINDS})")
        if not isinstance(t, dict):
            raise SpecError(f"{path}.{op}", "expected object with p99_ms/error_budget")
        budget = _number(t, f"{path}.{op}", "error_budget", default=1.0, minimum=0)
        if budget > 1.0:
            raise SpecError(f"{path}.{op}.error_budget", f"must be <= 1.0, got {budget}")
        out[opu] = SloTarget(
            p99_ms=float(_number(t, f"{path}.{op}", "p99_ms", default=0.0, minimum=0)),
            error_budget=float(budget),
            client_errors_burn=bool(
                _require(t, f"{path}.{op}", "client_errors_burn", bool, default=False)
            ),
        )
    return out


def parse_scenario(doc: dict) -> Scenario:
    """Validate a decoded spec document into a Scenario (raises SpecError)."""
    if not isinstance(doc, dict):
        raise SpecError("$", "scenario must be an object")
    name = _require(doc, "$", "name", str, required=True)
    ks = _require(doc, "$", "keyspace", dict, default={})
    cluster = _require(doc, "$", "cluster", dict, default={})
    sc = Scenario(
        name=name,
        description=_require(doc, "$", "description", str, default=""),
        seed=int(_number(doc, "$", "seed", default=1)),
        bucket=_require(doc, "$", "bucket", str, default="loadgen"),
        nodes=int(_number(cluster, "$.cluster", "nodes", default=4, minimum=1)),
        drives_per_node=int(
            _number(cluster, "$.cluster", "drives_per_node", default=4, minimum=1)
        ),
        pools=int(_number(cluster, "$.cluster", "pools", default=1, minimum=1)),
        keys=int(_number(ks, "$.keyspace", "keys", default=256, minimum=1)),
        prefix=_require(ks, "$.keyspace", "prefix", str, default="lg/"),
        prepopulate=int(_number(ks, "$.keyspace", "prepopulate", default=128, minimum=0)),
        zipf_theta=float(_number(ks, "$.keyspace", "zipf_theta", default=0.99, minimum=0)),
        sizes=_parse_sizes(_require(doc, "$", "sizes", dict, default={"kind": "fixed", "bytes": 4096}), "$.sizes"),
        slo=_parse_slo(doc.get("slo"), "$.slo"),
        compare=_require(doc, "$", "compare", (dict, list), default=None),
        profile=bool(_require(doc, "$", "profile", bool, default=False)),
        get_miss_is_loss=bool(
            _require(doc, "$", "get_miss_is_loss", bool, default=False)
        ),
    )
    if sc.get_miss_is_loss:
        if sc.prepopulate < sc.keys:
            raise SpecError(
                "$.keyspace.prepopulate",
                "get_miss_is_loss needs every GET-able key prepopulated "
                f"(prepopulate {sc.prepopulate} < keys {sc.keys})",
            )
        for i, p in enumerate(doc.get("phases") or []):
            if isinstance(p, dict) and "DELETE" in {
                str(k).upper() for k in (p.get("mix") or {})
            }:
                raise SpecError(
                    f"$.phases[{i}].mix",
                    "get_miss_is_loss scenarios must not DELETE: a racing "
                    "delete makes every GET miss ambiguous",
                )
    mp = _require(doc, "$", "multipart", dict, default={})
    sc.multipart_parts = int(_number(mp, "$.multipart", "parts", default=3, minimum=1))
    sc.multipart_part_size = int(
        _number(mp, "$.multipart", "part_size", default=5 << 20, minimum=1)
    )
    sc.list_max_keys = int(_number(doc, "$", "list_max_keys", default=100, minimum=1))
    if sc.prepopulate > sc.keys:
        raise SpecError("$.keyspace.prepopulate", f"exceeds keyspace keys ({sc.keys})")
    phases = _require(doc, "$", "phases", list, required=True)
    if not phases:
        raise SpecError("$.phases", "must not be empty")
    sc.phases = [_parse_phase(p, f"$.phases[{i}]") for i, p in enumerate(phases)]
    names = [p.name for p in sc.phases]
    if len(set(names)) != len(names):
        raise SpecError("$.phases", f"duplicate phase names: {names}")
    env = _require(doc, "$", "env", dict, default={})
    for k, v in env.items():
        if not isinstance(v, (str, int, float)) or isinstance(v, bool):
            raise SpecError(f"$.env.{k}", f"expected string/number, got {type(v).__name__}")
        sc.env[str(k)] = str(v)
    pg = _require(doc, "$", "pools", dict, default=None)
    if pg is not None:
        req = _require(pg, "$.pools", "require_drained", list, default=[])
        drained: list[int] = []
        for i, p in enumerate(req):
            if not isinstance(p, int) or isinstance(p, bool) or not 0 <= p < sc.pools:
                raise SpecError(
                    f"$.pools.require_drained[{i}]",
                    f"expected pool index 0..{sc.pools - 1}, got {p!r}",
                )
            drained.append(p)
        sc.pools_gate = {
            "require_drained": drained,
            "max_drain_s": float(
                _number(pg, "$.pools", "max_drain_s", default=120.0, minimum=0)
            ),
        }
    cache = _require(doc, "$", "cache", dict, default=None)
    if cache is not None:
        ratio = _number(cache, "$.cache", "min_hit_ratio", required=True, minimum=0)
        if ratio > 1.0:
            raise SpecError("$.cache.min_hit_ratio", f"must be <= 1.0, got {ratio}")
        phase_name = _require(cache, "$.cache", "phase", str, default="")
        if phase_name and phase_name not in names:
            raise SpecError("$.cache.phase", f"unknown phase {phase_name!r}")
        sc.cache = {"min_hit_ratio": float(ratio), "phase": phase_name}
    fl = _require(doc, "$", "flight", dict, default=None)
    if fl is not None:
        phase_name = _require(fl, "$.flight", "phase", str, required=True)
        if phase_name not in names:
            raise SpecError("$.flight.phase", f"unknown phase {phase_name!r}")
        sc.flight = {
            "phase": phase_name,
            "max_wait_s": float(
                _number(fl, "$.flight", "max_wait_s", default=15.0, minimum=0)
            ),
        }
    if sc.compare is not None:
        # One block (dict, the historical shape) or a list of blocks (e.g.
        # a concurrency sweep asserting one ratio per rung).
        is_list = isinstance(sc.compare, list)
        blocks = sc.compare if is_list else [sc.compare]
        if not blocks:
            raise SpecError("$.compare", "must not be empty")
        for bi, blk in enumerate(blocks):
            loc = f"$.compare[{bi}]" if is_list else "$.compare"
            if not isinstance(blk, dict):
                raise SpecError(loc, "compare entry must be an object")
            for k in ("a", "b"):
                pn = _require(blk, loc, k, str, required=True)
                if pn not in names:
                    raise SpecError(f"{loc}.{k}", f"unknown phase {pn!r}")
            _number(blk, loc, "min_ratio", default=1.0, minimum=0)
    return sc


def load_scenario(path: str) -> Scenario:
    """Load + validate a YAML or JSON scenario file."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        raise SpecError("$", f"cannot read {path}: {e}") from e
    doc = None
    if path.endswith(".json"):
        try:
            doc = json.loads(raw)
        except json.JSONDecodeError as e:
            raise SpecError("$", f"invalid JSON: {e}") from e
    else:
        try:
            import yaml
        except ImportError as e:  # environment without pyyaml: JSON still works
            raise SpecError("$", "pyyaml unavailable; use a .json spec") from e
        try:
            doc = yaml.safe_load(raw)
        except yaml.YAMLError as e:
            raise SpecError("$", f"invalid YAML: {e}") from e
    return parse_scenario(doc)
