"""In-process multi-node cluster for loadgen runs.

The same machinery the distributed tests trust (tests/test_dist.py): N
Node instances over local temp-dir drives, each behind its own
ThreadedServer on a localhost port, sharing nothing but the endpoint list
-- real sigv4 auth, real internode REST, real erasure IO, one process.
Packaged here (not in tests/) so `tools/loadgen.py` can stand a cluster up
outside pytest; tests/harness.py re-exports it for fixtures.
"""

from __future__ import annotations

import os
import socket
import threading
from types import SimpleNamespace

ROOT_USER = "loadgenadmin"
ROOT_PASSWORD = "loadgen-secret-key"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class InProcessCluster:
    """N-node erasure cluster in this process; `urls` is the S3 surface."""

    def __init__(
        self,
        workdir: str,
        n_nodes: int = 4,
        drives_per_node: int = 4,
        root_user: str = ROOT_USER,
        root_password: str = ROOT_PASSWORD,
        build_timeout_s: float = 120.0,
        pools: int = 1,
    ):
        from ..api.server import ThreadedServer
        from ..dist.node import Node

        self.root_user = root_user
        self.root_password = root_password
        ports = [_free_port() for _ in range(n_nodes)]
        self.urls = [f"http://127.0.0.1:{p}" for p in ports]
        # pools > 1 builds a server-pools cluster: each pool is an
        # independent endpoint group of the same shape (the reference's
        # `minio server poolA{1...n} poolB{1...n}` expansion), which is
        # what the pool-lifecycle scenarios decommission out from under
        # live traffic.
        endpoint_pools: list[list[str]] = []
        for pi in range(pools):
            group = []
            for ni in range(n_nodes):
                for di in range(drives_per_node):
                    tag = f"p{pi}n{ni}d{di}" if pools > 1 else f"n{ni}d{di}"
                    d = os.path.join(workdir, tag)
                    os.makedirs(d, exist_ok=True)
                    group.append(f"{self.urls[ni]}{d}")
            endpoint_pools.append(group)
        endpoints = endpoint_pools if pools > 1 else endpoint_pools[0]
        self.nodes = [
            Node(
                endpoints,
                url=self.urls[ni],
                root_user=root_user,
                root_password=root_password,
                set_drive_count=n_nodes * drives_per_node,
            )
            for ni in range(n_nodes)
        ]
        self.servers = []
        try:
            for ni, node in enumerate(self.nodes):
                ts = ThreadedServer(SimpleNamespace(app=node.make_app()), port=ports[ni])
                ts.start()
                self.servers.append(ts)
            # Build concurrently: node 0 leads the format, the rest wait
            # for quorum (the same dance real multi-server boot does).
            threads = [threading.Thread(target=n.build, daemon=True) for n in self.nodes]
            for t in threads:
                t.start()
            for t in threads:
                t.join(build_timeout_s)
            if not all(n.pools is not None for n in self.nodes):
                raise RuntimeError(
                    f"cluster failed to build within {build_timeout_s:.0f}s "
                    f"({n_nodes} nodes x {drives_per_node} drives)"
                )
        except BaseException:
            self.stop()
            raise

    def stop(self) -> None:
        for ts in self.servers:
            try:
                ts.stop()
            except Exception:  # noqa: BLE001 - teardown must reach every server
                pass
        self.servers = []
        # Stop every node's background workers too (replication, MRF heal,
        # disk-heal monitor, ...): a sanitized run (MTPU_TSAN=1) leak-checks
        # threads at exit, and a plain run shouldn't strand daemons either.
        for node in self.nodes:
            try:
                node.close()
            except Exception:  # noqa: BLE001
                pass
        self.nodes = []
