"""Load targets: the signed S3 op surface + the admin observability surface.

One S3Target drives any cluster that speaks the API -- the in-process
multi-node harness (cluster.py) or a live endpoint -- with sigv4-signed
requests round-robined across node URLs. Sessions are per-(thread, node):
workers never share a connection (requests.Session is not thread-safe and
sharing would serialize the very concurrency the scenario declares).

Error classes are what the SLO budget counts: transport failures and 5xx
burn budget; 4xx are split out by S3 code (a NoSuchKey during a racing
DELETE mix is workload shape, not server failure) and do NOT burn unless
the spec says so via `client_errors_burn`.

`requests` use here is deliberate and out of scope for the raw-transport
invariant: loadgen IS the external client; internode RPC discipline
(deadlines, chaos seams) does not apply to the traffic source.
"""

from __future__ import annotations

import threading
import urllib.parse
import xml.etree.ElementTree as ET
from typing import NamedTuple

import requests

from ..api.auth import Credentials, sign_request

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"
ADMIN = "/mtpu/admin/v1"

_SELECT_XML = (
    b"<?xml version=\"1.0\" encoding=\"UTF-8\"?>"
    b"<SelectObjectContentRequest>"
    b"<Expression>SELECT * FROM S3Object</Expression>"
    b"<ExpressionType>SQL</ExpressionType>"
    b"<InputSerialization><CSV/></InputSerialization>"
    b"<OutputSerialization><CSV/></OutputSerialization>"
    b"</SelectObjectContentRequest>"
)


class OpResult(NamedTuple):
    ok: bool
    error_class: str  # "" when ok; "transport" / "5xx" / "4xx:<Code>"
    nbytes: int       # payload bytes moved (PUT body / GET body / parts)


def _s3_code(resp: requests.Response) -> str:
    try:
        root = ET.fromstring(resp.content)
        code = root.find("Code")
        if code is None:
            code = root.find(f"{_NS}Code")
        if code is not None and code.text:
            return code.text
    except ET.ParseError:
        pass
    return str(resp.status_code)


def classify(resp: requests.Response) -> str:
    if resp.status_code < 400:
        return ""
    if resp.status_code >= 500:
        # Carry the S3 code: a shed (503 SlowDownRead) and an internal
        # error read very differently in a report, and both burn budget.
        return f"5xx:{_s3_code(resp)}"
    return f"4xx:{_s3_code(resp)}"


class S3Target:
    """Signed S3 ops against one or more node URLs of the same cluster."""

    def __init__(self, urls: list[str], access_key: str, secret_key: str,
                 region: str = "us-east-1", timeout_s: float = 30.0):
        if not urls:
            raise ValueError("S3Target needs at least one node URL")
        self.urls = [u.rstrip("/") for u in urls]
        self.creds = Credentials(access_key, secret_key)
        self.region = region
        self.timeout_s = timeout_s
        self._tls = threading.local()

    def _session(self, node: int) -> requests.Session:
        sessions = getattr(self._tls, "sessions", None)
        if sessions is None:
            sessions = self._tls.sessions = {}
        s = sessions.get(node)
        if s is None:
            s = sessions[node] = requests.Session()
        return s

    def close(self) -> None:
        sessions = getattr(self._tls, "sessions", None) or {}
        for s in sessions.values():
            s.close()
        self._tls.sessions = {}

    def request(self, method: str, path: str, query=None, body: bytes = b"",
                node: int = 0, stream: bool = False) -> requests.Response:
        query = query or []
        node = node % len(self.urls)
        base = self.urls[node]
        url = base + urllib.parse.quote(path)
        if query:
            url += "?" + urllib.parse.urlencode(query)
        headers = {"host": urllib.parse.urlparse(base).netloc}
        headers = sign_request(
            self.creds, method, path, query, headers, body, region=self.region
        )
        headers.pop("host")
        return self._session(node).request(
            method, url, data=body, headers=headers,
            timeout=self.timeout_s, stream=stream,
        )

    # -- scenario ops ------------------------------------------------------

    def ensure_bucket(self, bucket: str) -> None:
        r = self.request("PUT", f"/{bucket}")
        if r.status_code not in (200, 409):
            raise RuntimeError(f"cannot create bucket {bucket}: {r.status_code} {r.text[:200]}")

    def put(self, bucket: str, key: str, body: bytes, node: int = 0) -> OpResult:
        try:
            r = self.request("PUT", f"/{bucket}/{key}", body=body, node=node)
        except requests.RequestException:
            return OpResult(False, "transport", 0)
        err = classify(r)
        return OpResult(not err, err, len(body) if not err else 0)

    def get(self, bucket: str, key: str, node: int = 0) -> OpResult:
        try:
            r = self.request("GET", f"/{bucket}/{key}", node=node)
            n = len(r.content)
        except requests.RequestException:
            return OpResult(False, "transport", 0)
        err = classify(r)
        return OpResult(not err, err, n if not err else 0)

    def delete(self, bucket: str, key: str, node: int = 0) -> OpResult:
        try:
            r = self.request("DELETE", f"/{bucket}/{key}", node=node)
        except requests.RequestException:
            return OpResult(False, "transport", 0)
        # S3 DELETE is idempotent: 204 on present AND absent keys.
        err = "" if r.status_code in (200, 204) else classify(r)
        return OpResult(not err, err, 0)

    def list(self, bucket: str, prefix: str, max_keys: int, node: int = 0) -> OpResult:
        q = [("list-type", "2"), ("prefix", prefix), ("max-keys", str(max_keys))]
        try:
            r = self.request("GET", f"/{bucket}", query=q, node=node)
            n = len(r.content)
        except requests.RequestException:
            return OpResult(False, "transport", 0)
        err = classify(r)
        return OpResult(not err, err, n if not err else 0)

    def multipart(self, bucket: str, key: str, part: bytes, parts: int,
                  node: int = 0) -> OpResult:
        """Full create -> upload N parts -> complete flow as ONE op: the
        latency an application sees for a large object is the whole dance."""
        path = f"/{bucket}/{key}"
        try:
            r = self.request("POST", path, query=[("uploads", "")], node=node)
            if classify(r):
                return OpResult(False, classify(r), 0)
            upload_el = ET.fromstring(r.content).find(f"{_NS}UploadId")
            if upload_el is None or not upload_el.text:
                return OpResult(False, "5xx", 0)
            uid = upload_el.text
            etags = []
            for n in range(1, parts + 1):
                r = self.request(
                    "PUT", path,
                    query=[("partNumber", str(n)), ("uploadId", uid)],
                    body=part, node=node,
                )
                if classify(r):
                    self.request("DELETE", path, query=[("uploadId", uid)], node=node)
                    return OpResult(False, classify(r), 0)
                etags.append(r.headers.get("ETag", "").strip('"'))
            complete = (
                "<CompleteMultipartUpload>"
                + "".join(
                    f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
                    for n, e in enumerate(etags, 1)
                )
                + "</CompleteMultipartUpload>"
            ).encode()
            r = self.request("POST", path, query=[("uploadId", uid)],
                             body=complete, node=node)
        except (requests.RequestException, ET.ParseError):
            return OpResult(False, "transport", 0)
        err = classify(r)
        return OpResult(not err, err, len(part) * parts if not err else 0)

    def select(self, bucket: str, key: str, node: int = 0) -> OpResult:
        try:
            r = self.request(
                "POST", f"/{bucket}/{key}",
                query=[("select", ""), ("select-type", "2")],
                body=_SELECT_XML, node=node,
            )
            n = len(r.content)
        except requests.RequestException:
            return OpResult(False, "transport", 0)
        err = classify(r)
        return OpResult(not err, err, n if not err else 0)


# -- admin observability/chaos surfaces ---------------------------------------


class InProcessAdmin:
    """Admin surface when the cluster shares this process: read the global
    singletons directly. The process-wide perf ledger IS the cluster-merged
    view here (every node records into it), so asking one node for
    ?cluster=1 would sum the same ledger once per node."""

    probe_cached = False

    def __init__(self, cluster=None):
        # Optional InProcessCluster handle: memcache counters are per-node
        # objects (not global singletons), so the hot-read report needs the
        # nodes to sum them over. Tests that don't care pass nothing.
        self.cluster = cluster

    def stage_breakdown(self) -> dict:
        from ..control.perf import GLOBAL_PERF, summarize

        return summarize(GLOBAL_PERF.ledger.snapshot())

    def cache_stats(self) -> dict:
        """Cluster-summed memcache counters ({} when no node runs the tier)."""
        nodes = getattr(self.cluster, "nodes", None) or ()
        stats = [
            n.memcache.stats()
            for n in nodes
            if getattr(n, "memcache", None) is not None
        ]
        if not stats:
            return {}
        out = {k: sum(s[k] for s in stats) for k in stats[0] if k != "hit_ratio"}
        lookups = out["hits"] + out["misses"]
        out["hit_ratio"] = round(out["hits"] / lookups, 4) if lookups else 0.0
        return out

    def degrade(self) -> dict:
        from ..control.degrade import GLOBAL_DEGRADE

        return GLOBAL_DEGRADE.snapshot()

    def reset_perf(self) -> None:
        from ..control.perf import GLOBAL_PERF

        GLOBAL_PERF.ledger.reset()
        GLOBAL_PERF.slow.reset()

    def arm_fault(self, fault: dict) -> str:
        from ..chaos import crash as crash_mod
        from ..chaos.faults import REGISTRY, FaultSpec

        if fault.get("kind") == crash_mod.CRASH_KIND:
            return crash_mod.REGISTRY.arm(crash_mod.CrashSpec.from_dict(fault))
        return REGISTRY.arm(FaultSpec.from_dict(fault))

    def disarm_fault(self, fault_id: str) -> None:
        from ..chaos import crash as crash_mod
        from ..chaos.faults import REGISTRY

        if not REGISTRY.disarm(fault_id):
            crash_mod.REGISTRY.disarm(fault_id)

    def start_profile(self) -> bool:
        from ..control.profiler import GLOBAL_PROFILER

        return GLOBAL_PROFILER.ensure_started()

    def profile_summary(self) -> dict:
        from ..control.profiler import GLOBAL_PROFILER

        return GLOBAL_PROFILER.summary()

    # -- flight recorder ----------------------------------------------------

    def flight_reset(self) -> None:
        from ..control.flight import GLOBAL_FLIGHT

        GLOBAL_FLIGHT.reset()

    def flight_bundles(self) -> list:
        """Bundle metas for EVERY node: the process-wide recorder stores one
        bundle per node tag, so its list already is the cluster view."""
        from ..control.flight import GLOBAL_FLIGHT

        return GLOBAL_FLIGHT.list()

    # -- pool lifecycle ----------------------------------------------------

    def _poolmgr(self):
        for n in getattr(self.cluster, "nodes", None) or ():
            pm = getattr(n, "poolmgr", None)
            if pm is not None:
                return pm
        raise RuntimeError("no pool manager in the in-process cluster")

    def pool_admin(self, op: dict) -> dict:
        """One-shot pool lifecycle op: {"op": "decommission", "pool": i} /
        {"op": "attach", "endpoints": [...]} / {"op": "rebalance", ...}."""
        from dataclasses import asdict

        pm = self._poolmgr()
        kind = op.get("op")
        if kind == "decommission":
            tr = pm.start_decommission(
                int(op["pool"]), wait=bool(op.get("wait", False))
            )
            return {"drain": asdict(tr)}
        if kind == "attach":
            idx = pm.attach_endpoints([str(e) for e in op.get("endpoints", [])])
            return {"pool": idx}
        if kind == "rebalance":
            if op.get("start", True):
                return {"rebalance": pm.start_rebalance(op.get("threshold"))}
            return {"rebalance": pm.stop_rebalance()}
        raise RuntimeError(f"unknown pool admin op {kind!r}")

    def pool_status(self) -> dict:
        return self._poolmgr().status()


class EndpointAdmin:
    """Admin surface over the wire (live-endpoint mode): the signed admin
    REST endpoints, with ?cluster=1 doing the peer merge server-side."""

    def __init__(self, target: S3Target):
        self.target = target
        self.probe_cached = False

    def _get_json(self, path: str, query=None) -> dict:
        r = self.target.request("GET", path, query=query or [])
        if r.status_code != 200:
            return {}
        try:
            return r.json()
        except ValueError:
            return {}

    def stage_breakdown(self) -> dict:
        doc = self._get_json(ADMIN + "/perf", query=[("cluster", "1")])
        cluster = doc.get("cluster", {})
        if isinstance(cluster, dict) and cluster.get("stages"):
            return cluster["stages"]
        return doc.get("node", {}).get("stages", {})

    def degrade(self) -> dict:
        return self._get_json(ADMIN + "/perf").get("degrade", {})

    def cache_stats(self) -> dict:
        return self._get_json(ADMIN + "/perf").get("memcache", {})

    def reset_perf(self) -> None:
        self.target.request("GET", ADMIN + "/perf",
                            query=[("cluster", "1"), ("reset", "1")])

    def arm_fault(self, fault: dict) -> str:
        import json as _json

        r = self.target.request("POST", ADMIN + "/chaos",
                                body=_json.dumps(fault).encode())
        if r.status_code != 200:
            raise RuntimeError(f"chaos arm failed: {r.status_code} {r.text[:200]}")
        return r.json().get("fault_id", "")

    def disarm_fault(self, fault_id: str) -> None:
        self.target.request("DELETE", ADMIN + "/chaos",
                            query=[("fault-id", fault_id)])

    def start_profile(self) -> bool:
        # The plane is armed server-side at node build; asking for the
        # summary confirms it's live (armed=False in the block otherwise).
        return bool(self._get_json(ADMIN + "/profile").get("armed"))

    def profile_summary(self) -> dict:
        return self._get_json(ADMIN + "/profile", query=[("summary", "1")])

    # -- flight recorder ----------------------------------------------------

    def flight_bundles(self) -> list:
        """Cluster-merged bundle metas (GET /flight?cluster=1 flattened)."""
        doc = self._get_json(ADMIN + "/flight", query=[("cluster", "1")])
        out = list(doc.get("bundles") or [])
        for row in (doc.get("peers") or {}).values():
            if isinstance(row, dict) and row.get("ok"):
                out.extend(row.get("bundles") or [])
        return out

    # -- pool lifecycle ----------------------------------------------------

    def pool_admin(self, op: dict) -> dict:
        import json as _json

        paths = {
            "decommission": "/pools/decommission",
            "attach": "/pools/attach",
            "rebalance": "/pools/rebalance",
        }
        kind = op.get("op")
        path = paths.get(str(kind))
        if path is None:
            raise RuntimeError(f"unknown pool admin op {kind!r}")
        body = {k: v for k, v in op.items() if k != "op"}
        r = self.target.request("POST", ADMIN + path,
                                body=_json.dumps(body).encode())
        if r.status_code != 200:
            raise RuntimeError(
                f"pool admin {kind} failed: {r.status_code} {r.text[:200]}"
            )
        return r.json()

    def pool_status(self) -> dict:
        return self._get_json(ADMIN + "/pools/status")
