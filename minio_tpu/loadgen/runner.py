"""Scenario execution: drive a target with the generated op sequence and
measure every op into the control/perf.py bucket scheme.

Timing discipline:
  * op generation is pre-run (generators.py) -- the replay clock never
    waits on the dice;
  * prepopulation happens OFF the clock -- a scenario measures steady
    state, not its own setup;
  * each phase owns a fresh StageLedger keyed ("loadgen", op kind), so
    per-phase tails never bleed into each other, and the phase snapshots
    merge (control/perf.py merge_snapshots) into the run-wide view.

Chaos windows arm real faults through the admin surface at their declared
offsets (threading.Timer off the worker path) and ALWAYS disarm on exit --
a loadgen crash must not leave a live cluster injecting faults.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ..control.perf import StageLedger
from .generators import Op, generate_ops, op_sequence_hash
from .spec import Phase, Scenario
from .target import OpResult, S3Target
from ..control.sanitizer import san_lock, san_rlock

# Op-list cap for duration-bounded phases (generated up front; the run
# consumes a prefix). Logged into the phase result when it truncates.
_DURATION_OP_CAP = 200_000


def _payload(key: str, size: int) -> bytes:
    """Deterministic CSV-shaped payload: SELECT ops over these objects
    exercise the real scan path instead of erroring on binary junk."""
    if size <= 0:
        return b""
    row = f"{key},0123456789abcdef,42\n".encode()
    reps = size // len(row) + 1
    return (row * reps)[:size]


@dataclass
class PhaseResult:
    name: str
    concurrency: int
    wall_s: float = 0.0
    executed: int = 0
    generated: int = 0
    truncated: bool = False  # duration phase hit the op-list cap
    op_hash: str = ""
    ledger: StageLedger = field(default_factory=StageLedger)
    # kind -> {"ok": n, "bytes": n, "errors": {class: n}}
    kinds: dict = field(default_factory=dict)
    # second offset -> {"ops": n, "errors": n}
    timeline: dict = field(default_factory=dict)
    chaos_windows: list = field(default_factory=list)
    # memcache counter DELTA over this phase ({} when the tier is off):
    # run-cumulative counters can't judge one phase's hit ratio.
    cache: dict = field(default_factory=dict)


_CACHE_COUNTERS = (
    "hits", "misses", "fills", "evictions", "invalidations",
    "singleflight_waits",
)


def _cache_delta(before: dict, after: dict) -> dict:
    if not after:
        return {}
    out = {k: after.get(k, 0) - before.get(k, 0) for k in _CACHE_COUNTERS}
    out["bytes"] = after.get("bytes", 0)
    out["entries"] = after.get("entries", 0)
    lookups = out["hits"] + out["misses"]
    out["hit_ratio"] = round(out["hits"] / lookups, 4) if lookups else 0.0
    return out


class ScenarioRunner:
    def __init__(self, scenario: Scenario, target: S3Target, admin, log=None):
        self.scenario = scenario
        self.target = target
        self.admin = admin  # InProcessAdmin | EndpointAdmin
        self._log = log or (lambda msg: None)

    # -- op dispatch -------------------------------------------------------

    def _execute(self, op: Op) -> OpResult:
        b = self.scenario.bucket
        node = op.index  # S3Target mods by len(urls): round-robin
        if op.kind == "GET":
            return self.target.get(b, op.key, node=node)
        if op.kind == "PUT":
            return self.target.put(b, op.key, _payload(op.key, op.size), node=node)
        if op.kind == "DELETE":
            return self.target.delete(b, op.key, node=node)
        if op.kind == "LIST":
            return self.target.list(b, op.prefix, self.scenario.list_max_keys, node=node)
        if op.kind == "MULTIPART":
            part = _payload(op.key, self.scenario.multipart_part_size)
            return self.target.multipart(
                b, op.key, part, self.scenario.multipart_parts, node=node
            )
        if op.kind == "SELECT":
            return self.target.select(b, op.key, node=node)
        return OpResult(False, "unknown-op", 0)

    # -- setup -------------------------------------------------------------

    def prepopulate(self) -> int:
        """PUT the declared base keyspace (off the measurement clock)."""
        sc = self.scenario
        self.target.ensure_bucket(sc.bucket)
        if not sc.prepopulate:
            return 0
        import random

        from .generators import SizeDistribution

        rng = random.Random(sc.seed ^ 0x5EED)
        sizes = SizeDistribution(sc.sizes)
        keys = [
            (f"{sc.prefix}key-{kid:06d}", sizes.sample(rng))
            for kid in range(min(sc.prepopulate, sc.keys))
        ]
        failures = 0
        with ThreadPoolExecutor(max_workers=8, thread_name_prefix="lg-prepop") as ex:
            futs = [
                ex.submit(self.target.put, sc.bucket, k, _payload(k, n), i)
                for i, (k, n) in enumerate(keys)
            ]
            for f in futs:
                if not f.result().ok:
                    failures += 1
        if failures:
            raise RuntimeError(f"prepopulate: {failures}/{len(keys)} PUTs failed")
        return len(keys)

    # -- phase execution ---------------------------------------------------

    def _run_phase(self, phase: Phase) -> PhaseResult:
        count = phase.ops or _DURATION_OP_CAP
        ops = generate_ops(self.scenario, phase, count)
        pr = PhaseResult(
            name=phase.name,
            concurrency=phase.concurrency,
            generated=len(ops),
            truncated=not phase.ops,
            op_hash=op_sequence_hash(ops),
        )
        try:
            cache_before = self.admin.cache_stats()
        except Exception:  # noqa: BLE001 - a live target may deny admin
            cache_before = {}
        stats_lock = san_lock("ScenarioRunner.stats_lock")
        next_idx = itertools.count()
        stop = threading.Event()
        start = time.monotonic()
        deadline = start + phase.duration_s if phase.duration_s else None

        def worker(wi: int) -> None:
            if phase.ramp_s and phase.concurrency > 1:
                delay = phase.ramp_s * wi / phase.concurrency
                if stop.wait(delay):
                    return
            while not stop.is_set():
                i = next(next_idx)
                if i >= len(ops):
                    return
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    return
                op = ops[i]
                t0 = time.perf_counter()
                c0 = time.thread_time()
                res = self._execute(op)
                dt = time.perf_counter() - t0
                pr.ledger.record("loadgen", op.kind, dt, time.thread_time() - c0)
                sec = int(time.monotonic() - start)
                with stats_lock:
                    pr.executed += 1
                    row = pr.kinds.setdefault(
                        op.kind, {"ok": 0, "bytes": 0, "errors": {}}
                    )
                    tl = pr.timeline.setdefault(sec, {"ops": 0, "errors": 0})
                    tl["ops"] += 1
                    if res.ok:
                        row["ok"] += 1
                        row["bytes"] += res.nbytes
                    else:
                        row["errors"][res.error_class] = (
                            row["errors"].get(res.error_class, 0) + 1
                        )
                        tl["errors"] += 1

        timers: list[threading.Timer] = []
        armed: dict[str, dict] = {}
        armed_lock = san_lock("ScenarioRunner.armed_lock")

        def arm(window_i: int, fault: dict, at_s: float, for_s: float) -> None:
            try:
                fid = self.admin.arm_fault(fault)
            except Exception as e:  # noqa: BLE001 - report, don't kill workers
                pr.chaos_windows.append(
                    {"at_s": at_s, "for_s": for_s, "fault": fault,
                     "error": f"{type(e).__name__}: {e}"[:200]}
                )
                return
            rec = {
                "at_s": at_s, "for_s": for_s, "fault": fault, "fault_id": fid,
                "armed_at_s": round(time.monotonic() - start, 3),
            }
            with armed_lock:
                armed[fid] = rec
            pr.chaos_windows.append(rec)
            t = threading.Timer(for_s, disarm, args=(fid,))
            t.daemon = True
            timers.append(t)
            t.start()

        def disarm(fid: str) -> None:
            with armed_lock:
                rec = armed.pop(fid, None)
            if rec is None:
                return
            try:
                self.admin.disarm_fault(fid)
                rec["disarmed_at_s"] = round(time.monotonic() - start, 3)
            except Exception as e:  # noqa: BLE001
                rec["error"] = f"disarm: {type(e).__name__}: {e}"[:200]

        def run_admin(admin_op: dict, at_s: float) -> None:
            # One-shot admin window (pool attach / decommission /
            # rebalance): fired once, never disarmed -- a drain keeps
            # running after the window by design.
            rec = {"at_s": at_s, "admin": admin_op}
            try:
                rec["result"] = self.admin.pool_admin(admin_op)
                rec["ran_at_s"] = round(time.monotonic() - start, 3)
            except Exception as e:  # noqa: BLE001 - report, don't kill workers
                rec["error"] = f"{type(e).__name__}: {e}"[:200]
            with armed_lock:
                pr.chaos_windows.append(rec)

        for wi_c, cw in enumerate(phase.chaos):
            if cw.admin is not None:
                t = threading.Timer(cw.at_s, run_admin, args=(cw.admin, cw.at_s))
            else:
                t = threading.Timer(
                    cw.at_s, arm, args=(wi_c, cw.fault, cw.at_s, cw.for_s)
                )
            t.daemon = True
            timers.append(t)
            t.start()

        threads = [
            threading.Thread(target=worker, args=(wi,), name=f"lg-{phase.name}-{wi}")
            for wi in range(phase.concurrency)
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            stop.set()
            for t in timers:
                t.cancel()
            for fid in list(armed):
                disarm(fid)
        pr.wall_s = time.monotonic() - start
        try:
            pr.cache = _cache_delta(cache_before, self.admin.cache_stats())
        except Exception:  # noqa: BLE001
            pr.cache = {}
        return pr

    # -- whole run ---------------------------------------------------------

    def run(self) -> dict:
        from .report import build_report

        sc = self.scenario
        if sc.profile:
            # Arm the continuous profiling plane before the clock starts so
            # the run's windows cover the measured phases.
            try:
                armed = self.admin.start_profile()
                self._log(f"profiling plane {'armed' if armed else 'UNAVAILABLE'}")
            except Exception:  # noqa: BLE001 - a live target may deny admin
                pass
        self._log(f"prepopulating {sc.prepopulate} objects into {sc.bucket!r}")
        self.prepopulate()
        # A clean measurement window: setup traffic must not pollute the
        # cluster stage breakdown the report attributes the run to.
        try:
            self.admin.reset_perf()
        except Exception:  # noqa: BLE001 - a live target may deny admin
            pass
        if sc.flight is not None:
            # Clear the recorder's cooldown/baselines so a trigger from a
            # previous run in this process can't mute the fault window.
            try:
                self.admin.flight_reset()
            except Exception:  # noqa: BLE001 - a live target may deny admin
                pass
        run_t0 = time.time()
        # Wall-clock phase windows: the flight gate correlates bundle
        # windows (wall clock, cluster-wide) against the faulted phase.
        phase_windows: dict[str, tuple[float, float]] = {}
        results: list[PhaseResult] = []
        for phase in sc.phases:
            self._log(
                f"phase {phase.name!r}: concurrency={phase.concurrency} "
                + (f"ops={phase.ops}" if phase.ops else f"duration={phase.duration_s}s")
            )
            w0 = time.time()
            results.append(self._run_phase(phase))
            phase_windows[phase.name] = (w0, time.time())
        try:
            stage_breakdown = self.admin.stage_breakdown()
        except Exception:  # noqa: BLE001
            stage_breakdown = {}
        try:
            degrade = self.admin.degrade()
        except Exception:  # noqa: BLE001
            degrade = {}
        try:
            cache = self.admin.cache_stats()
        except Exception:  # noqa: BLE001
            cache = {}
        profile = None
        if sc.profile:
            try:
                profile = self.admin.profile_summary() or None
            except Exception:  # noqa: BLE001
                profile = None
        pools_report = None
        if sc.pools_gate is not None:
            pools_report = self._await_drained(sc.pools_gate)
        flight_report = None
        if sc.flight is not None:
            flight_report = self._await_flight(sc.flight, phase_windows, run_t0)
        from ..control.sanitizer import profile_if_armed

        report = build_report(
            sc,
            results,
            stage_breakdown=stage_breakdown,
            degrade=degrade,
            probe_cached=bool(getattr(self.admin, "probe_cached", False)),
            lock_profile=profile_if_armed(),
            profile=profile,
            cache=cache,
        )
        if pools_report is not None:
            report["pools"] = pools_report
        if flight_report is not None:
            report["flight"] = flight_report
        return report

    def _await_flight(self, gate: dict, windows: dict, run_t0: float) -> dict:
        """Post-run flight gate: the faulted phase must have auto-captured a
        diagnostic bundle on EVERY node whose window covers the fault, and no
        bundle may have triggered outside it (a false alarm in a healthy
        phase is as much a bug as a missed incident). Waits off the
        measurement clock -- the trigger engine judges a second only after it
        closes, so the fault phase's bundle can land just after it ends."""
        phase = str(gate.get("phase", ""))
        max_s = float(gate.get("max_wait_s", 15.0))
        w0, w1 = windows.get(phase, (run_t0, run_t0))
        grace = 3.0  # closed-second judging + poll cadence + fanout
        expected = len(getattr(self.target, "urls", None) or []) or self.scenario.nodes
        t_start = time.monotonic()
        captured: list = []
        false_triggers: list = []
        nodes: set = set()
        while True:
            try:
                metas = self.admin.flight_bundles()
            except Exception:  # noqa: BLE001 - a live target may deny admin
                metas = []
            captured, false_triggers, nodes = [], [], set()
            for m in metas:
                win = m.get("window") or {}
                t1 = float(win.get("t1", 0.0))
                if t1 < run_t0:
                    continue  # stale bundle from an earlier run
                if w0 - 1.0 <= t1 <= w1 + grace:
                    captured.append(m)
                    nodes.add(m.get("node", ""))
                else:
                    false_triggers.append(m)
            if len(nodes) >= expected or time.monotonic() - t_start >= max_s:
                break
            time.sleep(0.25)
        ok = len(nodes) >= expected and not false_triggers
        out = {
            "phase": phase,
            "window": [w0, w1],
            "expected_nodes": expected,
            "nodes_captured": sorted(nodes),
            "bundles": captured,
            "false_triggers": false_triggers,
            "ok": ok,
        }
        if ok:
            self._log(
                f"flight gate: {len(captured)} bundle(s) across "
                f"{len(nodes)}/{expected} nodes for phase {phase!r}"
            )
        else:
            self._log(
                f"flight gate FAILED: {len(nodes)}/{expected} nodes captured, "
                f"{len(false_triggers)} false trigger(s)"
            )
        return out

    def _await_drained(self, gate: dict) -> dict:
        """Post-run pool gate: poll the pool-lifecycle status until every
        pool in require_drained reports 'decommissioned' (or max_drain_s
        runs out). The drain keeps working after the traffic stops, so the
        wait happens off the measurement clock."""
        require = list(gate.get("require_drained") or [])
        max_s = float(gate.get("max_drain_s", 120.0))
        t0 = time.monotonic()
        status: dict = {}
        drained = not require
        while not drained and time.monotonic() - t0 < max_s:
            try:
                status = self.admin.pool_status()
            except Exception:  # noqa: BLE001 - a live target may deny admin
                status = {}
            rows = {r.get("index"): r for r in status.get("pools", [])}
            drained = all(
                rows.get(pi, {}).get("status") == "decommissioned"
                for pi in require
            )
            if not drained:
                time.sleep(0.25)
        out = {
            "require_drained": require,
            "max_drain_s": max_s,
            "drained": drained,
            "ok": drained,
            "status": status,
        }
        if drained and require:
            out["time_to_drained_s"] = round(time.monotonic() - t0, 3)
            self._log(f"pools {require} drained in {out['time_to_drained_s']}s")
        elif require:
            self._log(f"pools {require} NOT drained within {max_s}s")
        return out
