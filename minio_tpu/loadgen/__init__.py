"""Scenario-driven workload replayer + SLO harness (ROADMAP item 5).

The "warp" analogue: declarative scenario specs (op mix, zipfian hot-set,
size distributions, concurrency ramp, optional mid-run chaos) replayed
against a real multi-node cluster -- in-process (tests, CI) or a live
endpoint -- with every op recorded into the control/perf.py bucket scheme
and a BENCH-style JSON report of per-op tails, throughput, error-budget
burn, stage breakdown, and degradation counters.

Layering: spec (parse + validate) -> generators (deterministic op
sequences) -> target (signed S3 ops + admin surfaces) -> cluster
(in-process multi-node harness) -> runner (drive it) -> report (judge it).
"""

from .generators import SizeDistribution, ZipfianGenerator, generate_ops, op_sequence_hash
from .report import build_report, evaluate_slo, render_prometheus
from .runner import ScenarioRunner
from .spec import Phase, Scenario, SpecError, load_scenario, parse_scenario

__all__ = [
    "Phase",
    "Scenario",
    "ScenarioRunner",
    "SizeDistribution",
    "SpecError",
    "ZipfianGenerator",
    "build_report",
    "evaluate_slo",
    "generate_ops",
    "load_scenario",
    "op_sequence_hash",
    "parse_scenario",
    "render_prometheus",
]
