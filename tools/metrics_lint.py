#!/usr/bin/env python3
"""Prometheus exposition-format validator + lint for the minio_tpu metrics.

Pure stdlib on purpose: the tier-1 suite runs this over /minio/v2/metrics/node
and /minio/v2/metrics/cluster output so the hand-rendered exposition in
control/metrics.py cannot silently regress (a scrape that Prometheus rejects
is observability that does not exist).

Checks (validate_exposition):
  * every line parses as a comment, HELP, TYPE, or `name[{labels}] value`
  * HELP/TYPE pairing: a family with HELP also declares TYPE (and vice
    versa), each at most once, before the family's first sample
  * no duplicate samples (same name + identical label set)
  * histograms: bucket counts are monotone over increasing `le`, the +Inf
    bucket exists and equals `_count`, `_sum` is present, and every label
    set of a family exposes the same bucket boundaries

Lints (lint_exposition):
  * duplicate series (a family declared or emitted under two TYPE lines)
  * unlabeled high-cardinality families: more samples than `max_series`
    with at least one unlabeled sample -- per-entity series must carry the
    entity as a label, not explode the name space

Usage:
    python tools/metrics_lint.py FILE [FILE...]   # or - for stdin
"""

from __future__ import annotations

import re
import sys

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)(?:\s+\d+)?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

# A histogram/summary sample's family is its name minus these suffixes.
_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


def _family(name: str) -> str:
    for suf in _FAMILY_SUFFIXES:
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def _parse_value(raw: str) -> float | None:
    if raw in ("+Inf", "Inf"):
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    try:
        return float(raw)
    except ValueError:
        return None


def parse_samples(text: str):
    """Yield (lineno, name, labels: dict, value: float) for sample lines."""
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        value = _parse_value(m.group("value"))
        if value is None:
            continue
        yield i, m.group("name"), labels, value


def validate_exposition(text: str) -> list[str]:
    """Return a list of format problems; empty means valid."""
    problems: list[str] = []
    help_names: dict[str, int] = {}
    type_names: dict[str, str] = {}
    samples_seen: dict[tuple[str, tuple[tuple[str, str], ...]], int] = {}
    family_started: set[str] = set()

    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                problems.append(f"line {i}: malformed HELP: {line!r}")
                continue
            name = parts[2]
            if name in help_names:
                problems.append(f"line {i}: duplicate HELP for {name}")
            if name in family_started:
                problems.append(f"line {i}: HELP for {name} after its samples")
            help_names[name] = i
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 4)
            if len(parts) < 4 or not _NAME_RE.match(parts[2]):
                problems.append(f"line {i}: malformed TYPE: {line!r}")
                continue
            name = parts[2]
            if name in type_names:
                problems.append(f"line {i}: duplicate TYPE for {name}")
            if name in family_started:
                problems.append(f"line {i}: TYPE for {name} after its samples")
            if parts[3] not in ("counter", "gauge", "histogram", "summary", "untyped"):
                problems.append(f"line {i}: unknown TYPE {parts[3]!r} for {name}")
            type_names[name] = parts[3]
            continue
        if line.startswith("#"):
            continue  # free-form comment
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {i}: unparseable sample: {line!r}")
            continue
        name = m.group("name")
        if _parse_value(m.group("value")) is None:
            problems.append(f"line {i}: bad value in: {line!r}")
            continue
        labels = tuple(sorted(_LABEL_RE.findall(m.group("labels") or "")))
        raw_labels = m.group("labels")
        if raw_labels and _LABEL_RE.sub("", raw_labels).strip(", ") != "":
            problems.append(f"line {i}: malformed label set: {line!r}")
        family_started.add(_family(name))
        key = (name, labels)
        if key in samples_seen:
            problems.append(
                f"line {i}: duplicate sample {name}{dict(labels)} "
                f"(first at line {samples_seen[key]})"
            )
        else:
            samples_seen[key] = i

    # HELP <-> TYPE pairing.
    for name in help_names:
        if name not in type_names:
            problems.append(f"{name}: HELP without TYPE")
    for name in type_names:
        if name not in help_names:
            problems.append(f"{name}: TYPE without HELP")

    problems.extend(_check_histograms(text, type_names))
    return problems


def _check_histograms(text: str, type_names: dict[str, str]) -> list[str]:
    problems: list[str] = []
    hist_families = {n for n, t in type_names.items() if t == "histogram"}
    # group: family -> series-labels-without-le -> {le: value}, _sum, _count
    buckets: dict[tuple[str, tuple], dict[float, float]] = {}
    sums: dict[tuple[str, tuple], float] = {}
    counts: dict[tuple[str, tuple], float] = {}
    for _, name, labels, value in parse_samples(text):
        fam = _family(name)
        if fam not in hist_families:
            continue
        base = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        if name.endswith("_bucket"):
            le = _parse_value(labels.get("le", ""))
            if le is None:
                problems.append(f"{fam}{dict(base)}: bucket without numeric le")
                continue
            buckets.setdefault((fam, base), {})[le] = value
        elif name.endswith("_sum"):
            sums[(fam, base)] = value
        elif name.endswith("_count"):
            counts[(fam, base)] = value
    for key, series in buckets.items():
        fam, base = key
        ordered = sorted(series.items())
        values = [v for _, v in ordered]
        if any(b > a for a, b in zip(values[1:], values)):
            problems.append(f"{fam}{dict(base)}: bucket counts not monotone")
        if float("inf") not in series:
            problems.append(f"{fam}{dict(base)}: missing +Inf bucket")
        elif key in counts and counts[key] != series[float("inf")]:
            problems.append(
                f"{fam}{dict(base)}: _count {counts[key]} != +Inf bucket "
                f"{series[float('inf')]}"
            )
        if key not in sums:
            problems.append(f"{fam}{dict(base)}: missing _sum")
        if key not in counts:
            problems.append(f"{fam}{dict(base)}: missing _count")
    # Bucket-boundary consistency: every label set of one histogram family
    # must expose the SAME le edges -- Prometheus aggregations across label
    # sets (sum by (le)) silently produce garbage on mixed boundaries.
    fam_edges: dict[str, tuple[tuple[float, ...], tuple]] = {}
    for (fam, base), series in sorted(buckets.items()):
        edges = tuple(sorted(series))
        first = fam_edges.get(fam)
        if first is None:
            fam_edges[fam] = (edges, base)
        elif first[0] != edges:
            problems.append(
                f"{fam}{dict(base)}: bucket boundaries differ from "
                f"{fam}{dict(first[1])} -- mixed le edges break aggregation"
            )
    return problems


def lint_exposition(text: str, max_series: int = 200) -> list[str]:
    """Style lints beyond format validity; empty means clean."""
    problems: list[str] = []
    fam_samples: dict[str, int] = {}
    fam_unlabeled: dict[str, int] = {}
    for _, name, labels, _v in parse_samples(text):
        fam = _family(name)
        fam_samples[fam] = fam_samples.get(fam, 0) + 1
        if not labels:
            fam_unlabeled[fam] = fam_unlabeled.get(fam, 0) + 1
    for fam, n in sorted(fam_samples.items()):
        if n > max_series and fam_unlabeled.get(fam):
            problems.append(
                f"{fam}: {n} series with {fam_unlabeled[fam]} unlabeled samples "
                f"-- high-cardinality metrics must carry the entity as a label"
            )
    # Families whose samples appear in two disjoint runs separated by another
    # family's TYPE line usually indicate a name collision between sections.
    type_lines: dict[str, int] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 3:
                if parts[2] in type_lines:
                    problems.append(
                        f"{parts[2]}: declared twice (lines {type_lines[parts[2]]} "
                        f"and {i}) -- duplicate series name"
                    )
                else:
                    type_lines[parts[2]] = i
    return problems


def main(argv: list[str]) -> int:
    paths = argv or ["-"]
    rc = 0
    for path in paths:
        text = sys.stdin.read() if path == "-" else open(path).read()
        problems = validate_exposition(text) + lint_exposition(text)
        for p in problems:
            print(f"{path}: {p}")
        if problems:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
