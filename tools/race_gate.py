"""Race gate: the `-race` story for this codebase (buildscripts/race.sh role).

Python has no ThreadSanitizer, but the same class of bug -- check-then-act
races between the quorum writers, the batching codec's worker threads, dsync
refresh loops, replication workers, and pubsub hubs -- surfaces reliably
under adversarial thread scheduling. This gate reruns the concurrency-
sensitive slice of the suite with:

  * sys.setswitchinterval(2e-6) (via MINIO_TPU_RACE=1 in tests/conftest.py),
    forcing a potential thread switch at nearly every bytecode boundary
    (~1000x the default 5 ms), and
  * several repetitions, since schedule-dependent bugs are probabilistic,
  * a per-run deadlock watchdog: pytest's faulthandler plugin dumps all
    thread stacks from INSIDE the hung process (faulthandler_timeout) well
    before the outer subprocess timeout SIGKILLs it, so a deadlock produces
    stacks, not a hung CI job.

The reference runs its entire suite under the Go race detector
(/root/reference/buildscripts/race.sh); here the full suite runs once in
normal mode (pytest) and this gate stresses the files where threads
actually interleave.

    python tools/race_gate.py [repeats]
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

# The concurrency-bearing slice is self-describing: any test file carrying
# `pytest.mark.race` (module-level `pytestmark = pytest.mark.race` or a
# per-test decorator) is picked up here automatically -- no hardcoded list
# to forget when a new concurrency suite lands. Discovery is textual so the
# gate never imports test modules outside pytest.
_RACE_MARK_RE = re.compile(r"pytest\.mark\.race\b")

TIMEOUT_S = int(os.environ.get("RACE_GATE_TIMEOUT_S", "1200"))


def discover_race_tests(root: str) -> list[str]:
    """tests/*.py files that mention pytest.mark.race, repo-relative."""
    tests_dir = os.path.join(root, "tests")
    found = []
    for name in sorted(os.listdir(tests_dir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(tests_dir, name), encoding="utf-8") as f:
            if _RACE_MARK_RE.search(f.read()):
                found.append(f"tests/{name}")
    return found


def main() -> int:
    repeats = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    race_tests = discover_race_tests(root)
    if not race_tests:
        print("[race-gate] no tests marked pytest.mark.race -- the gate "
              "would silently cover nothing", file=sys.stderr)
        return 2
    print(f"[race-gate] {len(race_tests)} marked file(s): {', '.join(race_tests)}")
    env = dict(os.environ, MINIO_TPU_RACE="1")
    failures = 0
    for i in range(repeats):
        t0 = time.time()
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "pytest",
                    "-q",
                    "-x",
                    # In-process stack dump fires before the outer SIGKILL,
                    # so a wedged run leaves evidence.
                    "-o",
                    f"faulthandler_timeout={max(60, TIMEOUT_S - 120)}",
                    *race_tests,
                ],
                cwd=root,
                env=env,
                timeout=TIMEOUT_S,
            )
            status = "ok" if proc.returncode == 0 else f"rc={proc.returncode}"
            failures += proc.returncode != 0
        except subprocess.TimeoutExpired:
            status = f"DEADLOCK? timed out after {TIMEOUT_S}s"
            failures += 1
        print(f"[race-gate] round {i + 1}/{repeats}: {status} ({time.time() - t0:.0f}s)")
    print(f"[race-gate] {'PASS' if not failures else 'FAIL'} ({repeats} rounds)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
