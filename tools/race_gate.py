"""Race gate: the `-race` story for this codebase (buildscripts/race.sh role).

Python has no ThreadSanitizer, but the same class of bug -- check-then-act
races between the quorum writers, the batching codec's worker threads, dsync
refresh loops, replication workers, and pubsub hubs -- surfaces reliably
under adversarial thread scheduling. This gate reruns the concurrency-
sensitive slice of the suite with:

  * sys.setswitchinterval(2e-6) (via MINIO_TPU_RACE=1 in tests/conftest.py),
    forcing a potential thread switch at nearly every bytecode boundary
    (~1000x the default 5 ms), and
  * several repetitions, since schedule-dependent bugs are probabilistic,
  * a per-run deadlock watchdog: pytest's faulthandler plugin dumps all
    thread stacks from INSIDE the hung process (faulthandler_timeout) well
    before the outer subprocess timeout SIGKILLs it, so a deadlock produces
    stacks, not a hung CI job.

The reference runs its entire suite under the Go race detector
(/root/reference/buildscripts/race.sh); here the full suite runs once in
normal mode (pytest) and this gate stresses the files where threads
actually interleave.

With --sanitize every round ALSO arms the mtpusan runtime sanitizer
(MTPU_TSAN=1, minio_tpu/control/sanitizer.py): lock-order-inversion
cycles, long holds, sleeps under locks, and teardown thread/fd leaks are
collected per round and gated against tools/mtpusan_baseline.txt -- the
lockdep side of the story, where this gate alone only catches races that
actually fire. The same rounds arm the bufsan buffer-lifetime sanitizer
(MTPU_BUFSAN=1, minio_tpu/control/bufsan.py): view-outlives-buffer,
write-after-release, double-release, and buffer-leak findings gate
against tools/bufsan_baseline.txt (which is kept empty -- buffer
lifetime bugs are a data-corruption class, not a backlog).

    python tools/race_gate.py [repeats] [--sanitize]
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

# The concurrency-bearing slice is self-describing: any test file carrying
# `pytest.mark.race` (module-level `pytestmark = pytest.mark.race` or a
# per-test decorator) is picked up here automatically -- no hardcoded list
# to forget when a new concurrency suite lands. Discovery is textual so the
# gate never imports test modules outside pytest.
_RACE_MARK_RE = re.compile(r"pytest\.mark\.race\b")

TIMEOUT_S = int(os.environ.get("RACE_GATE_TIMEOUT_S", "1200"))


def discover_race_tests(root: str) -> list[str]:
    """tests/*.py files that mention pytest.mark.race, repo-relative."""
    tests_dir = os.path.join(root, "tests")
    found = []
    for name in sorted(os.listdir(tests_dir)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(tests_dir, name), encoding="utf-8") as f:
            if _RACE_MARK_RE.search(f.read()):
                found.append(f"tests/{name}")
    return found


def main() -> int:
    argv = sys.argv[1:]
    sanitize = "--sanitize" in argv
    argv = [a for a in argv if a != "--sanitize"]
    repeats = int(argv[0]) if argv else 3
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    race_tests = discover_race_tests(root)
    if not race_tests:
        print("[race-gate] no tests marked pytest.mark.race -- the gate "
              "would silently cover nothing", file=sys.stderr)
        return 2
    print(f"[race-gate] {len(race_tests)} marked file(s): {', '.join(race_tests)}"
          + (" [sanitized]" if sanitize else ""))
    env = dict(os.environ, MINIO_TPU_RACE="1")
    san_reports: list[dict] = []
    bufsan_reports: list[dict] = []
    failures = 0
    for i in range(repeats):
        t0 = time.time()
        san_out = bufsan_out = ""
        if sanitize:
            import tempfile

            fd, san_out = tempfile.mkstemp(suffix=".json", prefix="mtpusan-")
            os.close(fd)
            fd, bufsan_out = tempfile.mkstemp(suffix=".json", prefix="bufsan-")
            os.close(fd)
            env = dict(env, MTPU_TSAN="1", MTPU_TSAN_OUT=san_out,
                       MTPU_BUFSAN="1", MTPU_BUFSAN_OUT=bufsan_out)
            env.setdefault("MTPU_TSAN_HOLD_MS", "400")
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "pytest",
                    "-q",
                    "-x",
                    # In-process stack dump fires before the outer SIGKILL,
                    # so a wedged run leaves evidence.
                    "-o",
                    f"faulthandler_timeout={max(60, TIMEOUT_S - 120)}",
                    *race_tests,
                ],
                cwd=root,
                env=env,
                timeout=TIMEOUT_S,
            )
            status = "ok" if proc.returncode == 0 else f"rc={proc.returncode}"
            failures += proc.returncode != 0
        except subprocess.TimeoutExpired:
            status = f"DEADLOCK? timed out after {TIMEOUT_S}s"
            failures += 1
        if sanitize:
            for label, path, sink in (
                ("mtpusan", san_out, san_reports),
                ("bufsan", bufsan_out, bufsan_reports),
            ):
                try:
                    with open(path, encoding="utf-8") as f:
                        rep = __import__("json").load(f)
                    sink.append(rep)
                    status += (f", {rep.get('unsuppressed', '?')} "
                               f"unsuppressed {label} finding(s)")
                except (OSError, ValueError):
                    status += f", NO {label} report (armed process died early?)"
                    failures += 1
                finally:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
        print(f"[race-gate] round {i + 1}/{repeats}: {status} ({time.time() - t0:.0f}s)")
    if sanitize and san_reports:
        failures += _gate_sanitizer(root, san_reports, "mtpusan_baseline.txt")
    if sanitize and bufsan_reports:
        failures += _gate_sanitizer(root, bufsan_reports, "bufsan_baseline.txt")
    print(f"[race-gate] {'PASS' if not failures else 'FAIL'} ({repeats} rounds)")
    return 1 if failures else 0


def _gate_sanitizer(root: str, reports: list[dict], baseline: str) -> int:
    """Merge per-round sanitizer findings, gate vs tools/<baseline>
    (mtpusan.py / bufsan.py own the heavier scenario flows; this is the
    suite-only gate)."""
    sys.path.insert(0, os.path.join(root, "tools"))
    from mtpulint.engine import Finding, apply_baseline, load_baseline

    seen: set[tuple[str, str]] = set()
    merged: list[Finding] = []
    for rep in reports:
        for f in rep.get("findings", []):
            if "suppressed" in f:
                continue
            key = (f.get("rule", "?"), f.get("site", "?"))
            if key not in seen:
                seen.add(key)
                merged.append(Finding(key[0], key[1], 0, f.get("message", "")))
    new, _stale = apply_baseline(
        merged, load_baseline(os.path.join(root, "tools", baseline))
    )
    for f in new:
        print(f"[race-gate] SANITIZER {f.rule} @ {f.relpath}: {f.message}",
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
