"""crashcheck: enumerate every crash point, die at each, restart, verify.

The CrashMonkey-style harness over the crash-consistency plane: for every
point in chaos/crash.py's KNOWN_POINTS registry it

  1. builds a fresh 8-drive erasure set and commits ACKED objects (one
     streaming PUT, one multipart) recording their digests,
  2. runs a VICTIM subprocess that arms the point (kill mode) and drives the
     matching workload until the process dies mid-operation (exit 137),
  3. runs a VERIFY subprocess -- a cold restart: fresh process builds over
     the same drives, runs the recovery scan, executes queued heals -- and
     asserts the durability invariants:

       * acked-durability:       every acked object reads back bit-identical
       * no-partial-visibility:  the un-acked victim object is either absent
                                 or complete and bit-identical -- never a
                                 readable prefix, never a quorum error
       * no-orphans:             a second recovery pass sweeps nothing, and
                                 no stage/tmp debris survives anywhere on
                                 the drives
       * no-leaked-buffers:      a fresh PUT+GET leaves window_pool with
                                 zero outstanding buffers
       * quorum-after-heal:      versions the scan queued for heal end up on
                                 every drive

Crash model: the victim dies by os._exit -- kernel state (page cache,
completed writes) survives, process state (buffers, locks, threads) is
lost. That is exactly worker/process death and kill -9; it validates
commit-protocol ORDERING and ATOMICITY, not power-loss (which would also
need the fsync barriers MTPU_FSYNC=commit adds -- those are exercised here
too, but a page-cache-dropping power cut cannot be simulated in-process).

    python tools/crashcheck.py             # full enumeration (chaos_check --invariants)
    python tools/crashcheck.py --smoke     # 3-point tier-1 slice
    python tools/crashcheck.py --json      # machine-readable summary
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import shutil
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MINIO_TPU_CODEC", "host")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

N_DISKS = 8
PARITY = 2  # k=6, write quorum 6
CRASH_EXIT = 137
VICTIM_TIMEOUT_S = 120
VERIFY_TIMEOUT_S = 180

# Points whose firing site repeats per drive: skip a couple of fan-out hits
# so the death leaves genuinely partial cross-drive state.
_SKIP = {
    "put.mid-commit": 2,
    "multipart.part.published": 2,
    "multipart.complete.partial": 2,
    "storage.rename-data.pre-meta": 2,
    "storage.xlmeta.pre-replace": 2,
    "storage.append-iov.torn": 2,
}

_MODE = {"storage.append-iov.torn": "torn-kill"}

SMOKE_POINTS = ("put.after-stage", "put.mid-commit", "storage.append-iov.torn")

ACKED_PUT = ("b", "acked/put")
ACKED_MPU = ("b", "acked/mpu")
VICTIM_PUT = ("b", "crash/victim")


def _payload(tag: str, size: int) -> bytes:
    return random.Random(tag).randbytes(size)


def _build_layer(dirs):
    from minio_tpu.object.erasure import ErasureObjects
    from minio_tpu.storage.local import LocalDrive

    return ErasureObjects([LocalDrive(d) for d in dirs], parity=PARITY)


def _make_drives(work: str) -> list[str]:
    from minio_tpu.storage import format as fmt

    dirs = [os.path.join(work, f"disk{i}") for i in range(N_DISKS)]
    for d, f in zip(dirs, fmt.init_format(1, N_DISKS)):
        os.makedirs(d, exist_ok=True)
        f.save(d)
    return dirs


def _setup(work: str) -> dict:
    """Commit the acked objects and record their ground truth."""
    dirs = _make_drives(work)
    eo = _build_layer(dirs)
    eo.make_bucket("b")
    put_data = _payload("acked-put", 3 * (1 << 20) + 4097)
    eo.put_object(ACKED_PUT[0], ACKED_PUT[1], put_data)

    from minio_tpu.object.multipart import MultipartManager

    mp = MultipartManager(eo)
    p1 = _payload("acked-mpu-1", 5 * (1 << 20))
    p2 = _payload("acked-mpu-2", 1 << 20)
    uid = mp.new_multipart_upload(ACKED_MPU[0], ACKED_MPU[1])
    e1 = mp.put_object_part(ACKED_MPU[0], ACKED_MPU[1], uid, 1, p1).etag
    e2 = mp.put_object_part(ACKED_MPU[0], ACKED_MPU[1], uid, 2, p2).etag
    mp.complete_multipart_upload(ACKED_MPU[0], ACKED_MPU[1], uid, [(1, e1), (2, e2)])

    state = {
        "dirs": dirs,
        "acked": {
            "/".join(ACKED_PUT): hashlib.sha256(put_data).hexdigest(),
            "/".join(ACKED_MPU): hashlib.sha256(p1 + p2).hexdigest(),
        },
    }
    with open(os.path.join(work, "state.json"), "w") as f:
        json.dump(state, f)
    return state


# ---------------------------------------------------------------------------
# child: victim
# ---------------------------------------------------------------------------


def _victim_main(work: str, point: str, mode: str, skip: int) -> int:
    """Arm the point, drive the matching workload, die mid-flight. Returning
    at all means the point never fired -- the parent treats exit 3 as that
    failure."""
    from minio_tpu.chaos.crash import REGISTRY, CrashSpec

    with open(os.path.join(work, "state.json")) as f:
        dirs = json.load(f)["dirs"]
    eo = _build_layer(dirs)

    REGISTRY.arm(CrashSpec(point=point, mode=mode, skip=skip, seed=7))

    if point.startswith(("put.", "storage.")):
        eo.put_object(VICTIM_PUT[0], VICTIM_PUT[1], _payload("victim", 3 * (1 << 20) + 11))
        return 3
    from minio_tpu.object.multipart import MultipartManager

    mp = MultipartManager(eo)
    b, o = VICTIM_PUT
    if point.startswith("multipart.part."):
        uid = mp.new_multipart_upload(b, o)
        with open(os.path.join(work, "victim_upload.json"), "w") as f:
            json.dump({"upload_id": uid}, f)
        mp.put_object_part(b, o, uid, 1, _payload("victim-part", 5 * (1 << 20)))
        return 3
    # multipart.complete.*: full upload, crash inside complete's fan-out.
    uid = mp.new_multipart_upload(b, o)
    e1 = mp.put_object_part(b, o, uid, 1, _payload("victim-1", 5 * (1 << 20))).etag
    e2 = mp.put_object_part(b, o, uid, 2, _payload("victim-2", 1 << 20)).etag
    mp.complete_multipart_upload(b, o, uid, [(1, e1), (2, e2)])
    return 3


# ---------------------------------------------------------------------------
# child: verify (the cold restart)
# ---------------------------------------------------------------------------


def _scan_debris(dirs) -> list[str]:
    """Paths of anything recovery should have removed: stage/tmp files and
    non-empty tmp/ trees."""
    out = []
    for d in dirs:
        tmp_root = os.path.join(d, ".minio_tpu.sys", "tmp")
        for dirpath, _dn, files in os.walk(tmp_root):
            for n in files:
                out.append(os.path.join(dirpath, n))
        for dirpath, _dn, files in os.walk(d):
            if dirpath.startswith(tmp_root):
                continue
            for n in files:
                if ".tmp" in n:
                    out.append(os.path.join(dirpath, n))
    return out


def _verify_main(work: str, point: str) -> int:
    from minio_tpu.storage import recovery
    from minio_tpu.storage.local import LocalDrive
    from minio_tpu.utils import errors
    from minio_tpu.utils.bufpool import window_pool

    with open(os.path.join(work, "state.json")) as f:
        state = json.load(f)
    dirs = state["dirs"]
    failures: list[str] = []

    # -- restart recovery: per-drive sweep, then cross-drive reconcile ------
    for d in dirs:
        recovery.recover_drive(LocalDrive(d))
    eo = _build_layer(dirs)
    heal_q: list[tuple] = []
    recovery.recover_set(eo, heal=lambda b, o, v: heal_q.append((b, o, v)))
    for b, o, v in heal_q:
        try:
            eo.heal_object(b, o, version_id=v)
        except errors.StorageError as e:
            failures.append(f"quorum-after-heal: heal({b}/{o}) raised {type(e).__name__}: {e}")

    # -- acked-durability ---------------------------------------------------
    for key, digest in state["acked"].items():
        bucket, obj = key.split("/", 1)
        try:
            _oi, body = eo.get_object(bucket, obj)
        except errors.StorageError as e:
            failures.append(f"acked-durability: GET {key} raised {type(e).__name__}: {e}")
            continue
        if hashlib.sha256(body).hexdigest() != digest:
            failures.append(f"acked-durability: {key} read back different bytes")

    # -- no-partial-visibility (the victim object) --------------------------
    if point.startswith(("put.", "storage.")):
        want = hashlib.sha256(_payload("victim", 3 * (1 << 20) + 11)).hexdigest()
    else:
        want = hashlib.sha256(
            _payload("victim-1", 5 * (1 << 20)) + _payload("victim-2", 1 << 20)
        ).hexdigest()
    if not point.startswith("multipart.part."):
        try:
            _oi, body = eo.get_object(VICTIM_PUT[0], VICTIM_PUT[1])
            if hashlib.sha256(body).hexdigest() != want:
                failures.append("no-partial-visibility: victim readable but NOT bit-identical")
        except errors.ObjectNotFound:
            pass  # absent is the other legal outcome
        except errors.StorageError as e:
            failures.append(
                f"no-partial-visibility: victim GET must succeed or be absent, "
                f"got {type(e).__name__}: {e}"
            )
    else:
        # Part-level crash: the upload must still be listable and hold no
        # partially published part (a part with shards but no .meta is
        # invisible to list_parts by design; the stage files must be gone --
        # the no-orphan check below proves that).
        from minio_tpu.object.multipart import MultipartManager

        try:
            with open(os.path.join(work, "victim_upload.json")) as f:
                uid = json.load(f)["upload_id"]
            MultipartManager(eo).list_parts(VICTIM_PUT[0], VICTIM_PUT[1], uid)
        except errors.StorageError as e:
            failures.append(f"no-partial-visibility: list_parts raised {type(e).__name__}: {e}")

    # -- quorum-after-heal: every healed/acked version on every drive -------
    for key in state["acked"]:
        bucket, obj = key.split("/", 1)
        holders = sum(
            1 for d in dirs
            if os.path.isfile(os.path.join(d, bucket, obj, "xl.meta"))
        )
        if holders != len(dirs):
            failures.append(f"quorum-after-heal: {key} xl.meta on {holders}/{len(dirs)} drives")

    # -- no-orphans: a second pass must find nothing ------------------------
    recovery.reset_counters()
    for d in dirs:
        recovery.recover_drive(LocalDrive(d))
    second = recovery.counters()
    swept = {k: v for k, v in second.items() if v and k not in ("scans",)}
    if swept:
        failures.append(f"no-orphans: second recovery pass still swept {swept}")
    debris = _scan_debris(dirs)
    if debris:
        failures.append(f"no-orphans: debris survived recovery: {debris[:5]}")

    # -- no-leaked-buffers: data plane healthy, pool drained ----------------
    probe = _payload("probe", 2 * (1 << 20))
    eo.put_object("b", "post/probe", probe)
    _oi, body = eo.get_object("b", "post/probe")
    if hashlib.sha256(body).hexdigest() != hashlib.sha256(probe).hexdigest():
        failures.append("post-restart PUT/GET roundtrip corrupt")
    n_out = window_pool().outstanding()
    if n_out:
        failures.append(f"no-leaked-buffers: window_pool outstanding={n_out}")

    print(json.dumps({"point": point, "failures": failures}))
    return 1 if failures else 0


# ---------------------------------------------------------------------------
# parent
# ---------------------------------------------------------------------------


def _child_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("MINIO_TPU_CODEC", "host")
    env.pop("MTPU_CRASH", None)
    return env


def _run_point(point: str, base: str) -> dict:
    work = os.path.join(base, point.replace(".", "_"))
    os.makedirs(work, exist_ok=True)
    result = {"point": point, "ok": False, "victim_exit": None, "failures": []}
    try:
        _setup(work)
    except Exception as e:  # noqa: BLE001 - setup failure is a result, not a crash
        result["failures"] = [f"setup failed: {type(e).__name__}: {e}"]
        return result

    mode = _MODE.get(point, "kill")
    skip = _SKIP.get(point, 0)
    victim = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "victim",
         "--work", work, "--point", point, "--mode", mode, "--skip", str(skip)],
        cwd=_ROOT, env=_child_env(), timeout=VICTIM_TIMEOUT_S,
        capture_output=True, text=True,
    )
    result["victim_exit"] = victim.returncode
    if victim.returncode != CRASH_EXIT:
        why = "point never fired" if victim.returncode == 3 else "unexpected exit"
        result["failures"] = [
            f"victim: {why} (exit {victim.returncode}); stderr tail: "
            f"{victim.stderr.strip()[-400:]}"
        ]
        return result

    verify = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "verify",
         "--work", work, "--point", point],
        cwd=_ROOT, env=_child_env(), timeout=VERIFY_TIMEOUT_S,
        capture_output=True, text=True,
    )
    try:
        doc = json.loads(verify.stdout.strip().splitlines()[-1])
        result["failures"] = doc["failures"]
    except (ValueError, IndexError, KeyError):
        result["failures"] = [
            f"verify crashed (exit {verify.returncode}); stderr tail: "
            f"{verify.stderr.strip()[-400:]}"
        ]
    result["ok"] = verify.returncode == 0 and not result["failures"]
    if result["ok"]:
        shutil.rmtree(work, ignore_errors=True)
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="tier-1 slice (3 points)")
    ap.add_argument("--json", action="store_true", help="JSON summary to stdout")
    ap.add_argument("--point", default="", help="run a single named point")
    ap.add_argument("--keep", action="store_true", help="keep workdirs of passing points")
    ap.add_argument("--child", choices=("victim", "verify"), help=argparse.SUPPRESS)
    ap.add_argument("--work", default="", help=argparse.SUPPRESS)
    ap.add_argument("--mode", default="kill", help=argparse.SUPPRESS)
    ap.add_argument("--skip", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child == "victim":
        return _victim_main(args.work, args.point, args.mode, args.skip)
    if args.child == "verify":
        return _verify_main(args.work, args.point)

    from minio_tpu.chaos.crash import KNOWN_POINTS

    points = list(KNOWN_POINTS)
    if args.smoke:
        points = list(SMOKE_POINTS)
    if args.point:
        if args.point not in KNOWN_POINTS:
            print(f"unknown point {args.point!r}", file=sys.stderr)
            return 2
        points = [args.point]

    import tempfile

    base = tempfile.mkdtemp(prefix="crashcheck-")
    results = []
    for point in points:
        r = _run_point(point, base)
        results.append(r)
        if not args.json:
            mark = "PASS" if r["ok"] else "FAIL"
            print(f"[{mark}] {point} (victim exit {r['victim_exit']})")
            for f in r["failures"]:
                print(f"    - {f}")
    n_fail = sum(1 for r in results if not r["ok"])
    if args.json:
        print(json.dumps({"points": results, "failed": n_fail}, indent=2))
    else:
        print(f"crashcheck: {len(results) - n_fail}/{len(results)} points pass")
    if n_fail == 0 and not args.keep:
        shutil.rmtree(base, ignore_errors=True)
    elif n_fail:
        print(f"crashcheck: failing workdirs kept under {base}", file=sys.stderr)
    return 1 if n_fail else 0


if __name__ == "__main__":
    sys.exit(main())
