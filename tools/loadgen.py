#!/usr/bin/env python3
"""Run a loadgen scenario against an in-process cluster or a live endpoint.

Usage:
    python tools/loadgen.py scenarios/mixed_70_30.yaml
    python tools/loadgen.py SPEC --endpoint http://host:9000 \\
        --access-key AK --secret-key SK
    python tools/loadgen.py SPEC --out report.json --metrics-out report.prom

Without --endpoint, a real multi-node cluster (shape from the spec's
`cluster` block, overridable with --nodes/--drives) is built in-process on
temp-dir drives, driven, and torn down. The final stdout line is the whole
report as ONE JSON object (the BENCH contract: tools/perf_gate.py --slo
consumes it); --out additionally writes it pretty-printed.

Exit 0: ran and every declared SLO held. Exit 1: ran but an SLO was
violated (or the compare block failed to reproduce). Exit 2: could not
run (bad spec, cluster failed to build).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _log(msg: str) -> None:
    print(f"loadgen: {msg}", file=sys.stderr)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("spec", help="scenario YAML/JSON path")
    ap.add_argument("--endpoint", action="append", default=[],
                    help="live S3 endpoint URL (repeatable for multi-node)")
    ap.add_argument("--access-key", default="")
    ap.add_argument("--secret-key", default="")
    ap.add_argument("--nodes", type=int, default=0, help="override spec cluster.nodes")
    ap.add_argument("--drives", type=int, default=0,
                    help="override spec cluster.drives_per_node")
    ap.add_argument("--seed", type=int, default=None, help="override spec seed")
    ap.add_argument("--profile", action="store_true",
                    help="arm the continuous profiling plane and embed its "
                         "summary (gil_load, role stacks, copy ledger) in "
                         "the report (same as `profile: true` in the spec)")
    ap.add_argument("--out", default="", help="write pretty report JSON here")
    ap.add_argument("--metrics-out", default="",
                    help="write Prometheus exposition of the report here")
    args = ap.parse_args(argv)

    # Satellite knobs: cache the device-probe verdict across runs (no
    # re-paying a 180 s init wedge per invocation), and sample trace-span
    # publication so high concurrency doesn't flood the hub/slow-ring
    # (the perf ledger still sees every request).
    os.environ.setdefault(
        "MTPU_PROBE_CACHE", os.path.join(tempfile.gettempdir(), "mtpu_probe_cache.json")
    )
    os.environ.setdefault("MTPU_TRACE_SAMPLE", "0.1")

    from minio_tpu.loadgen.runner import ScenarioRunner
    from minio_tpu.loadgen.spec import SpecError, load_scenario
    from minio_tpu.loadgen.target import EndpointAdmin, InProcessAdmin, S3Target

    try:
        scenario = load_scenario(args.spec)
    except SpecError as e:
        _log(f"bad spec: {e}")
        return 2
    if args.seed is not None:
        scenario.seed = args.seed
    if args.nodes:
        scenario.nodes = args.nodes
    if args.drives:
        scenario.drives_per_node = args.drives
    if args.profile:
        scenario.profile = True

    cluster = None
    workdir = ""
    try:
        if args.endpoint:
            if not args.access_key or not args.secret_key:
                _log("--endpoint needs --access-key and --secret-key")
                return 2
            target = S3Target(args.endpoint, args.access_key, args.secret_key)
            admin = EndpointAdmin(target)
            _log(f"target: live endpoint(s) {args.endpoint}")
        else:
            from minio_tpu.loadgen.cluster import InProcessCluster

            # Spec-declared env knobs (e.g. MTPU_MEMCACHE_MB for the hot-read
            # tier) must be live before the nodes build. setdefault: the
            # operator's explicit environment wins over the spec.
            for k, v in scenario.env.items():
                os.environ.setdefault(k, v)
            workdir = tempfile.mkdtemp(prefix="mtpu-loadgen-")
            _log(
                f"building in-process cluster: {scenario.nodes} nodes x "
                f"{scenario.drives_per_node} drives x {scenario.pools} pool(s) "
                f"under {workdir}"
            )
            try:
                cluster = InProcessCluster(
                    workdir, scenario.nodes, scenario.drives_per_node,
                    pools=scenario.pools,
                )
            except RuntimeError as e:
                _log(str(e))
                return 2
            target = S3Target(cluster.urls, cluster.root_user, cluster.root_password)
            admin = InProcessAdmin(cluster)

        report = ScenarioRunner(scenario, target, admin, log=_log).run()

        from minio_tpu.runtime import probe_status

        probe = probe_status()
        if probe is not None:
            report["probe_cached"] = probe.cached
    finally:
        if cluster is not None:
            cluster.stop()
        if workdir:
            shutil.rmtree(workdir, ignore_errors=True)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        _log(f"report written to {args.out}")
    if args.metrics_out:
        from minio_tpu.loadgen.report import render_prometheus

        with open(args.metrics_out, "w") as f:
            f.write(render_prometheus(report))
        _log(f"metrics written to {args.metrics_out}")

    print(json.dumps(report, sort_keys=True))

    slo_ok = all(
        row.get("ok", True) for row in report.get("slo", {}).values()
    )
    cmp = report.get("compare")
    cmp_blocks = cmp if isinstance(cmp, list) else [cmp] if isinstance(cmp, dict) else []
    cmp_ok = all(b.get("reproduced", True) for b in cmp_blocks)
    loss = report.get("acked_object_loss")
    loss_ok = loss.get("ok", True) if isinstance(loss, dict) else True
    cache_slo = report.get("cache_slo")
    cache_ok = cache_slo.get("ok", True) if isinstance(cache_slo, dict) else True
    pools_blk = report.get("pools")
    pools_ok = pools_blk.get("ok", True) if isinstance(pools_blk, dict) else True
    if not slo_ok:
        _log("SLO VIOLATED (see report.slo)")
    if not cmp_ok:
        _log("compare block did not reproduce (see report.compare)")
    if not loss_ok:
        _log(
            f"ACKED OBJECT LOSS: {loss.get('get_miss_count')} GET(s) hit "
            "NoSuchKey on a prepopulated, never-deleted key"
        )
    if not cache_ok:
        _log("cache hit-ratio promise missed (see report.cache_slo)")
    if not pools_ok:
        _log(
            f"pool(s) {pools_blk.get('require_drained')} did not drain within "
            f"{pools_blk.get('max_drain_s')}s (see report.pools)"
        )
    return 0 if slo_ok and cmp_ok and loss_ok and cache_ok and pools_ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
