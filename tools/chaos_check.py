"""Chaos gate: run the fault-injection scenario matrix (tests/chaos_scenarios.py).

The analogue of the reference's verify-healing.sh / verify-resiliency CI legs:
exercise the deterministic fault plane (minio_tpu/chaos/) end to end -- drives
dying mid-PUT, shards corrupted at rest, peers partitioned during multipart
commit, lock servers dropping quorum mid-hold -- and assert the recovery
invariants (quorum reads, MRF re-drive, heal convergence, bit-identical reads
after heal).

    python tools/chaos_check.py               # full matrix, including `slow`
    python tools/chaos_check.py --fast        # tier-1 smoke slice only
    python tools/chaos_check.py --invariants  # degradation slice: breaker /
                                              # hedged-read / deadline scenarios

Exit status is pytest's, so this drops straight into CI. Scenarios are
collected from the scenario file directly (pytest accepts an explicit path
regardless of its test-file naming convention). Before any scenario runs,
the deadline-propagation lint (tools/deadline_lint.py) gates the tree: a
hop that lost the budget plumbing fails here, not in a live cluster. With
--invariants the FULL mtpulint rule set runs first, which since the mtpusan
work includes the concurrency rules (lock-order, unjoined-thread,
cond-wait-loop, shared-publish) -- the static half of what the runtime
sanitizer (tools/mtpusan.py, MTPU_TSAN=1) checks dynamically -- and since
the bufsan work the buffer-lifetime rules (release-on-all-paths,
double-release, view-escape, interface-conformance), whose runtime half
(tools/bufsan.py --smoke, MTPU_BUFSAN=1) replays a sanitized smoke
scenario right after.
"""

from __future__ import annotations

import os
import subprocess
import sys

TIMEOUT_S = int(os.environ.get("CHAOS_CHECK_TIMEOUT_S", "900"))


def main() -> int:
    flags = {"--fast", "--invariants"}
    fast = "--fast" in sys.argv[1:]
    invariants = "--invariants" in sys.argv[1:]
    extra = [a for a in sys.argv[1:] if a not in flags]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    # Static gate first: the recovery scenarios assume the deadline rides
    # every hop; don't burn minutes of chaos on a tree that already lost it.
    from deadline_lint import main as lint_main

    rc = lint_main()
    if rc != 0:
        return rc

    if invariants:
        # The degradation slice leans on every project invariant, not just
        # the deadline ones: run the full mtpulint rule set over the tree.
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mtpulint", "minio_tpu"], cwd=root
        )
        if proc.returncode != 0:
            return proc.returncode
        # Buffer-lifetime gate (tools/bufsan.py --smoke): the static buffer
        # rules again (redundant with mtpulint above, cheap) PLUS one
        # sanitized smoke replay with MTPU_BUFSAN=1 -- sentinel poisoning,
        # view-export probes, and leak tracking against live pool traffic,
        # gated on the (empty) tools/bufsan_baseline.txt.
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "bufsan.py"), "--smoke"],
            cwd=root,
        )
        if proc.returncode != 0:
            return proc.returncode
        # Flight-bundle gate (tools/flight_check.py --selftest): exercise
        # the recorder's write -> schema-validate -> retention-prune cycle
        # in a temp dir. Needs no pre-existing incident, so it runs (and
        # means something) on every invocation.
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "flight_check.py"),
             "--selftest"],
            cwd=root,
        )
        if proc.returncode != 0:
            return proc.returncode
        # Crash-consistency gate: enumerate every registered crash point,
        # kill at each, restart, verify the durability invariants
        # (tools/crashcheck.py). The full enumeration lives here; tier-1
        # runs the --smoke slice via tests/test_crash.py.
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join("tools", "crashcheck.py")],
                cwd=root, timeout=TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            print(f"chaos_check: crashcheck timed out after {TIMEOUT_S}s", file=sys.stderr)
            return 124
        if proc.returncode != 0:
            return proc.returncode
        # Live-vs-offline gate (tools/selftest_gate.py): when both a saved
        # object-speedtest report and a BENCH line exist, hold the live
        # cluster's numbers to the offline harness. Both artifacts are
        # produced out-of-band (an admin POST, a bench run), so absence is
        # a skip, not a failure.
        import glob

        speedtests = sorted(glob.glob(os.path.join(root, "SPEEDTEST_*.json")))
        benches = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
        if speedtests and benches:
            proc = subprocess.run(
                [sys.executable, os.path.join("tools", "selftest_gate.py"),
                 speedtests[-1], benches[-1]],
                cwd=root,
            )
            if proc.returncode == 1:
                return proc.returncode
            # rc 2 = unusable artifact: the gate can't vouch; don't block.
        else:
            print("chaos_check: no SPEEDTEST_*.json + BENCH_*.json pair; "
                  "selftest gate skipped")

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable, "-m", "pytest", "-q",
        "-p", "no:cacheprovider", "-p", "no:randomly",
        os.path.join("tests", "chaos_scenarios.py"),
    ]
    if fast:
        cmd += ["-m", "not slow"]
    if invariants:
        cmd += ["-k", "breaker or hedged or deadline or Hedged or Breaker "
                      "or Deadline or decommission or Decommission"]
    cmd += extra
    try:
        proc = subprocess.run(cmd, cwd=root, env=env, timeout=TIMEOUT_S)
    except subprocess.TimeoutExpired:
        print(f"chaos_check: timed out after {TIMEOUT_S}s", file=sys.stderr)
        return 124
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
