"""Chaos gate: run the fault-injection scenario matrix (tests/chaos_scenarios.py).

The analogue of the reference's verify-healing.sh / verify-resiliency CI legs:
exercise the deterministic fault plane (minio_tpu/chaos/) end to end -- drives
dying mid-PUT, shards corrupted at rest, peers partitioned during multipart
commit, lock servers dropping quorum mid-hold -- and assert the recovery
invariants (quorum reads, MRF re-drive, heal convergence, bit-identical reads
after heal).

    python tools/chaos_check.py           # full matrix, including `slow`
    python tools/chaos_check.py --fast    # tier-1 smoke slice only

Exit status is pytest's, so this drops straight into CI. Scenarios are
collected from the scenario file directly (pytest accepts an explicit path
regardless of its test-file naming convention).
"""

from __future__ import annotations

import os
import subprocess
import sys

TIMEOUT_S = int(os.environ.get("CHAOS_CHECK_TIMEOUT_S", "900"))


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    extra = [a for a in sys.argv[1:] if a != "--fast"]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable, "-m", "pytest", "-q",
        "-p", "no:cacheprovider", "-p", "no:randomly",
        os.path.join("tests", "chaos_scenarios.py"),
    ]
    if fast:
        cmd += ["-m", "not slow"]
    cmd += extra
    try:
        proc = subprocess.run(cmd, cwd=root, env=env, timeout=TIMEOUT_S)
    except subprocess.TimeoutExpired:
        print(f"chaos_check: timed out after {TIMEOUT_S}s", file=sys.stderr)
        return 124
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
