#!/usr/bin/env python3
"""Live-vs-offline gate over object-speedtest JSON (control/selftest.py).

The offline harness (bench.py) says what the machine CAN do; the live
cluster's object speedtest (POST /mtpu/admin/v1/speedtest/object) says what
it actually delivers with auth, dispatch, peers, and production drive
stacks in the path. This gate holds the two to each other:

  * throughput floor -- the live cluster's aggregate PUT GiB/s must be at
    least `--factor` of the latest BENCH line's `putobject_gibs` (default
    0.1: the live path carries per-request overhead an in-process bench
    never pays, but an order-of-magnitude collapse means a real bottleneck
    -- a dead codec, a wedged drive, an accidental serial path).
  * scaling floor (N>1 only) -- the speedtest's own scaling-efficiency
    verdict (aggregate / (N x best single node)) must clear
    `--efficiency-floor` (default 0.5): nodes that add no throughput are a
    topology bug, not capacity.
  * probe health -- a speedtest that reports ok=false (a node's round
    failed) can vouch for nothing.

Inputs are files whose LAST JSON-object line is the report (the speedtest
JSON saved from the admin endpoint; BENCH_*.json as bench.py writes it).

Usage:
    python tools/selftest_gate.py SPEEDTEST.json BENCH.json \\
        [--factor=0.1] [--efficiency-floor=0.5]

Exit 0 = live numbers hold up, 1 = violation(s) flagged, 2 = unusable
input (the gate cannot vouch either way; callers decide whether that
blocks). chaos_check --invariants runs this automatically when both
artifacts exist.
"""

from __future__ import annotations

import json
import sys

DEFAULT_FACTOR = 0.1
DEFAULT_EFFICIENCY_FLOOR = 0.5


def findings(speedtest: dict, bench: dict, factor: float = DEFAULT_FACTOR,
             efficiency_floor: float = DEFAULT_EFFICIENCY_FLOOR) -> list[dict]:
    """Violations of the live-vs-offline contract; empty means it holds."""
    out: list[dict] = []
    if not speedtest.get("ok", False):
        failed = [
            url for url, r in (speedtest.get("nodes") or {}).items()
            if not r.get("ok")
        ]
        out.append({"kind": "probe-failed", "nodes": failed})
        return out  # failed rounds make the numbers below meaningless
    agg = speedtest.get("aggregate") or {}
    live_put = float(agg.get("put_gibs", 0.0))
    bench_put = float(bench.get("putobject_gibs", 0.0))
    if bench_put > 0 and live_put < bench_put * factor:
        out.append({
            "kind": "throughput-floor",
            "live_put_gibs": live_put,
            "bench_put_gibs": bench_put,
            "factor": factor,
        })
    scaling = speedtest.get("scaling") or {}
    n = int(scaling.get("nodes", 1))
    eff = float(scaling.get("efficiency", 0.0))
    if n > 1 and eff < efficiency_floor:
        out.append({
            "kind": "efficiency-floor",
            "nodes": n,
            "efficiency": eff,
            "floor": efficiency_floor,
            "verdict": scaling.get("verdict", ""),
        })
    return out


def _load(path: str) -> dict | None:
    """Last parseable JSON object line of a file (same contract as
    perf_gate: BENCH logs are JSONL, the final line is the report)."""
    try:
        with open(path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except OSError as e:
        print(f"selftest_gate: {path}: {e}", file=sys.stderr)
        return None
    for ln in reversed(lines):
        try:
            doc = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict):
            return doc
    print(f"selftest_gate: {path}: no JSON object line", file=sys.stderr)
    return None


def main(argv: list[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    factor = DEFAULT_FACTOR
    floor = DEFAULT_EFFICIENCY_FLOOR
    for a in argv:
        if a.startswith("--factor="):
            factor = float(a.split("=", 1)[1])
        elif a.startswith("--efficiency-floor="):
            floor = float(a.split("=", 1)[1])
    if len(args) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    speedtest, bench = _load(args[0]), _load(args[1])
    if speedtest is None or bench is None:
        return 2
    if "aggregate" not in speedtest or "putobject_gibs" not in bench:
        print("selftest_gate: inputs lack aggregate/putobject_gibs; "
              "nothing to gate", file=sys.stderr)
        return 2
    found = findings(speedtest, bench, factor, floor)
    for f in found:
        if f["kind"] == "probe-failed":
            print(f"PROBE FAILED on nodes: {', '.join(f['nodes']) or 'unknown'}")
        elif f["kind"] == "throughput-floor":
            print(f"LIVE FLOOR: {f['live_put_gibs']:.3f} GiB/s live PUT < "
                  f"{f['factor']:.2f} x bench {f['bench_put_gibs']:.3f} GiB/s")
        else:
            print(f"SCALING FLOOR: efficiency {f['efficiency']:.3f} "
                  f"({f['verdict']}) < {f['floor']:.2f} across {f['nodes']} nodes")
    if not found:
        print("selftest_gate: ok")
    return 1 if found else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
