#!/usr/bin/env python3
"""Stage-share regression gate over BENCH JSON `stage_breakdown` objects.

bench.py attributes its end-to-end PUT/GET wall clock to pipeline stages
via the always-on perf ledger (control/perf.py). This gate compares the
latest BENCH line's breakdown against the previous one and flags any stage
whose SHARE of total latency grew by more than a threshold -- a share
shift localizes a regression to a stage even when absolute times moved
with the machine (shares are scale-free; GiB/s is not).

A stage is flagged when BOTH hold:
  * its share grew by more than `threshold` (absolute, e.g. 0.10 = ten
    percentage points), and
  * its absolute time grew too -- a share can grow because OTHER stages
    got faster, which is an improvement, not a regression.

Codec-floor mode (automatic in stage mode): when the new BENCH line claims
`device: true`, its headline encode number -- and the fused Pallas number,
when measured -- must beat the same line's recorded CPU floor
(`cpu_avx2_gibs`). A "device" round that encodes slower than the host AVX2
path means the device codec regressed into net-negative territory; the
seed shipped exactly that (`pallas_encode_gibs: 0.0`) for five rounds
without any gate noticing. Wedged-probe rounds report `device: false` and
are never floor-gated -- a dead tunnel is a probe finding, not a codec
regression.

SLO mode (`--slo`) gates loadgen reports (tools/loadgen.py) instead:
per-op p99 regressions between two same-scenario reports, plus absolute
SLO violations (budget burn > 1, declared p99 target missed) in the new
report. A p99 is flagged only when it grew by BOTH a relative tolerance
and an absolute floor -- bucket-scheme quantiles are coarse, and a
1 ms -> 2 ms "doubling" is measurement noise, not a regression.

Usage:
    python tools/perf_gate.py OLD.json NEW.json [--threshold 0.10]
    python tools/perf_gate.py --slo OLD.json NEW.json \\
        [--p99-tol=0.25] [--min-ms=5]

Exit 0 = no stage regressed, 1 = regression(s) flagged, 2 = unusable
input (missing/unparseable breakdowns -- the gate cannot vouch either
way, callers decide whether that blocks).
"""

from __future__ import annotations

import json
import sys

DEFAULT_THRESHOLD = 0.10  # share points a stage may grow before flagging
DEFAULT_P99_TOL = 0.25    # relative p99 growth tolerated between reports
DEFAULT_MIN_MS = 5.0      # ...and the absolute floor under which it's noise


def _breakdowns(bench: dict) -> dict:
    """Phase -> breakdown from one BENCH JSON object (tolerates absence)."""
    sb = bench.get("stage_breakdown")
    return sb if isinstance(sb, dict) else {}


def compare(old: dict, new: dict, threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """Regressed stages between two BENCH JSON objects.

    Returns one record per flagged stage: phase, stage, old/new share,
    old/new total_ms. Stages present on only one side are skipped (no
    basis for a delta); phases compare independently.
    """
    flagged: list[dict] = []
    old_sb, new_sb = _breakdowns(old), _breakdowns(new)
    for phase, new_phase in new_sb.items():
        old_phase = old_sb.get(phase)
        if not isinstance(old_phase, dict):
            continue
        old_stages = old_phase.get("stages", {})
        for stage, new_row in new_phase.get("stages", {}).items():
            old_row = old_stages.get(stage)
            if not isinstance(old_row, dict) or not isinstance(new_row, dict):
                continue
            d_share = float(new_row.get("share", 0.0)) - float(old_row.get("share", 0.0))
            d_ms = float(new_row.get("total_ms", 0.0)) - float(old_row.get("total_ms", 0.0))
            if d_share > threshold and d_ms > 0:
                flagged.append(
                    {
                        "phase": phase,
                        "stage": stage,
                        "old_share": old_row.get("share", 0.0),
                        "new_share": new_row.get("share", 0.0),
                        "old_total_ms": old_row.get("total_ms", 0.0),
                        "new_total_ms": new_row.get("total_ms", 0.0),
                    }
                )
    return flagged


def codec_floor_findings(new: dict) -> list[dict]:
    """Device-codec floor violations in one BENCH line (empty when the line
    makes no device claim or carries no codec keys).

    Gated metrics: the headline `value` (device encode GiB/s) always; the
    fused Pallas number only when it was actually measured (non-zero, no
    recorded error) -- a skipped secondary metric is absence of evidence,
    not a regression.
    """
    if new.get("device") is not True:
        return []
    try:
        floor = float(new.get("cpu_avx2_gibs", 0.0))
    except (TypeError, ValueError):
        return []
    if floor <= 0:
        return []
    findings: list[dict] = []
    for key, err_key in (("value", None), ("pallas_fused_gibs", "pallas_fused_error")):
        if key not in new:
            continue
        if err_key and new.get(err_key):
            continue
        try:
            v = float(new[key])
        except (TypeError, ValueError):
            continue
        if key != "value" and v == 0.0:
            continue
        if v <= floor:
            findings.append(
                {"kind": "codec-floor", "metric": key,
                 "gibs": v, "cpu_floor_gibs": floor}
            )
    return findings


def compare_slo(
    old: dict,
    new: dict,
    p99_tol: float = DEFAULT_P99_TOL,
    min_ms: float = DEFAULT_MIN_MS,
) -> list[dict]:
    """SLO findings between two loadgen reports (tolerates partial shapes).

    Five finding kinds:
      * p99-regression: an op's p99 grew past old * (1 + p99_tol) AND by
        more than min_ms (both sides must report the op);
      * burn-violation: the new report burned more than its whole error
        budget (burn > 1.0) -- absolute, old report not required;
      * p99-violation: the new report misses its own declared p99 target;
      * compare-violation: a compare block in the new report (dict, or one
        entry of a sweep list like put_scaling's) missed its min_ratio;
      * cache-violation: the report's cache_slo block (hot-read memcache
        hit-ratio promise) judged itself not ok.
    """
    findings: list[dict] = []
    old_ops = old.get("ops") if isinstance(old.get("ops"), dict) else {}
    new_ops = new.get("ops") if isinstance(new.get("ops"), dict) else {}
    for op, new_row in sorted(new_ops.items()):
        old_row = old_ops.get(op)
        if not isinstance(new_row, dict) or not isinstance(old_row, dict):
            continue
        try:
            old_p99 = float(old_row.get("p99_ms", 0.0))
            new_p99 = float(new_row.get("p99_ms", 0.0))
        except (TypeError, ValueError):
            continue
        if old_p99 > 0 and new_p99 > old_p99 * (1.0 + p99_tol) and new_p99 - old_p99 > min_ms:
            findings.append(
                {"kind": "p99-regression", "op": op,
                 "old_p99_ms": old_p99, "new_p99_ms": new_p99}
            )
    slo = new.get("slo") if isinstance(new.get("slo"), dict) else {}
    for op, row in sorted(slo.items()):
        if not isinstance(row, dict):
            continue
        try:
            burn = float(row.get("budget_burn", 0.0))
        except (TypeError, ValueError):
            burn = 0.0
        if burn > 1.0:
            findings.append(
                {"kind": "burn-violation", "op": op, "budget_burn": burn,
                 "error_budget": row.get("error_budget")}
            )
        if row.get("p99_ok") is False:
            findings.append(
                {"kind": "p99-violation", "op": op,
                 "p99_ms": row.get("p99_ms"),
                 "target_p99_ms": row.get("target_p99_ms")}
            )
    cmp = new.get("compare")
    blocks = cmp if isinstance(cmp, list) else [cmp] if isinstance(cmp, dict) else []
    for entry in blocks:
        if isinstance(entry, dict) and entry.get("reproduced") is False:
            findings.append(
                {"kind": "compare-violation",
                 "a": entry.get("a"), "b": entry.get("b"),
                 "op": entry.get("op"), "metric": entry.get("metric"),
                 "ratio": entry.get("ratio"),
                 "min_ratio": entry.get("min_ratio")}
            )
    cache_slo = new.get("cache_slo")
    if isinstance(cache_slo, dict) and cache_slo.get("ok") is False:
        findings.append(
            {"kind": "cache-violation",
             "phase": cache_slo.get("phase", ""),
             "hit_ratio": cache_slo.get("hit_ratio"),
             "min_hit_ratio": cache_slo.get("min_hit_ratio"),
             "error": cache_slo.get("error", "")}
        )
    return findings


def _load(path: str) -> dict | None:
    """Last parseable JSON object line of a file (BENCH logs are JSONL;
    the final line is the bench's one-object contract)."""
    try:
        with open(path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except OSError as e:
        print(f"perf_gate: {path}: {e}", file=sys.stderr)
        return None
    for ln in reversed(lines):
        try:
            doc = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict):
            return doc
    print(f"perf_gate: {path}: no JSON object line", file=sys.stderr)
    return None


def main(argv: list[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    threshold = DEFAULT_THRESHOLD
    p99_tol, min_ms = DEFAULT_P99_TOL, DEFAULT_MIN_MS
    slo_mode = "--slo" in argv
    for a in argv:
        if a.startswith("--threshold="):
            threshold = float(a.split("=", 1)[1])
        elif a.startswith("--p99-tol="):
            p99_tol = float(a.split("=", 1)[1])
        elif a.startswith("--min-ms="):
            min_ms = float(a.split("=", 1)[1])
    if len(args) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    old, new = _load(args[0]), _load(args[1])
    if old is None or new is None:
        return 2
    if slo_mode:
        if not new.get("ops") and not new.get("slo"):
            print("perf_gate: new report has no ops/slo sections; nothing to gate",
                  file=sys.stderr)
            return 2
        findings = compare_slo(old, new, p99_tol, min_ms)
        for f in findings:
            if f["kind"] == "p99-regression":
                print(f"REGRESSED p99 {f['op']}: "
                      f"{f['old_p99_ms']:.1f} ms -> {f['new_p99_ms']:.1f} ms")
            elif f["kind"] == "burn-violation":
                print(f"SLO BURN {f['op']}: {f['budget_burn']:.2f}x the error budget")
            elif f["kind"] == "compare-violation":
                print(f"COMPARE MISS {f['a']}/{f['b']} {f['op']} {f['metric']}: "
                      f"ratio {f['ratio']} < {f['min_ratio']}")
            elif f["kind"] == "cache-violation":
                where = f" ({f['phase']})" if f.get("phase") else ""
                why = f": {f['error']}" if f.get("error") else (
                    f": hit ratio {f['hit_ratio']} < {f['min_hit_ratio']}")
                print(f"CACHE MISS{where}{why}")
            else:
                print(f"SLO MISS {f['op']}: p99 {f['p99_ms']} ms "
                      f"over target {f['target_p99_ms']} ms")
        if not findings:
            print("perf_gate: slo ok")
        return 1 if findings else 0
    floor = codec_floor_findings(new)
    for f in floor:
        print(
            f"CODEC FLOOR {f['metric']}: {f['gibs']:.2f} GiB/s on-device "
            f"<= CPU floor {f['cpu_floor_gibs']:.2f} GiB/s"
        )
    if not _breakdowns(old) or not _breakdowns(new):
        print("perf_gate: no stage_breakdown on one side; nothing to compare",
              file=sys.stderr)
        return 1 if floor else 2
    flagged = compare(old, new, threshold)
    for f in flagged:
        print(
            f"REGRESSED {f['phase']}/{f['stage']}: share "
            f"{f['old_share']:.3f} -> {f['new_share']:.3f}, "
            f"{f['old_total_ms']:.1f} ms -> {f['new_total_ms']:.1f} ms"
        )
    if not flagged and not floor:
        print("perf_gate: ok")
    return 1 if (flagged or floor) else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
