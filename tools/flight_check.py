"""Flight-bundle gate: validate diagnostic bundles against the schema and
the retention invariants (the static half of the flight recorder's promise;
the dynamic half -- triggers actually firing -- is tests/test_flight.py and
the scenarios/flight_recorder.yaml loadgen gate).

    python tools/flight_check.py <dir> [...]   # validate existing bundles
    python tools/flight_check.py --selftest    # build a recorder in a temp
                                               # dir, fire it past the
                                               # retention cap, validate

chaos_check --invariants runs the selftest leg: it needs no pre-existing
incident, so CI exercises the write -> validate -> prune cycle
deterministically on every run. Directory mode is the operator tool: point
it at MTPU_FLIGHT_DIR after an incident and it vouches for (or indicts)
every bundle on disk before anyone reads numbers out of them.

Exit status: 0 all bundles valid (or nothing to check), 1 violations found.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REQUIRED_KEYS = (
    "flight_bundle", "id", "incident", "node", "reason", "window",
    "captured_at", "spans", "timeseries", "ledger", "degrade",
)


def check_bundle(doc, where: str) -> list[str]:
    """Schema violations in one decoded bundle document."""
    from minio_tpu.control.flight import BUNDLE_SCHEMA, TRIGGER_KINDS, _safe_tag

    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"{where}: bundle is not an object"]
    for k in _REQUIRED_KEYS:
        if k not in doc:
            problems.append(f"{where}: missing key {k!r}")
    if problems:
        return problems
    if doc["flight_bundle"] != BUNDLE_SCHEMA:
        problems.append(
            f"{where}: schema {doc['flight_bundle']!r} != {BUNDLE_SCHEMA}"
        )
    if doc["reason"] not in TRIGGER_KINDS:
        problems.append(f"{where}: unknown trigger reason {doc['reason']!r}")
    expect_id = f"{doc['incident']}__{_safe_tag(str(doc['node']))}"
    if doc["id"] != expect_id:
        problems.append(f"{where}: id {doc['id']!r} != {expect_id!r}")
    win = doc["window"]
    if not isinstance(win, dict) or "t0" not in win or "t1" not in win:
        problems.append(f"{where}: window needs t0/t1")
        return problems
    t0, t1 = float(win["t0"]), float(win["t1"])
    if not t0 < t1:
        problems.append(f"{where}: window t0 {t0} !< t1 {t1}")
    if float(doc["captured_at"]) + 1.0 < t1:
        problems.append(f"{where}: captured_at predates the window end")
    if not isinstance(doc["spans"], list):
        problems.append(f"{where}: spans must be a list")
    else:
        for i, s in enumerate(doc["spans"]):
            if not isinstance(s, dict) or not {"t", "name", "duration_ms"} <= set(s):
                problems.append(f"{where}: spans[{i}] malformed")
                break
            if not t0 <= s["t"] <= t1:
                problems.append(
                    f"{where}: spans[{i}].t {s['t']} outside window [{t0}, {t1}]"
                )
                break
    ts = doc["timeseries"]
    for sec in ts.get("series", []) if isinstance(ts, dict) else []:
        st = sec.get("t")
        # The bundle keeps the window's seconds plus one leading second
        # (the ring bucket a window edge lands inside).
        if st is not None and not t0 - 1 <= st <= t1:
            problems.append(
                f"{where}: timeseries second {st} outside window [{t0}, {t1}]"
            )
            break
    return problems


def check_dir(path: str, retain: int | None = None) -> list[str]:
    """Schema problems for every bundle in a directory, plus the retention
    invariant: at most `retain` bundles PER NODE TAG may exist."""
    if retain is None:
        try:
            retain = int(os.environ.get("MTPU_FLIGHT_RETAIN", "16"))
        except ValueError:
            retain = 16
    try:
        names = sorted(
            n for n in os.listdir(path)
            if n.startswith("flight-") and n.endswith(".json")
        )
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    problems: list[str] = []
    per_node: dict[str, int] = {}
    for n in names:
        where = os.path.join(path, n)
        try:
            with open(where) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f"{where}: unreadable bundle ({e})")
            continue
        problems.extend(check_bundle(doc, where))
        if isinstance(doc, dict) and "node" in doc:
            tag = str(doc["node"])
            per_node[tag] = per_node.get(tag, 0) + 1
    for tag, count in sorted(per_node.items()):
        if count > retain:
            problems.append(
                f"{path}: node {tag!r} holds {count} bundles > retain cap {retain}"
            )
    return problems


def selftest() -> int:
    """Deterministic write -> validate -> prune cycle in a temp dir: no
    pre-existing incident needed, so the CI leg always exercises the code."""
    import tempfile

    from minio_tpu.control.degrade import DegradeStats
    from minio_tpu.control.flight import FlightRecorder
    from minio_tpu.control.perf import PerfSys

    retain = 3
    with tempfile.TemporaryDirectory(prefix="mtpu-flight-check-") as td:
        fr = FlightRecorder(
            dir=td, retain=retain, window_s=5.0, cooldown_s=0.0,
            perf=PerfSys(), degrade=DegradeStats(),
        )
        # Feed the ring so bundles carry spans, then fire past the cap.
        class _Span:
            name = "s3.GetObject"
            layer = "api"
            trace_id = "t-selftest"

        for _ in range(4):
            fr.record_span(_Span(), 0.012)
        for i in range(retain + 2):
            fr.trigger("manual", detail={"via": "flight_check", "i": i},
                       fan_out=False)
        problems = check_dir(td, retain=retain)
        written = len([n for n in os.listdir(td) if n.endswith(".json")])
        if written != retain:
            problems.append(
                f"selftest: {written} bundles on disk != retain cap {retain}"
            )
        if fr.stats()["bundles_pruned"] != 2:
            problems.append(
                f"selftest: pruned {fr.stats()['bundles_pruned']} != 2"
            )
        for p in problems:
            print(f"flight_check: {p}", file=sys.stderr)
        if not problems:
            print(f"flight_check: selftest ok ({written} bundles, cap {retain})")
        return 1 if problems else 0


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if "--selftest" in args:
        return selftest()
    if not args:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    problems: list[str] = []
    for d in args:
        problems.extend(check_dir(d))
    for p in problems:
        print(f"flight_check: {p}", file=sys.stderr)
    if not problems:
        print(f"flight_check: ok ({len(args)} dir(s))")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
