"""mtpusan driver: run suites/scenarios under the runtime concurrency
sanitizer and gate on its findings.

The dynamic half of the mtpusan pair (static rules live in tools/mtpulint:
lock-order, unjoined-thread, cond-wait-loop, shared-publish). This driver:

  1. re-runs the `pytest.mark.race` suites (same discovery as
     tools/race_gate.py) with ``MTPU_TSAN=1``, so every SanLock acquisition
     feeds the lock-order graph and every teardown is leak-checked;
  2. replays a loadgen scenario (default: ``concurrent_put_collapse``, the
     ROADMAP item-1 repro) sanitized, and keeps the per-lock
     contention/hold-time profile the armed runner embeds in its report --
     the measured serialization evidence the item-1 rewrite starts from;
  3. merges every subprocess's findings artifact (written to
     ``MTPU_TSAN_OUT`` at exit), drops rows the in-code SUPPRESSIONS table
     already justified, applies the shrink-only baseline
     (``tools/mtpusan_baseline.txt``, same relpath::rule::count format and
     semantics as mtpulint's -- the site string rides in the relpath slot),
     and fails on anything left.

    python tools/mtpusan.py                 # suites + scenario, gate
    python tools/mtpusan.py --suites-only
    python tools/mtpusan.py --scenario-only --scenario mixed_smoke
    python tools/mtpusan.py --out /tmp/mtpusan.json   # merged report JSON
    python tools/mtpusan.py --write-baseline          # grandfather (shrink-only)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _HERE)
sys.path.insert(0, ROOT)

from mtpulint.engine import (  # noqa: E402
    Finding,
    apply_baseline,
    format_baseline,
    load_baseline,
)
from race_gate import discover_race_tests  # noqa: E402

BASELINE_PATH = os.path.join(_HERE, "mtpusan_baseline.txt")
DEFAULT_SCENARIO = "concurrent_put_collapse"
TIMEOUT_S = int(os.environ.get("MTPUSAN_TIMEOUT_S", "1200"))


def _san_env(out_path: str) -> dict:
    env = dict(os.environ, MTPU_TSAN="1", MTPU_TSAN_OUT=out_path)
    # The hold-time detector measures the PRODUCT's critical sections; under
    # the sanitizer's own overhead + race-mode switch intervals a tighter
    # threshold would mint schedule-noise findings.
    env.setdefault("MTPU_TSAN_HOLD_MS", "400")
    return env


def _read_report(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_suites(reports: list[dict]) -> int:
    """Race-marked suites, one sanitized pytest run. Returns the pytest rc."""
    race_tests = discover_race_tests(ROOT)
    if not race_tests:
        print("[mtpusan] no race-marked suites found", file=sys.stderr)
        return 2
    print(f"[mtpusan] sanitized suite run: {', '.join(race_tests)}")
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        out = tf.name
    try:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-x",
             "-o", f"faulthandler_timeout={max(60, TIMEOUT_S - 120)}",
             *race_tests],
            cwd=ROOT, env=_san_env(out), timeout=TIMEOUT_S,
        )
        rep = _read_report(out)
        if rep is not None:
            rep["source"] = "race-suites"
            reports.append(rep)
        print(f"[mtpusan] suites: rc={proc.returncode} "
              f"({time.time() - t0:.0f}s, "
              f"{len(rep['findings']) if rep else '?'} raw finding(s))")
        return proc.returncode
    except subprocess.TimeoutExpired:
        print(f"[mtpusan] suites: DEADLOCK? timed out after {TIMEOUT_S}s",
              file=sys.stderr)
        return 1
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def run_scenario(name: str, reports: list[dict], out_json: str | None) -> int:
    """One sanitized loadgen replay; keeps the report's lock profile."""
    scen = os.path.join(ROOT, "scenarios", f"{name}.yaml")
    if not os.path.exists(scen):
        print(f"[mtpusan] scenario not found: {scen}", file=sys.stderr)
        return 2
    print(f"[mtpusan] sanitized scenario replay: {name}")
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        out = tf.name
    report_path = out_json or os.path.join(
        tempfile.gettempdir(), f"mtpusan_{name}.json"
    )
    try:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, os.path.join(_HERE, "loadgen.py"), scen,
             "--out", report_path],
            cwd=ROOT, env=_san_env(out), timeout=TIMEOUT_S,
        )
        rep = _read_report(out)
        scen_rep = _read_report(report_path)
        if rep is not None:
            rep["source"] = f"scenario:{name}"
            if scen_rep is not None:
                # Prefer the profile snapshotted INSIDE the run (post-phases)
                # over the atexit one; both exist, the runner's is canonical.
                rep["lock_profile"] = scen_rep.get(
                    "lock_profile", rep.get("lock_profile")
                )
            reports.append(rep)
        n_locks = len((rep or {}).get("lock_profile") or {})
        print(f"[mtpusan] scenario: rc={proc.returncode} "
              f"({time.time() - t0:.0f}s, {n_locks} lock(s) profiled, "
              f"report: {report_path})")
        # The scenario's own SLO/compare verdict is tools/perf_gate.py's
        # business; here only sanitizer findings gate, so a perf regression
        # cannot mask (or be masked by) a concurrency finding.
        return 0 if proc.returncode in (0, 1) else proc.returncode
    except subprocess.TimeoutExpired:
        print(f"[mtpusan] scenario: timed out after {TIMEOUT_S}s", file=sys.stderr)
        return 1
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def merge_findings(reports: list[dict]) -> tuple[list[dict], list[dict]]:
    """(unsuppressed, suppressed) across runs, deduped by (rule, site)."""
    seen: set[tuple[str, str]] = set()
    unsup: list[dict] = []
    sup: list[dict] = []
    for rep in reports:
        for f in rep.get("findings", []):
            key = (f.get("rule", "?"), f.get("site", "?"))
            if key in seen:
                continue
            seen.add(key)
            f = dict(f, source=rep.get("source", "?"))
            (sup if "suppressed" in f else unsup).append(f)
    return unsup, sup


def gate(unsup: list[dict], baseline_path: str, write: bool) -> int:
    """Apply the shrink-only baseline; 0 iff nothing new."""
    as_findings = [
        Finding(f["rule"], f["site"], 0, f.get("message", "")) for f in unsup
    ]
    if write:
        header = (
            "# mtpusan baseline -- grandfathered runtime findings\n"
            "# (site::rule::count). Shrink-only: fix a finding, delete its\n"
            "# line. Regenerate: python tools/mtpusan.py --write-baseline"
        )
        with open(baseline_path, "w", encoding="utf-8") as f:
            f.write(format_baseline(as_findings, header))
        print(f"[mtpusan] baseline written: {len(as_findings)} finding(s) "
              f"-> {baseline_path}")
        return 0
    new, stale = apply_baseline(as_findings, load_baseline(baseline_path))
    for f in new:
        print(f"[mtpusan] FINDING {f.rule} @ {f.relpath}: {f.message}",
              file=sys.stderr)
    for s in stale:
        print(f"[mtpusan] stale baseline entry: {s}", file=sys.stderr)
    if new:
        print(f"[mtpusan] {len(new)} unsuppressed finding(s)", file=sys.stderr)
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mtpusan", description="runtime concurrency sanitizer driver"
    )
    ap.add_argument("--suites-only", action="store_true")
    ap.add_argument("--scenario-only", action="store_true")
    ap.add_argument("--scenario", default=DEFAULT_SCENARIO,
                    help=f"loadgen scenario name (default: {DEFAULT_SCENARIO})")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather current findings (shrink-only) and exit 0")
    ap.add_argument("--out", default=None,
                    help="write the merged mtpusan report JSON here")
    args = ap.parse_args(argv)

    reports: list[dict] = []
    rc = 0
    if not args.scenario_only:
        rc = max(rc, run_suites(reports))
    if not args.suites_only:
        rc = max(rc, run_scenario(args.scenario, reports, None))

    unsup, sup = merge_findings(reports)
    for f in sup:
        print(f"[mtpusan] suppressed: {f['rule']} @ {f['site']} "
              f"({f['suppressed']})")
    profile = {}
    for rep in reports:
        profile.update(rep.get("lock_profile") or {})
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(
                {"mtpusan": 1, "findings": unsup, "suppressed": sup,
                 "lock_profile": profile, "runs": len(reports)},
                f, indent=2, sort_keys=True,
            )
        print(f"[mtpusan] merged report: {args.out}")
    gate_rc = gate(unsup, args.baseline, args.write_baseline)
    rc = max(rc, gate_rc)
    print(f"[mtpusan] {'PASS' if rc == 0 else 'FAIL'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
