"""Live-chip tuning harness for the device kernels (run when a TPU is up).

Measures, on the real chip, everything bench.py summarizes -- but swept
over the tunables so the constants in the kernels can be re-pinned:

  * XLA hash chunk unroll (highwayhash_jax.CHUNK): 4..32
  * Pallas hash tile/chunk (highwayhash_pallas.TILE_N / CHUNK_P)
  * Pallas RS tile (rs_pallas.TILE_S)
  * fused encode+hash with each hash impl at serving batch sizes

Each configuration runs in-process; module constants are monkey-set and
jit caches cleared per point. Prints one line per point; run under
`timeout` -- first compiles on a cold chip are slow.

    python tools/tpu_tune.py [quick|full]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

K, M = 12, 4
# Env knobs: shrink for harness smoke-tests off-chip (Pallas interpret mode
# is orders of magnitude slower than compiled) or for short chip windows.
BLOCK = int(os.environ.get("TUNE_BLOCK", str(1 << 20)))
SHARD = -(-BLOCK // K)
BATCH_Q = int(os.environ.get("TUNE_BATCH", "128"))
STREAMS_Q = int(os.environ.get("TUNE_STREAMS", "1024"))


def _time(fn, arg, iters=8) -> float:
    import jax

    out = fn(arg)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(arg)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


# (family, label, gibs, exact); exact: True = oracle-checked ok,
# False = mismatch/failure, None = no oracle for this family (timing only).
_RESULTS: list[tuple[str, str, float, bool | None]] = []
_OUT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tune_last.txt")


def _report(family: str, label: str, gibs: float, exact: bool | None) -> None:
    """Print AND append to the durable record immediately: chip windows are
    short and runs sit under `timeout` — points measured before a kill must
    survive it (scrollback doesn't)."""
    _RESULTS.append((family, label, gibs, exact))
    tag = {True: "ok", False: "FAIL", None: "unchecked"}[exact]
    print(f"{label}: {gibs:.2f} GiB/s [{tag}]")
    mode = "a" if _RESULTS[1:] else "w"
    with open(_OUT_PATH, mode) as f:
        if mode == "w":
            f.write(f"# tpu_tune results {time.strftime('%Y-%m-%dT%H:%M:%S')}\n")
        f.write(f"{family}\t{label}\t{gibs:.3f}\t{tag}\n")


def _fail(family: str, label: str, err: str) -> None:
    _report(family, f"{label} ({err})", 0.0, False)


def _summary() -> None:
    """Winners per family (mismatched/failed points are never winners;
    families without an oracle are reported as timing-only)."""
    fams: dict[str, tuple[str, float, bool | None]] = {}
    for family, label, gibs, exact in _RESULTS:
        if exact is not False and (family not in fams or gibs > fams[family][1]):
            fams[family] = (label, gibs, exact)
    lines = [
        f"[tune] BEST {fam}: {label} ({gibs:.2f} GiB/s"
        f"{', timing-only' if exact is None else ''})"
        for fam, (label, gibs, exact) in fams.items()
    ]
    for ln in lines:
        print(ln)
    with open(_OUT_PATH, "a") as f:
        f.write("\n".join(lines) + "\n")
    print(f"[tune] results written to {_OUT_PATH}")


def main() -> None:
    quick = (sys.argv[1:] or ["quick"])[0] == "quick"
    import jax
    import jax.numpy as jnp

    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    rng = np.random.default_rng(0)

    # --- Pallas RS tile sweep -------------------------------------------
    import minio_tpu.ops.rs_pallas as rp
    from minio_tpu.ops import rs

    batch = BATCH_Q if quick else max(512, BATCH_Q)
    data = rng.integers(0, 256, (batch, K, SHARD), dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(data))
    codec = rs.RSCodec(K, M)
    dt = _time(jax.jit(codec.encode), dev)
    # Timing-only here: the XLA encode is golden-pinned by the test suite,
    # not re-checked in this run.
    _report("rs-encode", "xla encode", batch * BLOCK * 8 / dt / 2**30, None)
    for ts in (4096, 8192, 16384) if quick else (2048, 4096, 8192, 16384, 32768):
        try:
            rp.TILE_S = ts
            rp._apply_padded.clear_cache()
            pcodec = rp.RSPallasCodec(K, M)
            ok = np.array_equal(
                np.asarray(codec.encode(dev[:2])), np.asarray(pcodec.encode(dev[:2]))
            )
            dt = _time(jax.jit(pcodec.encode), dev)
            _report("rs-encode", f"pallas rs TILE_S={ts}", batch * BLOCK * 8 / dt / 2**30, ok)
        except Exception as e:  # noqa: BLE001
            _fail("rs-encode", f"pallas rs TILE_S={ts}", str(e)[:120])

    # --- hash sweeps -----------------------------------------------------
    from minio_tpu.ops import highwayhash as hh_host
    from minio_tpu.ops import highwayhash_jax as hhj

    streams = STREAMS_Q if quick else max(4096, STREAMS_Q)
    hdata_np = rng.integers(0, 256, (streams, SHARD), dtype=np.uint8)
    hdata = jax.device_put(jnp.asarray(hdata_np))
    oracle = hh_host.hash256_batch(hdata_np[:2])

    for chunk in (8, 16, 32):
        hhj.CHUNK = chunk
        hhj._hh256_impl.clear_cache()
        try:
            ok = np.array_equal(np.asarray(hhj.hash256_batch(hdata[:2])), oracle)
            dt = _time(jax.jit(hhj.hash256_batch), hdata)
            _report("hash", f"xla hash CHUNK={chunk}", hdata.size * 8 / dt / 2**30, ok)
        except Exception as e:  # noqa: BLE001
            _fail("hash", f"xla hash CHUNK={chunk}", str(e)[:120])
    hhj.CHUNK = None
    hhj._hh256_impl.clear_cache()

    import minio_tpu.ops.highwayhash_pallas as hhp

    tiles = ((256, 8), (512, 8), (512, 16)) if quick else (
        (256, 8), (512, 8), (1024, 8), (512, 16), (1024, 16), (512, 4)
    )
    for tile_n, chunk_p in tiles:
        hhp.TILE_N, hhp.CHUNK_P = tile_n, chunk_p
        hhp._run_chain.clear_cache()
        hhp._hh256_pallas.clear_cache()
        try:
            ok = np.array_equal(np.asarray(hhp.hash256_batch(hdata[:2])), oracle)
            dt = _time(jax.jit(hhp.hash256_batch), hdata)
            _report(
                "hash", f"pallas hash TILE_N={tile_n} CHUNK_P={chunk_p}",
                hdata.size * 8 / dt / 2**30, ok,
            )
        except Exception as e:  # noqa: BLE001
            _fail("hash", f"pallas hash TILE_N={tile_n} CHUNK_P={chunk_p}", str(e)[:150])

    # --- fused at serving batch sizes ------------------------------------
    from minio_tpu.models import pipeline as pipe_mod

    grid = (16, 32, 64) if quick else (16, 32, 64, 128)
    for fb in sorted({min(fb, batch) for fb in grid}):
        fdata = jax.device_put(jnp.asarray(data[:fb]))
        for impl in ("xla", "pallas"):
            os.environ["MINIO_TPU_HASH"] = impl
            p = pipe_mod.ErasurePipeline(pipe_mod.Geometry(K, M))
            try:
                dt = _time(p.encode, fdata, iters=4)
                _report("fused", f"fused B={fb} hash={impl}", fb * BLOCK * 4 / dt / 2**30, None)
            except Exception as e:  # noqa: BLE001
                _fail("fused", f"fused B={fb} hash={impl}", str(e)[:120])
        os.environ.pop("MINIO_TPU_HASH", None)
    _summary()


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/tools/", 1)[0])
    main()
