"""Live-chip tuning harness for the device kernels (run when a TPU is up).

Measures, on the real chip, everything bench.py summarizes -- but swept
over the tunables so the constants in the kernels can be re-pinned:

  * XLA hash chunk unroll (highwayhash_jax.CHUNK): 4..32
  * Pallas hash tile/chunk (highwayhash_pallas.TILE_N / CHUNK_P)
  * Pallas RS tile (rs_pallas.TILE_S)
  * fused encode+hash with each hash impl at serving batch sizes

Each configuration runs in-process; module constants are monkey-set and
jit caches cleared per point. Prints one line per point; run under
`timeout` -- first compiles on a cold chip are slow.

    python tools/tpu_tune.py [quick|full]
"""

from __future__ import annotations

import sys
import time

import numpy as np

K, M = 12, 4
BLOCK = 1 << 20
SHARD = -(-BLOCK // K)


def _time(fn, arg, iters=8) -> float:
    import jax

    out = fn(arg)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(arg)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def main() -> None:
    quick = (sys.argv[1:] or ["quick"])[0] == "quick"
    import jax
    import jax.numpy as jnp

    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    rng = np.random.default_rng(0)

    # --- Pallas RS tile sweep -------------------------------------------
    import minio_tpu.ops.rs_pallas as rp
    from minio_tpu.ops import rs

    batch = 128 if quick else 512
    data = rng.integers(0, 256, (batch, K, SHARD), dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(data))
    codec = rs.RSCodec(K, M)
    dt = _time(jax.jit(codec.encode), dev)
    print(f"xla encode: {batch * BLOCK * 8 / dt / 2**30:.2f} GiB/s")
    for ts in (4096, 8192, 16384) if quick else (2048, 4096, 8192, 16384, 32768):
        rp.TILE_S = ts
        rp._apply_padded.clear_cache()
        pcodec = rp.RSPallasCodec(K, M)
        try:
            ok = np.array_equal(
                np.asarray(codec.encode(dev[:2])), np.asarray(pcodec.encode(dev[:2]))
            )
            dt = _time(jax.jit(pcodec.encode), dev)
            print(f"pallas rs TILE_S={ts}: {batch * BLOCK * 8 / dt / 2**30:.2f} GiB/s exact={ok}")
        except Exception as e:  # noqa: BLE001
            print(f"pallas rs TILE_S={ts}: FAIL {str(e)[:120]}")

    # --- hash sweeps -----------------------------------------------------
    from minio_tpu.ops import highwayhash as hh_host
    from minio_tpu.ops import highwayhash_jax as hhj

    streams = 1024 if quick else 4096
    hdata_np = rng.integers(0, 256, (streams, SHARD), dtype=np.uint8)
    hdata = jax.device_put(jnp.asarray(hdata_np))
    oracle = hh_host.hash256_batch(hdata_np[:2])

    for chunk in (8, 16, 32):
        hhj.CHUNK = chunk
        hhj._hh256_impl.clear_cache()
        try:
            ok = np.array_equal(np.asarray(hhj.hash256_batch(hdata[:2])), oracle)
            dt = _time(jax.jit(hhj.hash256_batch), hdata)
            print(
                f"xla hash CHUNK={chunk}: {hdata.size * 8 / dt / 2**30:.2f} GiB/s exact={ok}"
            )
        except Exception as e:  # noqa: BLE001
            print(f"xla hash CHUNK={chunk}: FAIL {str(e)[:120]}")
    hhj.CHUNK = None
    hhj._hh256_impl.clear_cache()

    import minio_tpu.ops.highwayhash_pallas as hhp

    tiles = ((256, 8), (512, 8), (512, 16)) if quick else (
        (256, 8), (512, 8), (1024, 8), (512, 16), (1024, 16), (512, 4)
    )
    for tile_n, chunk_p in tiles:
        hhp.TILE_N, hhp.CHUNK_P = tile_n, chunk_p
        hhp._run_chain.clear_cache()
        hhp._hh256_pallas.clear_cache()
        try:
            ok = np.array_equal(np.asarray(hhp.hash256_batch(hdata[:2])), oracle)
            dt = _time(jax.jit(hhp.hash256_batch), hdata)
            print(
                f"pallas hash TILE_N={tile_n} CHUNK_P={chunk_p}: "
                f"{hdata.size * 8 / dt / 2**30:.2f} GiB/s exact={ok}"
            )
        except Exception as e:  # noqa: BLE001
            print(f"pallas hash TILE_N={tile_n} CHUNK_P={chunk_p}: FAIL {str(e)[:150]}")

    # --- fused at serving batch sizes ------------------------------------
    from minio_tpu.models import pipeline as pipe_mod

    for fb in (16, 32, 64) if quick else (16, 32, 64, 128):
        fdata = jax.device_put(jnp.asarray(data[:fb]))
        for impl in ("xla", "pallas"):
            import os

            os.environ["MINIO_TPU_HASH"] = impl
            p = pipe_mod.ErasurePipeline(pipe_mod.Geometry(K, M))
            try:
                dt = _time(p.encode, fdata, iters=4)
                print(f"fused B={fb} hash={impl}: {fb * BLOCK * 4 / dt / 2**30:.2f} GiB/s")
            except Exception as e:  # noqa: BLE001
                print(f"fused B={fb} hash={impl}: FAIL {str(e)[:120]}")
        os.environ.pop("MINIO_TPU_HASH", None)


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/tools/", 1)[0])
    main()
