"""mtpulint engine: AST project scan, suppressions, baseline accounting.

The framework half of tools/mtpulint (rules live in rules.py): walk a tree,
parse every Python file once, hand the shared ProjectContext to each rule,
then filter the findings through two escape hatches:

  * inline suppressions -- `# mtpulint: disable=<rule>[,<rule>...]` on the
    finding line (or alone on the line directly above it) silences that
    line; `# mtpulint: disable-file=<rule>` anywhere silences the whole
    file for that rule. Suppressions are for *justified* exemptions (the
    comment should say why), not for burying findings.
  * the committed baseline -- grandfathered findings recorded as
    `relpath::rule::count` lines. A file/rule pair may produce at most its
    baselined count; anything beyond is NEW and fails the run. Entries
    whose count exceeds reality are reported as stale so the baseline only
    ever shrinks.

Pure stdlib, no imports of the linted package: the tree is analyzed as
text + AST, never executed, so the lint runs in milliseconds and cannot be
confused by import-time side effects or missing accelerator deps.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

_SUPPRESS_RE = re.compile(
    r"#\s*mtpulint:\s*(disable|disable-file)=([a-zA-Z0-9_,\- ]+)"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    relpath: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.relpath}:{self.line}: {self.rule}: {self.message}"


@dataclass
class FileContext:
    """One parsed source file. `relpath` is slash-normalized and relative to
    the project root (the directory that contains `minio_tpu/`), so rules
    and baseline entries are stable regardless of where the scan runs."""

    relpath: str
    source: str
    tree: ast.AST
    line_disables: dict[int, set[str]] = field(default_factory=dict)
    file_disables: set[str] = field(default_factory=set)

    @property
    def lines(self) -> list[str]:
        return self.source.splitlines()


def _parse_suppressions(ctx: FileContext) -> None:
    """Populate line/file disables. A disable comment alone on its own line
    applies to the next NON-comment line (multi-line statements anchor
    findings at their first line, so `# mtpulint: disable=x` sits naturally
    above, anywhere inside the justification comment block)."""
    all_lines = ctx.source.splitlines()
    for lineno, text in enumerate(all_lines, 1):
        m = _SUPPRESS_RE.search(text)
        if m is None:
            continue
        kind = m.group(1)
        # First whitespace token per comma segment, so a justification may
        # trail the rule name: `# mtpulint: disable=foo -- why this is ok`.
        rules = {
            seg.split()[0] for seg in m.group(2).split(",") if seg.split()
        }
        if kind == "disable-file":
            ctx.file_disables |= rules
        elif text.lstrip().startswith("#"):
            tgt = lineno + 1
            while tgt <= len(all_lines) and (
                not all_lines[tgt - 1].strip()
                or all_lines[tgt - 1].lstrip().startswith("#")
            ):
                tgt += 1
            target = ctx.line_disables.setdefault(tgt, set())
            target |= rules
        else:
            target = ctx.line_disables.setdefault(lineno, set())
            target |= rules


class ProjectContext:
    """Everything a rule may look at: every parsed file, keyed by relpath."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.files: list[FileContext] = []
        self.by_relpath: dict[str, FileContext] = {}
        self.parse_errors: list[Finding] = []

    def add_file(self, abspath: str) -> None:
        relpath = os.path.relpath(abspath, self.root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            self.parse_errors.append(
                Finding("parse-error", relpath, 0, f"unreadable: {e}")
            )
            return
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            self.parse_errors.append(
                Finding("parse-error", relpath, e.lineno or 0, f"syntax error: {e.msg}")
            )
            return
        ctx = FileContext(relpath=relpath, source=source, tree=tree)
        _parse_suppressions(ctx)
        self.files.append(ctx)
        self.by_relpath[relpath] = ctx

    def iter_files(self, *prefixes: str):
        """FileContexts whose relpath starts with any prefix ('' = all)."""
        for ctx in self.files:
            if not prefixes or any(ctx.relpath.startswith(p) for p in prefixes):
                yield ctx

    def get(self, relpath: str) -> FileContext | None:
        return self.by_relpath.get(relpath)


def build_project(root: str, paths: list[str]) -> ProjectContext:
    """Parse every .py under `paths` (files or directories, relative to or
    under `root`) into one ProjectContext. __pycache__ is skipped."""
    project = ProjectContext(root)
    seen: set[str] = set()
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(absp):
            targets = [absp]
        else:
            targets = []
            for dirpath, dirnames, filenames in os.walk(absp):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                targets.extend(
                    os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
                )
        for t in sorted(targets):
            t = os.path.abspath(t)
            if t not in seen:
                seen.add(t)
                project.add_file(t)
    return project


class Rule:
    """Base rule: subclasses set `id`/`title`/`scope` and implement check().

    `scope` is a tuple of relpath prefixes the rule applies to (empty =
    whole tree); the engine does not pre-filter -- rules call
    project.iter_files(*self.scope) so cross-file rules can still see
    out-of-scope files (e.g. the stage registry) when they need to.
    """

    id: str = ""
    title: str = ""
    scope: tuple[str, ...] = ()

    def check(self, project: ProjectContext):  # pragma: no cover - interface
        raise NotImplementedError
        yield  # makes every override a generator for free


def run_rules(project: ProjectContext, rules: list[Rule]) -> list[Finding]:
    """All non-suppressed findings, sorted by (path, line, rule)."""
    findings: list[Finding] = list(project.parse_errors)
    for rule in rules:
        for f in rule.check(project):
            ctx = project.get(f.relpath)
            if ctx is not None and _is_suppressed(ctx, f):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.relpath, f.line, f.rule))
    return findings


def _is_suppressed(ctx: FileContext, f: Finding) -> bool:
    if f.rule in ctx.file_disables or "all" in ctx.file_disables:
        return True
    rules = ctx.line_disables.get(f.line, set())
    return f.rule in rules or "all" in rules


# -- baseline -----------------------------------------------------------------


def load_baseline(path: str) -> dict[tuple[str, str], int]:
    """Parse `relpath::rule::count` lines; comments/blanks ignored."""
    allowed: dict[tuple[str, str], int] = {}
    if not os.path.exists(path):
        return allowed
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split("::")
            if len(parts) != 3:
                continue
            relpath, rule, count = parts
            try:
                allowed[(relpath, rule)] = allowed.get((relpath, rule), 0) + int(count)
            except ValueError:
                continue
    return allowed


def apply_baseline(
    findings: list[Finding], allowed: dict[tuple[str, str], int]
) -> tuple[list[Finding], list[str]]:
    """Split findings into (new, stale-baseline-notes).

    Per (file, rule): the first `allowed` findings (in line order) are
    grandfathered; the rest are new. Baseline entries covering more
    findings than exist are stale -- the fix landed, shrink the file.
    """
    by_key: dict[tuple[str, str], list[Finding]] = {}
    for f in findings:
        by_key.setdefault((f.relpath, f.rule), []).append(f)
    new: list[Finding] = []
    for key, group in sorted(by_key.items()):
        quota = allowed.get(key, 0)
        if len(group) > quota:
            new.extend(group[quota:])
    stale = [
        f"{relpath}::{rule}: baseline allows {quota}, found "
        f"{len(by_key.get((relpath, rule), []))} -- shrink the baseline"
        for (relpath, rule), quota in sorted(allowed.items())
        if len(by_key.get((relpath, rule), [])) < quota
    ]
    return new, stale


def format_baseline(findings: list[Finding], header: str = "") -> str:
    counts: dict[tuple[str, str], int] = {}
    for f in findings:
        key = (f.relpath, f.rule)
        counts[key] = counts.get(key, 0) + 1
    lines = [header.rstrip()] if header else []
    lines.extend(
        f"{relpath}::{rule}::{n}" for (relpath, rule), n in sorted(counts.items())
    )
    return "\n".join(lines) + "\n"
