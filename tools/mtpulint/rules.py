"""mtpulint rules: the project invariants, one class each.

Every rule encodes a structural property PRs 1-4 established and a refactor
could silently drop: error transport (swallowed-except, typed-errors),
deadline plumbing (raw-transport, deadline-rebind), lock hygiene
(lock-blocking-io, unlocked-global), resource lifetime (resource-leak),
durability barriers (unsynced-commit), the observability seams
(stage-key, metrics-rendered), and buffer lifetime on the zero-copy plane
(release-on-all-paths, double-release, view-escape, interface-conformance
-- the static half of bufsan, see minio_tpu/control/bufsan.py). Rules are
AST-based
-- they see structure, not text -- so renames and reformatting can't dodge
them, and suppressions (`# mtpulint: disable=<rule>`) are visible decisions
in the diff rather than regex blind spots.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding, ProjectContext, Rule

# Hot-path packages: where a swallowed error means silent data-plane damage.
HOT_PATHS = (
    "minio_tpu/api/",
    "minio_tpu/object/",
    "minio_tpu/dist/",
    "minio_tpu/storage/",
    "minio_tpu/chaos/",
)

TRANSPORT = "minio_tpu/dist/transport.py"
PERF = "minio_tpu/control/perf.py"
METRICS = "minio_tpu/control/metrics.py"
DEGRADE = "minio_tpu/control/degrade.py"
PROFILER = "minio_tpu/control/profiler.py"
SELFTEST = "minio_tpu/control/selftest.py"
POOLMGR = "minio_tpu/object/poolmgr.py"
REBALANCE = "minio_tpu/control/rebalance.py"
FLIGHT = "minio_tpu/control/flight.py"
LOGGING = "minio_tpu/control/logging.py"
PUBSUB = "minio_tpu/control/pubsub.py"


def _call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call: `a.b.c(...)` -> 'a.b.c',
    `f(...)` -> 'f'. Unresolvable pieces render as '?'."""
    parts: list[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def _str_const(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# swallowed-except
# ---------------------------------------------------------------------------


class SwallowedExceptRule(Rule):
    """Broad `except` that swallows silently on a hot path.

    A handler for bare/`Exception`/`BaseException` whose body neither
    re-raises, returns, logs, counts, nor calls anything is a black hole:
    the error happened, nobody will ever know. Narrow the type, or make the
    swallow observable (log + metric)."""

    id = "swallowed-except"
    title = "broad except swallows without logging or re-raising"
    scope = HOT_PATHS

    BROAD = {"Exception", "BaseException"}

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, ast.Name):
            return t.id in self.BROAD
        if isinstance(t, ast.Tuple):
            return any(
                isinstance(e, ast.Name) and e.id in self.BROAD for e in t.elts
            )
        return False

    def _is_silent(self, handler: ast.ExceptHandler) -> bool:
        """Silent = nothing in the body raises, returns, or calls anything.
        A bare `return`/`continue`/`pass` body observes nothing."""
        for stmt in handler.body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Raise, ast.Call, ast.Yield, ast.YieldFrom)):
                    return False
                if isinstance(node, ast.Return) and node.value is not None:
                    return False
        return True

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if self._is_broad(node) and self._is_silent(node):
                    what = "bare except" if node.type is None else "broad except"
                    yield Finding(
                        self.id,
                        ctx.relpath,
                        node.lineno,
                        f"{what} swallows silently -- narrow the type, or "
                        "log-and-count before continuing",
                    )


# ---------------------------------------------------------------------------
# raw-transport
# ---------------------------------------------------------------------------


class RawTransportRule(Rule):
    """Raw `requests`/`socket` traffic outside dist/transport.py.

    All internode RPC must ride RestClient.call: that is where the deadline
    budget caps the socket timeout, the X-Mtpu-Deadline header is stamped,
    chaos faults inject, and per-peer histograms record. A module opening
    its own HTTP session or socket re-introduces the unbounded hop. External
    backends (the S3 gateway) are the one legitimate exception -- suppress
    with a justification comment."""

    id = "raw-transport"
    title = "raw requests/socket use outside dist/transport.py"
    scope = ("minio_tpu/dist/", "minio_tpu/storage/", "minio_tpu/object/")

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            if ctx.relpath == TRANSPORT:
                continue
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name.split(".")[0] in ("requests", "socket"):
                            yield self._finding(ctx, node, f"import {alias.name}")
                elif isinstance(node, ast.ImportFrom):
                    if (node.module or "").split(".")[0] in ("requests", "socket"):
                        yield self._finding(ctx, node, f"from {node.module} import ...")
                elif isinstance(node, ast.Call):
                    name = _call_name(node)
                    root = name.split(".")[0]
                    if root in ("requests", "socket") and "." in name:
                        yield self._finding(ctx, node, f"{name}(...)")

    def _finding(self, ctx, node, what: str) -> Finding:
        return Finding(
            self.id,
            ctx.relpath,
            node.lineno,
            f"{what} -- internode traffic must ride dist/transport.py "
            "RestClient so the deadline/chaos/metrics seams apply",
        )


# ---------------------------------------------------------------------------
# deadline-rebind
# ---------------------------------------------------------------------------


class DeadlineRebindRule(Rule):
    """The deadline budget must ride EVERY hop (tools/deadline_lint.py,
    generalized to the AST).

    Two obligations:
      1. dist/transport.py keeps the plumbing: a `deadline.remaining()`
         check, a DEADLINE_HEADER stamp on outgoing requests
         (`headers[DEADLINE_HEADER] = ...`), and a DeadlineExceeded raise.
      2. Every internode REST *server* module (one that authenticates
         TOKEN_HEADER on inbound requests) re-binds the propagated budget
         with `deadline.bind_header(...)` -- a hop that drops the header
         resets the budget to infinity for everything downstream."""

    id = "deadline-rebind"
    title = "deadline propagation plumbing dropped"
    scope = ("minio_tpu/",)

    def check(self, project: ProjectContext):
        tctx = project.get(TRANSPORT)
        if tctx is not None:
            yield from self._check_transport(tctx)
        for ctx in project.iter_files(*self.scope):
            if ctx.relpath == TRANSPORT:
                continue
            if self._authenticates_token(ctx) and not self._rebinds(ctx):
                yield Finding(
                    self.id,
                    ctx.relpath,
                    1,
                    "authenticates TOKEN_HEADER (REST server) but never calls "
                    "deadline.bind_header -- inbound budgets are dropped here",
                )

    def _check_transport(self, ctx):
        has_remaining = False
        has_stamp = False
        has_exceeded = False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _call_name(node).endswith(
                "deadline.remaining"
            ):
                has_remaining = True
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Name)
                        and tgt.slice.id == "DEADLINE_HEADER"
                    ):
                        has_stamp = True
            if isinstance(node, ast.Raise) and node.exc is not None:
                name = ""
                if isinstance(node.exc, ast.Call):
                    name = _call_name(node.exc)
                elif isinstance(node.exc, (ast.Name, ast.Attribute)):
                    cur = node.exc
                    name = cur.attr if isinstance(cur, ast.Attribute) else cur.id
                if "DeadlineExceeded" in name:
                    has_exceeded = True
        if not has_remaining:
            yield Finding(self.id, ctx.relpath, 1,
                          "missing deadline.remaining() budget check before the hop")
        if not has_stamp:
            yield Finding(self.id, ctx.relpath, 1,
                          "missing headers[DEADLINE_HEADER] stamp on outgoing RPCs")
        if not has_exceeded:
            yield Finding(self.id, ctx.relpath, 1,
                          "missing DeadlineExceeded raise for a spent budget")

    @staticmethod
    def _authenticates_token(ctx) -> bool:
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and _call_name(node).endswith("headers.get")
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == "TOKEN_HEADER"
            ):
                return True
        return False

    @staticmethod
    def _rebinds(ctx) -> bool:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _call_name(node).endswith(
                "deadline.bind_header"
            ):
                return True
        return False


# ---------------------------------------------------------------------------
# lock-blocking-io
# ---------------------------------------------------------------------------


class LockBlockingIORule(Rule):
    """Blocking I/O inside a `with <lock>:` body.

    A sleep, HTTP call, or file open while holding a mutex convoys every
    other thread that needs it -- the exact pattern behind the refresh-
    daemon redesign in dist/locks.py. Do the I/O outside, publish results
    under the lock."""

    id = "lock-blocking-io"
    title = "blocking I/O while holding a lock"
    scope = ("minio_tpu/storage/", "minio_tpu/dist/", "minio_tpu/control/")

    _LOCK_HINTS = ("lock", "mutex", "_mu", "sem")
    _BLOCKING_EXACT = {
        "time.sleep", "sleep", "open", "subprocess.run", "subprocess.Popen",
        "subprocess.check_call", "subprocess.check_output",
        "socket.create_connection", "tempfile.NamedTemporaryFile",
    }
    _BLOCKING_PREFIX = ("requests.",)
    _BLOCKING_SUFFIX = (".read_file", ".write_all", ".create_file", ".append_file")

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        name = ""
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Call):
            # with self._locks[i] / with lock() styles resolve via the callee
            return self._is_lock_expr(expr.func)
        elif isinstance(expr, ast.Subscript):
            return self._is_lock_expr(expr.value)
        low = name.lower()
        return any(h in low for h in self._LOCK_HINTS)

    def _is_blocking(self, call: ast.Call) -> bool:
        name = _call_name(call)
        if name in self._BLOCKING_EXACT:
            return True
        if any(name.startswith(p) for p in self._BLOCKING_PREFIX):
            return True
        return any(name.endswith(s) for s in self._BLOCKING_SUFFIX)

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                if not any(
                    self._is_lock_expr(item.context_expr) for item in node.items
                ):
                    continue
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        # Deferred work (nested defs) runs after release.
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                        ):
                            break
                        if isinstance(sub, ast.Call) and self._is_blocking(sub):
                            yield Finding(
                                self.id,
                                ctx.relpath,
                                sub.lineno,
                                f"{_call_name(sub)}(...) inside a `with lock:` "
                                "body -- do the I/O outside, publish under "
                                "the lock",
                            )


# ---------------------------------------------------------------------------
# resource-leak
# ---------------------------------------------------------------------------


class ResourceLeakRule(Rule):
    """open()/NamedTemporaryFile() without `with` or a closing try/finally.

    A handle that leaks on the exception path pins an fd (and on staged
    writes, a .tmp file) until GC happens to run -- under load that is fd
    exhaustion. Acceptable shapes: `with open(...)`, `f = open(...)` later
    entered as `with f:` or closed via `f.close()` in a `finally:`, or the
    handle escaping as a return value / argument (ownership transferred)."""

    id = "resource-leak"
    title = "file handle not closed on all paths"
    scope = HOT_PATHS

    _OPENERS = {
        "open", "tempfile.NamedTemporaryFile", "tempfile.TemporaryFile",
        "NamedTemporaryFile", "TemporaryFile", "io.open",
    }

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for fn in ast.walk(ctx.tree):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_function(ctx, fn)

    def _check_function(self, ctx, fn):
        with_exprs: set[int] = set()     # id() of calls used as with-items
        owned: set[int] = set()          # id() of calls whose result escapes
        assigns: dict[int, str] = {}     # id(call) -> simple target name
        calls: list[ast.Call] = []

        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            with_exprs.add(id(sub))
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Call):
                    pass
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Call):
                            owned.add(id(sub))
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        owned.add(id(sub))
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Call):
                            assigns[id(sub)] = tgt.id
            if isinstance(node, ast.Call) and self._is_opener(node):
                calls.append(node)

        closed_names = self._names_closed_or_withed(fn)
        for call in calls:
            if id(call) in with_exprs or id(call) in owned:
                continue
            name = assigns.get(id(call))
            if name is not None and name in closed_names:
                continue
            yield Finding(
                self.id,
                ctx.relpath,
                call.lineno,
                f"{_call_name(call)}(...) result is neither entered as "
                "`with` nor closed in a try/finally -- leaks the handle "
                "on the exception path",
            )

    def _is_opener(self, call: ast.Call) -> bool:
        return _call_name(call) in self._OPENERS

    @staticmethod
    def _names_closed_or_withed(fn) -> set[str]:
        """Names later entered as `with <name>:` anywhere in the function,
        or `.close()`d inside a `finally:` block."""
        names: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Name):
                        names.add(item.context_expr.id)
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "close"
                            and isinstance(sub.func.value, ast.Name)
                        ):
                            names.add(sub.func.value.id)
        return names


# ---------------------------------------------------------------------------
# stage-key
# ---------------------------------------------------------------------------


class StageKeyRule(Rule):
    """Every literal stage mark must name a registered (layer, stage) key.

    control/perf.py declares STAGES (the literal registry) and
    DYNAMIC_STAGE_LAYERS (layers whose stage names are computed at runtime:
    per-peer endpoints, per-storage-API names). A mark outside both would
    silently mint a new unaggregated ledger series no dashboard knows about
    -- register it (and its dashboard row) or fix the typo."""

    id = "stage-key"
    title = "stage mark not registered in control/perf.py"
    scope = ("minio_tpu/",)

    def _load_registry(self, project):
        stages: set[tuple[str, str]] = set()
        dynamic: set[str] = set()
        ctx = project.get(PERF)
        if ctx is None:
            return None, None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            else:
                continue
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "STAGES":
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Tuple) and len(sub.elts) == 2:
                        layer = _str_const(sub.elts[0])
                        stage = _str_const(sub.elts[1])
                        if layer is not None and stage is not None:
                            stages.add((layer, stage))
            elif tgt.id == "DYNAMIC_STAGE_LAYERS":
                for sub in ast.walk(value):
                    s = _str_const(sub)
                    if s is not None:
                        dynamic.add(s)
        return (stages or None), (dynamic or None)

    def check(self, project: ProjectContext):
        stages, dynamic = self._load_registry(project)
        if stages is None:
            ctx = project.get(PERF)
            if ctx is not None:
                yield Finding(
                    self.id, PERF, 1,
                    "STAGES registry literal not found in control/perf.py",
                )
            return
        dynamic = dynamic or set()
        layers = {l for l, _ in stages} | dynamic
        for ctx in project.iter_files("minio_tpu/"):
            if ctx.relpath in (PERF, "minio_tpu/control/tracing.py"):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if name.endswith("tracing.span") or name.endswith("tracing.root_span"):
                    if len(node.args) < 2:
                        continue
                    stage_arg, layer_arg = node.args[0], node.args[1]
                elif name.endswith("ledger.record"):
                    if len(node.args) < 2:
                        continue
                    layer_arg, stage_arg = node.args[0], node.args[1]
                else:
                    continue
                layer = _str_const(layer_arg)
                stage = _str_const(stage_arg)
                if layer is None:
                    continue  # computed layer: nothing checkable statically
                if stage is None:
                    if layer not in layers:
                        yield Finding(
                            self.id, ctx.relpath, node.lineno,
                            f"dynamic stage mark in unregistered layer "
                            f"{layer!r} -- add it to DYNAMIC_STAGE_LAYERS "
                            "in control/perf.py",
                        )
                elif (layer, stage) not in stages and layer not in dynamic:
                    yield Finding(
                        self.id, ctx.relpath, node.lineno,
                        f"stage key ({layer!r}, {stage!r}) not in the "
                        "control/perf.py STAGES registry",
                    )


# ---------------------------------------------------------------------------
# metrics-rendered
# ---------------------------------------------------------------------------


class MetricsRenderedRule(Rule):
    """Counters bumped in control/degrade.py and control/perf.py must be
    rendered by control/metrics.py.

    A counter nobody exports is a measurement nobody sees: the increment
    costs a lock on the hot path and buys zero observability. Every public
    `self.<name> += ...` / keyed-dict bump in DegradeStats,
    SlowRequestCapture, the profiling plane's CopyLedger, the
    self-measurement plane's SelfTestStats, the flight recorder, the
    pub/sub hubs' drop accounting, and the webhook log sink's queue
    counters must appear (as a string key or attribute) in the exposition
    renderer."""

    id = "metrics-rendered"
    title = "counter incremented but never rendered in control/metrics.py"
    scope = (DEGRADE, PERF, PROFILER, SELFTEST, POOLMGR, REBALANCE, FLIGHT,
             LOGGING, PUBSUB)

    _COUNTER_CLASSES = {
        "DegradeStats", "SlowRequestCapture", "CopyLedger", "SelfTestStats",
        "PoolLifecycleStats", "ThrottleBudget", "FlightRecorder", "PubSub",
        "WebhookTarget",
    }

    def _counters(self, ctx) -> list[tuple[str, int]]:
        out: list[tuple[str, int]] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if node.name not in self._COUNTER_CLASSES:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.AugAssign) or not isinstance(
                    sub.op, ast.Add
                ):
                    continue
                tgt = sub.target
                name = None
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    name = tgt.attr
                elif (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Attribute)
                    and isinstance(tgt.value.value, ast.Name)
                    and tgt.value.value.id == "self"
                ):
                    name = tgt.value.attr
                if name and not name.startswith("_"):
                    out.append((name, sub.lineno))
        # keyed bumps written as self.d[k] = self.d.get(k, 0) + 1
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if (
                isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Attribute)
                and isinstance(tgt.value.value, ast.Name)
                and tgt.value.value.id == "self"
                and not tgt.value.attr.startswith("_")
                and isinstance(node.value, ast.BinOp)
                and isinstance(node.value.op, ast.Add)
            ):
                out.append((tgt.value.attr, node.lineno))
        return out

    @staticmethod
    def _rendered_tokens(metrics_ctx) -> set[str]:
        tokens: set[str] = set()
        for node in ast.walk(metrics_ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                tokens.add(node.value)
            if isinstance(node, ast.Attribute):
                tokens.add(node.attr)
        return tokens

    def check(self, project: ProjectContext):
        metrics_ctx = project.get(METRICS)
        if metrics_ctx is None:
            return
        tokens = self._rendered_tokens(metrics_ctx)
        seen: set[str] = set()
        for relpath in self.scope:
            ctx = project.get(relpath)
            if ctx is None:
                continue
            for name, lineno in self._counters(ctx):
                if name in seen:
                    continue
                seen.add(name)
                if name not in tokens:
                    yield Finding(
                        self.id, ctx.relpath, lineno,
                        f"counter {name!r} is incremented here but "
                        "control/metrics.py never renders it",
                    )


# ---------------------------------------------------------------------------
# typed-errors
# ---------------------------------------------------------------------------


class TypedErrorsRule(Rule):
    """API handlers must raise typed errors, never `raise Exception(...)`.

    api/errors.py maps exception TYPES onto S3 wire codes; an untyped raise
    can only ever surface as a 500 InternalError with a leaked str(e). Use
    S3Error / utils.errors types so the client sees the right code."""

    id = "typed-errors"
    title = "untyped raise in an API module"
    scope = ("minio_tpu/api/",)

    _UNTYPED = {"Exception", "BaseException", "RuntimeError"}

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                    name = exc.func.id
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name in self._UNTYPED:
                    yield Finding(
                        self.id, ctx.relpath, node.lineno,
                        f"raise {name}(...) in an API module -- raise "
                        "S3Error or a typed utils.errors class so the "
                        "client sees a real S3 code",
                    )


# ---------------------------------------------------------------------------
# unlocked-global
# ---------------------------------------------------------------------------


class UnlockedGlobalRule(Rule):
    """Mutable module globals mutated outside a lock.

    A module-level dict/list/set written from request or worker threads
    without a lock is a check-then-act race (the `_HASH_SELECT` class of
    bug). Either guard every mutation with a module lock, or mark the
    binding `# mtpulint: immutable` when it is write-once at import time."""

    id = "unlocked-global"
    title = "mutable module global mutated without a lock"
    scope = ("minio_tpu/",)

    _MUTABLE_CTORS = {
        "dict", "list", "set", "OrderedDict", "defaultdict", "deque",
        "collections.OrderedDict", "collections.defaultdict",
        "collections.deque",
    }
    _MUTATORS = {
        "append", "add", "update", "pop", "popitem", "clear", "extend",
        "insert", "remove", "discard", "setdefault", "appendleft",
    }
    _LOCK_HINTS = ("lock", "mutex", "_mu", "sem")

    def _module_mutables(self, ctx) -> dict[str, int]:
        """Module-level `NAME = {}/[]/set()/...` bindings -> lineno."""
        out: dict[str, int] = {}
        body = getattr(ctx.tree, "body", [])
        for node in body:
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(value, ast.Call)
                and _call_name(value) in self._MUTABLE_CTORS
            )
            if not mutable:
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name) and not self._marked_immutable(
                    ctx, node.lineno
                ):
                    out[tgt.id] = node.lineno
        return out

    @staticmethod
    def _marked_immutable(ctx, lineno: int) -> bool:
        lines = ctx.lines
        if 1 <= lineno <= len(lines) and "immutable" in lines[lineno - 1]:
            return True
        return lineno >= 2 and "immutable" in lines[lineno - 2]

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        name = ""
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Subscript):
            return self._is_lock_expr(expr.value)
        low = name.lower()
        return any(h in low for h in self._LOCK_HINTS)

    def _mutation_at(self, node, names: set[str]):
        """(name, lineno) when THIS node (not its subtree) mutates a
        watched global: subscript assign/del/augassign, or a mutator-method
        call (`g.append(...)`, `g.setdefault(...)`, ...)."""
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in tgts:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in names
                ):
                    return (tgt.value.id, node.lineno)
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in names
                ):
                    return (tgt.value.id, node.lineno)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in self._MUTATORS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in names
        ):
            return (node.func.value.id, node.lineno)
        return None

    def _mutations(self, fn, names: set[str]):
        """(name, lineno, locked) for every mutation of a watched global
        inside `fn`, where locked = lexically inside a `with <lock>:` body
        at any nesting depth. Each node is visited exactly once, carrying
        the innermost lock state down the tree."""

        def scan(node, locked: bool):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                body_locked = locked or any(
                    self._is_lock_expr(i.context_expr) for i in node.items
                )
                for item in node.items:
                    yield from scan(item.context_expr, locked)
                for child in node.body:
                    yield from scan(child, body_locked)
                return
            hit = self._mutation_at(node, names)
            if hit is not None:
                yield (*hit, locked)
            for child in ast.iter_child_nodes(node):
                yield from scan(child, locked)

        for stmt in fn.body:
            yield from scan(stmt, False)

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            mutables = self._module_mutables(ctx)
            if not mutables:
                continue
            names = set(mutables)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for name, lineno, locked in self._mutations(node, names):
                    if not locked:
                        yield Finding(
                            self.id, ctx.relpath, lineno,
                            f"module global {name!r} mutated outside a "
                            "lock -- guard it, or mark the binding "
                            "`# mtpulint: immutable` if write-once",
                        )


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------


SANITIZER = "minio_tpu/control/sanitizer.py"

_LOCK_HINTS = ("lock", "mutex", "_mu", "sem")


def _class_spans(ctx) -> list[tuple[int, int, str]]:
    spans = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            spans.append((node.lineno, node.end_lineno or node.lineno, node.name))
    return spans


def _enclosing_class(spans, lineno: int) -> str | None:
    best = None
    for lo, hi, name in spans:
        if lo <= lineno <= hi and (best is None or lo > best[0]):
            best = (lo, name)
    return best[1] if best else None


class LockOrderRule(Rule):
    """Nested `with lock:` pairs must agree on one global acquisition order.

    The static half of mtpusan's lock-order graph: every lexically nested
    lock pair (`with A: ... with B:`) contributes an A->B edge, named by the
    qualified form `ClassName.attr` (module locks: `filestem.name`). Two
    checks over the cross-module digraph:
      * a cycle (A->B somewhere, B->A somewhere else) is a potential
        deadlock even if no run has wedged yet;
      * a pair that contradicts the declared LOCK_ORDER table in
        control/sanitizer.py (outermost first) is a hierarchy violation.
    The runtime sanitizer catches orders composed dynamically through
    calls; this rule catches the lexical ones before the code ever runs."""

    id = "lock-order"
    title = "nested lock acquisition order inverted"
    scope = ("minio_tpu/",)

    def _lock_name(self, expr: ast.AST, ctx, spans, lineno: int) -> str | None:
        """Qualified lock-class name for a with-item, or None if not a lock
        (or not statically nameable)."""
        if isinstance(expr, ast.Subscript):
            return self._lock_name(expr.value, ctx, spans, lineno)
        attr = None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            attr = expr.attr
            owner = _enclosing_class(spans, lineno)
            if owner is None:
                return None
        elif isinstance(expr, ast.Name):
            attr = expr.id
            owner = ctx.relpath.rsplit("/", 1)[-1][:-3]  # file stem
        else:
            return None
        low = attr.lower()
        if not any(h in low for h in _LOCK_HINTS):
            return None
        return f"{owner}.{attr}"

    def _declared_order(self, project) -> list[str]:
        ctx = project.get(SANITIZER)
        if ctx is None:
            return []
        for node in ast.walk(ctx.tree):
            tgt = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            if isinstance(tgt, ast.Name) and tgt.id == "LOCK_ORDER":
                return [
                    s for s in (
                        _str_const(e) for e in ast.walk(value)
                        if isinstance(e, ast.Constant)
                    ) if s
                ]
        return []

    def _edges(self, project):
        """Every lexically nested (outer, inner) lock pair in scope, with
        the inner acquisition's location."""
        for ctx in project.iter_files(*self.scope):
            if ctx.relpath == SANITIZER:
                continue
            spans = _class_spans(ctx)

            def scan(node, held):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    inner_held = list(held)
                    for item in node.items:
                        name = self._lock_name(
                            item.context_expr, ctx, spans, node.lineno
                        )
                        if name is not None:
                            for outer in inner_held:
                                yield (outer, name, ctx, node.lineno)
                            inner_held.append(name)
                    for child in node.body:
                        yield from scan(child, inner_held)
                    return
                # A nested def's body runs later, outside these withs.
                child_held = (
                    []
                    if isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                    )
                    else held
                )
                for child in ast.iter_child_nodes(node):
                    yield from scan(child, child_held)

            yield from scan(ctx.tree, [])

    def check(self, project: ProjectContext):
        order = self._declared_order(project)
        rank = {name: i for i, name in enumerate(order)}
        graph: dict[str, set[str]] = {}
        first_at: dict[tuple[str, str], tuple] = {}
        for outer, inner, ctx, lineno in self._edges(project):
            if outer == inner:
                continue
            graph.setdefault(outer, set()).add(inner)
            first_at.setdefault((outer, inner), (ctx, lineno))
            if outer in rank and inner in rank and rank[outer] > rank[inner]:
                yield Finding(
                    self.id, ctx.relpath, lineno,
                    f"acquires {inner!r} while holding {outer!r}, but "
                    "LOCK_ORDER in control/sanitizer.py declares "
                    f"{inner!r} before {outer!r} -- invert the nesting or "
                    "amend the declared order",
                )
        seen_cycles: set[frozenset] = set()
        for (a, b), (ctx, lineno) in sorted(
            first_at.items(), key=lambda kv: (kv[1][0].relpath, kv[1][1])
        ):
            path = self._find_path(graph, b, a)
            if path is None:
                continue
            cycle = frozenset([a] + path)
            if cycle in seen_cycles:
                continue
            seen_cycles.add(cycle)
            yield Finding(
                self.id, ctx.relpath, lineno,
                "lock-order cycle: " + " -> ".join([a] + path)
                + " -- threads taking these in opposite orders can "
                "deadlock; pick one global order",
            )

    @staticmethod
    def _find_path(graph, src: str, dst: str) -> list[str] | None:
        prev = {src: src}
        frontier = [src]
        while frontier:
            nxt = []
            for u in frontier:
                for v in graph.get(u, ()):
                    if v in prev:
                        continue
                    prev[v] = u
                    if v == dst:
                        path = [v]
                        while path[-1] != src:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    nxt.append(v)
            frontier = nxt
        return None


# ---------------------------------------------------------------------------
# unjoined-thread
# ---------------------------------------------------------------------------


class UnjoinedThreadRule(Rule):
    """`Thread(daemon=True)` without a registered stop/join path.

    daemon=True means "the interpreter may kill this mid-write at exit" --
    acceptable only for workers that also have an orderly shutdown. A
    daemon thread started in a function that never joins anything, inside a
    class with no stop/close/shutdown method that joins, is a worker nobody
    can ever wait out: tests leak it, teardown races it, and mtpusan's
    leaked-thread detector will fire at runtime. Give the owner a stop path
    that joins, or suppress with the justification for a process-lifetime
    daemon."""

    id = "unjoined-thread"
    title = "daemon thread started without a stop/join path"
    scope = ("minio_tpu/",)

    STOP_NAMES = {
        "stop", "close", "shutdown", "stop_all", "cancel", "join",
        "wait_all", "drain",
    }

    @staticmethod
    def _is_thread_ctor(call: ast.Call) -> bool:
        name = _call_name(call)
        return name == "Thread" or name.endswith(".Thread")

    @staticmethod
    def _daemon_true(call: ast.Call) -> bool:
        for kw in call.keywords:
            if (
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            ):
                return True
        return False

    @staticmethod
    def _has_join(node) -> bool:
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "join"
            ):
                return True
        return False

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            fn_spans = [
                (n.lineno, n.end_lineno or n.lineno, n)
                for n in ast.walk(ctx.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            cls_spans = [
                (n.lineno, n.end_lineno or n.lineno, n)
                for n in ast.walk(ctx.tree)
                if isinstance(n, ast.ClassDef)
            ]
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call) or not self._is_thread_ctor(node):
                    continue
                if not self._daemon_true(node):
                    continue
                if self._joined_somewhere(node.lineno, fn_spans, cls_spans, ctx):
                    continue
                yield Finding(
                    self.id, ctx.relpath, node.lineno,
                    "Thread(daemon=True) started here but neither this "
                    "function nor any stop/close/shutdown method on the "
                    "owning class ever join()s -- register a join path, or "
                    "suppress with the process-lifetime justification",
                )

    def _joined_somewhere(self, lineno, fn_spans, cls_spans, ctx) -> bool:
        fn = self._innermost(fn_spans, lineno)
        if fn is not None and self._has_join(fn):
            return True
        cls = self._innermost(cls_spans, lineno)
        if cls is not None:
            for stmt in cls.body:
                if (
                    isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt.name in self.STOP_NAMES
                    and self._has_join(stmt)
                ):
                    return True
            return False
        if fn is None:
            # Module-level start: any module-level join path counts.
            return self._has_join(ctx.tree)
        return False

    @staticmethod
    def _innermost(spans, lineno):
        best = None
        for lo, hi, node in spans:
            if lo <= lineno <= hi and (best is None or lo > best[0]):
                best = (lo, node)
        return best[1] if best else None


# ---------------------------------------------------------------------------
# cond-wait-loop
# ---------------------------------------------------------------------------


class CondWaitLoopRule(Rule):
    """`Condition.wait()` must sit inside a `while predicate:` loop.

    Spurious wakeups and stolen notifies are real: a bare `if pred: wait()`
    (or a naked wait) resumes with the predicate false and corrupts
    whatever the waiter does next. Re-check the predicate in a `while`
    loop, or use `wait_for(predicate)` which loops internally. Only names
    assigned a Condition are checked -- `Event.wait` is level-triggered
    and exempt."""

    id = "cond-wait-loop"
    title = "Condition.wait() outside a while-predicate loop"
    scope = ("minio_tpu/",)

    _COND_CTORS = {
        "threading.Condition", "Condition", "san_condition",
    }

    def _condition_names(self, ctx) -> set[str]:
        """Attr/var names bound to a Condition anywhere in the file."""
        names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Call)
                and _call_name(node.value) in self._COND_CTORS
            ):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                elif isinstance(tgt, ast.Attribute):
                    names.add(tgt.attr)
        return names

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            conds = self._condition_names(ctx)
            if not conds:
                continue

            def scan(node, in_while: bool):
                if isinstance(node, ast.While):
                    for child in ast.iter_child_nodes(node):
                        yield from scan(child, True)
                    return
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    # A nested def's body executes outside this loop.
                    for child in ast.iter_child_nodes(node):
                        yield from scan(child, False)
                    return
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"
                    and not in_while
                ):
                    holder = node.func.value
                    hname = (
                        holder.attr if isinstance(holder, ast.Attribute)
                        else holder.id if isinstance(holder, ast.Name) else None
                    )
                    if hname in conds:
                        yield node
                for child in ast.iter_child_nodes(node):
                    yield from scan(child, in_while)

            for call in scan(ctx.tree, False):
                yield Finding(
                    self.id, ctx.relpath, call.lineno,
                    "Condition.wait() outside a `while predicate:` loop -- "
                    "spurious wakeups break this; loop on the predicate or "
                    "use wait_for()",
                )


# ---------------------------------------------------------------------------
# shared-publish
# ---------------------------------------------------------------------------


class SharedPublishRule(Rule):
    """Read-modify-write on shared state from a worker thread, outside any
    lock.

    Methods reachable from a `Thread(target=self.X)` run concurrently with
    request threads; `self.counter += 1` there is a lost-update race (the
    GIL makes single writes atomic, but += is load/add/store). Guard the
    update with a lock. Plain assignments and list.append are exempt --
    they are single atomic publishes under the GIL."""

    id = "shared-publish"
    title = "unlocked read-modify-write on shared state in a worker thread"
    scope = ("minio_tpu/",)

    @staticmethod
    def _worker_methods(cls: ast.ClassDef) -> set[str]:
        """Method names reachable from a Thread(target=self.X) started
        anywhere in the class, expanded transitively through self.Y()
        calls."""
        methods = {
            s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        roots: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if not (name == "Thread" or name.endswith(".Thread")):
                continue
            for kw in node.keywords:
                if (
                    kw.arg == "target"
                    and isinstance(kw.value, ast.Attribute)
                    and isinstance(kw.value.value, ast.Name)
                    and kw.value.value.id == "self"
                    and kw.value.attr in methods
                ):
                    roots.add(kw.value.attr)
        # Transitive closure through self.method() calls.
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            for node in ast.walk(methods[m]):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in methods
                    and node.func.attr not in roots
                ):
                    roots.add(node.func.attr)
                    frontier.append(node.func.attr)
        return roots

    @staticmethod
    def _is_lock_expr(expr: ast.AST) -> bool:
        name = ""
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Subscript):
            return SharedPublishRule._is_lock_expr(expr.value)
        low = name.lower()
        return any(h in low for h in _LOCK_HINTS)

    @classmethod
    def _shared_target(cls, node: ast.AugAssign, globals_declared: set[str]):
        tgt = node.target
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
        ):
            return f"self.{tgt.attr}"
        if (
            isinstance(tgt, ast.Subscript)
            and isinstance(tgt.value, ast.Attribute)
            and isinstance(tgt.value.value, ast.Name)
            and tgt.value.value.id == "self"
        ):
            return f"self.{tgt.value.attr}[...]"
        if isinstance(tgt, ast.Name) and tgt.id in globals_declared:
            return tgt.id
        return None

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for cls in ast.walk(ctx.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                workers = self._worker_methods(cls)
                if not workers:
                    continue
                methods = {
                    s.name: s for s in cls.body
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                for name in sorted(workers):
                    yield from self._check_method(ctx, methods[name])

    def _check_method(self, ctx, fn):
        globals_declared: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)

        def scan(node, locked: bool):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                body_locked = locked or any(
                    self._is_lock_expr(i.context_expr) for i in node.items
                )
                for child in node.body:
                    yield from scan(child, body_locked)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return
            if isinstance(node, ast.AugAssign) and not locked:
                what = self._shared_target(node, globals_declared)
                if what is not None:
                    yield (node, what)
            for child in ast.iter_child_nodes(node):
                yield from scan(child, locked)

        for stmt in fn.body:
            for node, what in scan(stmt, False):
                yield Finding(
                    self.id, ctx.relpath, node.lineno,
                    f"{what} read-modify-written in worker method "
                    f"{fn.name!r} outside any lock -- += is load/add/store, "
                    "concurrent updates lose increments; guard it",
                )


# ---------------------------------------------------------------------------
# unsynced-commit
# ---------------------------------------------------------------------------


class UnsyncedCommitRule(Rule):
    """Atomic rename-commit without a durability barrier in the same function.

    The crash-consistency plane (storage/local.py, MTPU_FSYNC) publishes
    every durable artifact the same way: write a staged tmp file, sync it,
    `os.replace`/`os.rename` into place, sync the parent directory. An
    `os.replace` in storage/ or object/ whose enclosing function never calls
    any sync primitive (os.fsync, os.fdatasync, the `_sync_*` helpers) is a
    commit that a crash can tear: the rename may hit disk before the data
    it publishes. Add the barrier (gated on the fsync mode where the path
    is hot), or suppress with the justification for a best-effort file
    (e.g. a rebuildable cache entry)."""

    id = "unsynced-commit"
    title = "rename/replace commit without a sync barrier in the same function"
    scope = ("minio_tpu/storage/", "minio_tpu/object/")

    _COMMIT_CALLS = {"os.replace", "os.rename", "os.renames"}
    # Names that merely *mention* sync without performing one.
    _NON_BARRIER = {"fsync_mode"}

    @classmethod
    def _is_barrier(cls, name: str) -> bool:
        last = name.rsplit(".", 1)[-1]
        if last in cls._NON_BARRIER:
            return False
        return "sync" in last.lower()

    @classmethod
    def _shallow(cls, node: ast.AST):
        """Pre-order walk that stays inside one function scope: nested defs
        get their own pass, so each commit is judged against the barriers
        of its innermost function only."""
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        for child in ast.iter_child_nodes(node):
            yield from cls._shallow(child)

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for fn in ast.walk(ctx.tree):
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                commits: list[ast.Call] = []
                barriered = False
                for stmt in fn.body:
                    for node in self._shallow(stmt):
                        if not isinstance(node, ast.Call):
                            continue
                        name = _call_name(node)
                        if name in self._COMMIT_CALLS:
                            commits.append(node)
                        elif self._is_barrier(name):
                            barriered = True
                if barriered:
                    continue
                for call in commits:
                    yield Finding(
                        self.id, ctx.relpath, call.lineno,
                        f"{_call_name(call)}(...) publishes a file but "
                        f"{fn.name!r} never calls a sync barrier -- a crash "
                        "can commit the rename before the data; sync the "
                        "staged file (and parent dir), or suppress with the "
                        "best-effort justification",
                    )


# ---------------------------------------------------------------------------
# hot-path-copy
# ---------------------------------------------------------------------------


class HotPathCopyRule(Rule):
    """Byte-copying constructs on the zero-copy data plane.

    PR 9 rebuilt the socket -> sigv4 -> erasure-stage -> shard-fanout
    pipeline around pooled buffers and memoryviews; a casual `bytes(view)`,
    `b"".join(parts)`, or `buf += chunk` quietly reintroduces an
    O(object size) copy that the copy ledger then reports as a regression.
    Sites that MUST materialize (header text being decoded, inline blobs
    outliving a pooled window, client-side test helpers, legacy whole-file
    bitrot algorithms) carry a justified
    `# mtpulint: disable=hot-path-copy -- why`."""

    id = "hot-path-copy"
    title = "byte-copying construct on the zero-copy data plane"
    scope = (
        "minio_tpu/api/streaming.py",
        "minio_tpu/object/erasure.py",
        "minio_tpu/object/memcache.py",
        "minio_tpu/storage/local.py",
    )

    @staticmethod
    def _bytesish(value: ast.AST | None) -> bool:
        """Is this initializer a byte accumulator? (b"..." literal, or a
        bytes()/bytearray() construction.)"""
        if isinstance(value, ast.Constant) and isinstance(value.value, bytes):
            return True
        return (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("bytes", "bytearray")
        )

    @classmethod
    def _shallow(cls, node: ast.AST):
        """Pre-order walk that does not descend into nested function scopes
        (each scope tracks its own accumulator names)."""
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        for child in ast.iter_child_nodes(node):
            yield from cls._shallow(child)

    def _check_calls(self, ctx: FileContext):
        parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # b"".join(parts): materializes a contiguous copy of every part.
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "join"
                and isinstance(func.value, ast.Constant)
                and isinstance(func.value.value, bytes)
            ):
                yield Finding(
                    self.id, ctx.relpath, node.lineno,
                    'b"".join(...) copies every part into one contiguous '
                    "buffer -- hand the pieces to a scatter write "
                    "(append_iov) or stream them",
                )
                continue
            # bytes(buffer): a full copy of whatever the buffer holds.
            if (
                isinstance(func, ast.Name)
                and func.id == "bytes"
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                parent = parents.get(node)
                if isinstance(parent, ast.Attribute) and parent.attr == "decode":
                    continue  # small header text being decoded, not payload
                yield Finding(
                    self.id, ctx.relpath, node.lineno,
                    "bytes(...) copies the underlying buffer -- pass the "
                    "memoryview through, or justify the materialization",
                )

    def _check_augments(self, ctx: FileContext):
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            body = scope.body if not isinstance(scope, ast.Module) else scope.body
            nodes = [n for stmt in body for n in self._shallow(stmt)]
            accumulators = {
                t.id
                for n in nodes
                if isinstance(n, ast.Assign) and self._bytesish(n.value)
                for t in n.targets
                if isinstance(t, ast.Name)
            }
            for n in nodes:
                if (
                    isinstance(n, ast.AugAssign)
                    and isinstance(n.op, ast.Add)
                    and isinstance(n.target, ast.Name)
                    and n.target.id in accumulators
                ):
                    yield Finding(
                        self.id, ctx.relpath, n.lineno,
                        f"{n.target.id!r} += concatenation re-copies the "
                        "accumulated payload -- collect views and scatter-"
                        "write, or stream through the pooled pipeline",
                    )

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            yield from self._check_calls(ctx)
            yield from self._check_augments(ctx)


# ---------------------------------------------------------------------------
# bufsan static half: buffer-lifetime dataflow over the zero-copy plane.
# The runtime complement lives in minio_tpu/control/bufsan.py (MTPU_BUFSAN=1);
# these rules prove the discipline about paths the sanitized replay never ran.
# ---------------------------------------------------------------------------

STORAGE_IFACE = "minio_tpu/storage/interface.py"

# Everywhere pooled buffers flow today, plus the control-plane probe that
# borrows the pool (selftest netperf) and utils/ itself.
BUFFER_PATHS = HOT_PATHS + (
    "minio_tpu/control/selftest.py",
    "minio_tpu/utils/",
)


def _is_poolish(expr: ast.AST) -> bool:
    """Does this expression look like a BufferPool? Matched by the naming
    convention the tree actually uses -- `pool`, `self._pool`,
    `window_pool()`, `shard_pool()`, `BufferPool(...)` -- so `lk.acquire()`
    (locks) and `sem.acquire()` (semaphores) never enter the dataflow."""
    if isinstance(expr, ast.Name):
        return "pool" in expr.id.lower()
    if isinstance(expr, ast.Attribute):
        return "pool" in expr.attr.lower()
    if isinstance(expr, ast.Call):
        last = _call_name(expr).rsplit(".", 1)[-1]
        return "pool" in last.lower() or last == "BufferPool"
    return False


# Both end the buffer's life: release() recycles the storage, discard()
# drops it (exception paths where a traceback may pin foreign views).
RELEASE_METHODS = ("release", "discard")


def _is_buffer_acquire(value: ast.AST | None) -> bool:
    """`<pool>.acquire(...)` or a `*acquire*buf*` helper call."""
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    if isinstance(func, ast.Attribute) and func.attr == "acquire":
        return _is_poolish(func.value)
    if isinstance(func, ast.Name):
        low = func.id.lower()
        return "acquire" in low and "buf" in low
    return False


def _shallow_nodes(root: ast.AST):
    """Pre-order walk of a function body that does not descend into nested
    function scopes (each scope owns its own buffer lifecycle)."""
    for stmt in root.body:
        stack = [stmt]
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.extend(reversed(list(ast.iter_child_nodes(node))))


def _method_call(node: ast.AST, name: str, method: str) -> bool:
    """Is `node` the call `name.method(...)`?"""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == method
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == name
    )


def _escaping_names(expr: ast.AST | None) -> set[str]:
    """Names whose VALUE escapes through `expr` (returned/stored as-is):
    direct names and names inside tuple/list/dict/set/conditional
    containers. Does NOT descend into calls -- `bytes(v)` / `len(v)`
    compute FROM the view, they do not leak it."""
    out: set[str] = set()
    if expr is None:
        return out
    if isinstance(expr, ast.Name):
        out.add(expr.id)
    elif isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        for e in expr.elts:
            out |= _escaping_names(e)
    elif isinstance(expr, ast.Dict):
        for e in expr.values:
            out |= _escaping_names(e)
    elif isinstance(expr, ast.Starred):
        out |= _escaping_names(expr.value)
    elif isinstance(expr, ast.IfExp):
        out |= _escaping_names(expr.body) | _escaping_names(expr.orelse)
    elif isinstance(expr, ast.NamedExpr):
        out |= _escaping_names(expr.value)
    return out


class _BufferFlow:
    """Per-function buffer-lifetime facts shared by the three bufsan rules:
    which names were acquired from a pool, where they are released (and
    whether any release sits on an exception edge), which were retained,
    and which were handed off (bare argument to a call, returned, yielded,
    or stored into an attribute/container)."""

    CONTAINER_METHODS = {"append", "add", "put", "put_nowait", "appendleft"}

    def __init__(self, func: ast.AST):
        self.func = func
        self.acquired: dict[str, int] = {}          # name -> first acquire line
        self.releases: dict[str, list[ast.Call]] = {}
        self.protected: set[str] = set()            # release on an except/finally edge
        self.retained: set[str] = set()
        self.transferred: set[str] = set()
        self._collect()

    def _collect(self) -> None:
        nodes = list(_shallow_nodes(self.func))
        for node in nodes:
            if isinstance(node, ast.Assign) and _is_buffer_acquire(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.acquired.setdefault(t.id, node.lineno)
        if not self.acquired:
            return
        for node in nodes:
            if isinstance(node, ast.Call):
                self._note_call(node)
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                for name in _escaping_names(node.value):
                    if name in self.acquired:
                        self.transferred.add(name)
            elif isinstance(node, ast.Assign):
                if any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in node.targets
                ):
                    for name in _escaping_names(node.value):
                        if name in self.acquired:
                            self.transferred.add(name)
        # Exception-edge coverage: a release reachable from an except
        # handler or finally body covers the raise paths of its try.
        for node in nodes:
            if not isinstance(node, ast.Try):
                continue
            edges = list(node.finalbody)
            for h in node.handlers:
                edges.extend(h.body)
            for stmt in edges:
                for sub in ast.walk(stmt):
                    for name in self.acquired:
                        if any(
                            _method_call(sub, name, m) for m in RELEASE_METHODS
                        ):
                            self.protected.add(name)

    def _note_call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            owner = func.value.id
            if owner in self.acquired:
                if func.attr in RELEASE_METHODS:
                    self.releases.setdefault(owner, []).append(node)
                    return
                if func.attr == "retain":
                    self.retained.add(owner)
                    return
                if func.attr == "view":
                    return  # view creation is not a handoff of the buffer
        # A tracked buffer passed as a bare argument is an ownership
        # transfer: `_stream_windows(data, pool, pb, filled)`,
        # `_Window(view, pb)`, `bufs.add(pb)` all hand the release
        # obligation to the callee.
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in self.acquired:
                self.transferred.add(arg.id)


def _iter_functions(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class ReleaseOnAllPathsRule(Rule):
    """Every pooled-buffer acquire() must reach release() on every path.

    The pool's pigeonhole (outstanding == 0 after every request) only holds
    when each `pb = pool.acquire()` either releases on the exception edges
    too -- a release inside an `except`/`finally` -- or hands the buffer
    off (bare argument to a call, returned, yielded, stored) to an owner
    that takes over the obligation. A straight-line release with neither is
    one raise away from leaking the window forever."""

    id = "release-on-all-paths"
    title = "pooled buffer acquire() without release on every path"
    scope = BUFFER_PATHS

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for func in _iter_functions(ctx):
                flow = _BufferFlow(func)
                for name, lineno in flow.acquired.items():
                    if name in flow.retained or name in flow.transferred:
                        continue
                    if not flow.releases.get(name):
                        yield Finding(
                            self.id, ctx.relpath, lineno,
                            f"{name!r} is acquired from a pool but never "
                            "released or handed off in this function -- "
                            "the window leaks and outstanding never drains",
                        )
                    elif name not in flow.protected:
                        yield Finding(
                            self.id, ctx.relpath, lineno,
                            f"{name!r} is only released on the straight-line "
                            "path -- a raise between acquire() and release() "
                            "leaks the window; release in a finally/except "
                            "or hand the buffer off",
                        )


class DoubleReleaseRule(Rule):
    """release() twice on the same pooled buffer.

    The second release corrupts whoever re-acquired the storage (or raises
    under the pool's refcount guard, torching an unrelated request). Two
    shapes: back-to-back unconditional releases in one statement list, and
    a try-body release repeated unguarded in the finally (the correct
    pattern rebinds `pb = None` after the handoff and guards the finally
    with `if pb is not None`)."""

    id = "double-release"
    title = "pooled buffer released twice on one path"
    scope = BUFFER_PATHS

    def _sequential(self, flow: _BufferFlow):
        """Two top-level `name.release()` statements in one body list with
        no rebind/retain between them."""
        for node in [flow.func, *_shallow_nodes(flow.func)]:
            for field in ("body", "orelse", "finalbody"):
                body = getattr(node, field, None)
                if not isinstance(body, list):
                    continue
                seen: set[str] = set()
                for stmt in body:
                    if isinstance(stmt, ast.Assign):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                seen.discard(t.id)
                        continue
                    if not isinstance(stmt, ast.Expr):
                        continue
                    call = stmt.value
                    for name in flow.acquired:
                        if _method_call(call, name, "retain"):
                            seen.discard(name)
                        elif any(
                            _method_call(call, name, m) for m in RELEASE_METHODS
                        ):
                            if name in seen:
                                yield name, stmt.lineno
                            seen.add(name)

    def _try_finally(self, flow: _BufferFlow):
        """Unconditional release in a try body + unguarded release at the
        top of its finally: both run on the success path."""
        for node in _shallow_nodes(flow.func):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            for name in flow.acquired:
                in_try = any(
                    isinstance(stmt, ast.Expr)
                    and any(
                        _method_call(stmt.value, name, m)
                        for m in RELEASE_METHODS
                    )
                    for stmt in node.body
                )
                rebound = any(
                    isinstance(stmt, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in stmt.targets
                    )
                    for stmt in node.body
                )
                if not in_try or rebound:
                    continue
                for stmt in node.finalbody:
                    if isinstance(stmt, ast.Expr) and any(
                        _method_call(stmt.value, name, m)
                        for m in RELEASE_METHODS
                    ):
                        yield name, stmt.lineno

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for func in _iter_functions(ctx):
                flow = _BufferFlow(func)
                if not flow.acquired:
                    continue
                seen_lines: set[tuple[str, int]] = set()
                for name, lineno in self._sequential(flow):
                    seen_lines.add((name, lineno))
                    yield Finding(
                        self.id, ctx.relpath, lineno,
                        f"{name!r} released twice on the same path -- the "
                        "second release corrupts the refcount of whoever "
                        "re-acquired the storage",
                    )
                for name, lineno in self._try_finally(flow):
                    if (name, lineno) in seen_lines:
                        continue
                    yield Finding(
                        self.id, ctx.relpath, lineno,
                        f"{name!r} released in the try body AND unguarded in "
                        "its finally -- rebind to None after the handoff and "
                        "guard the finally with `if {0} is not None`".format(name),
                    )


class ViewEscapeRule(Rule):
    """A memoryview over a pooled buffer escaping its owner's scope.

    bufpool's contract: views must not outlive the buffer's last release.
    A view that is returned/yielded, stored on `self` or in a container,
    shipped to a thread/lane submit, or captured by a closure survives
    past the release that recycles the storage underneath it -- the holder
    then silently reads ANOTHER request's bytes. Legitimate long-lived
    views ride a `retain()`ed buffer (the _Window pattern: view and buffer
    handed off together)."""

    id = "view-escape"
    title = "pooled-buffer view escapes without a retain()"
    scope = BUFFER_PATHS

    SUBMITISH = ("submit", "Thread", "start_new_thread", "run_in_executor")

    def _is_view_of(self, node: ast.AST, flow: _BufferFlow) -> str | None:
        """Owner name when `node` is `<buf>.view(...)` or
        `memoryview(<buf>.data)` over a tracked buffer."""
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "view"
            and isinstance(func.value, ast.Name)
            and func.value.id in flow.acquired
        ):
            return func.value.id
        if (
            isinstance(func, ast.Name)
            and func.id == "memoryview"
            and node.args
            and isinstance(node.args[0], ast.Attribute)
            and node.args[0].attr == "data"
            and isinstance(node.args[0].value, ast.Name)
            and node.args[0].value.id in flow.acquired
        ):
            return node.args[0].value.id
        return None

    def check(self, project: ProjectContext):
        for ctx in project.iter_files(*self.scope):
            for func in _iter_functions(ctx):
                flow = _BufferFlow(func)
                if not flow.acquired:
                    continue
                # vname -> owning buffer name, for named view bindings.
                views: dict[str, str] = {}
                for node in _shallow_nodes(func):
                    if isinstance(node, ast.Assign):
                        owner = self._is_view_of(node.value, flow)
                        if owner is not None:
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    views[t.id] = owner

                def owner_of(expr: ast.AST) -> str | None:
                    direct = self._is_view_of(expr, flow)
                    if direct is not None:
                        return direct
                    if isinstance(expr, ast.Name):
                        return views.get(expr.id)
                    return None

                def escapees(expr: ast.AST | None):
                    direct = self._is_view_of(expr, flow) if expr is not None else None
                    if direct is not None:
                        yield direct, expr
                    for name in _escaping_names(expr):
                        if name in views:
                            yield views[name], expr

                findings: dict[tuple[int, str], str] = {}

                def note(owner: str, node: ast.AST, how: str) -> None:
                    if owner in flow.retained:
                        return
                    findings.setdefault((node.lineno, owner), how)

                for node in _shallow_nodes(func):
                    if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                        for owner, val in escapees(getattr(node, "value", None)):
                            note(owner, node, "returned/yielded")
                    elif isinstance(node, ast.Assign):
                        if any(
                            isinstance(t, (ast.Attribute, ast.Subscript))
                            for t in node.targets
                        ):
                            for owner, val in escapees(node.value):
                                note(owner, node, "stored outside the scope")
                    elif isinstance(node, ast.Call):
                        callee = _call_name(node)
                        last = callee.rsplit(".", 1)[-1]
                        args = list(node.args) + [kw.value for kw in node.keywords]
                        if last in _BufferFlow.CONTAINER_METHODS:
                            for a in args:
                                o = owner_of(a)
                                if o is not None:
                                    note(o, node, "appended to a container")
                        elif any(s in last for s in self.SUBMITISH):
                            for a in args:
                                for sub in ast.walk(a):
                                    o = owner_of(sub)
                                    if o is not None:
                                        note(o, node, "passed to a thread/lane submit")
                    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                        # Closure capture: the nested scope outlives this one.
                        inner = (
                            node.body if isinstance(node.body, list) else [node.body]
                        )
                        for stmt in inner:
                            for sub in ast.walk(stmt if isinstance(stmt, ast.AST) else node):
                                if isinstance(sub, ast.Name) and sub.id in views:
                                    note(views[sub.id], node, "captured by a closure")
                for (lineno, owner), how in sorted(findings.items()):
                    yield Finding(
                        self.id, ctx.relpath, lineno,
                        f"view over pooled buffer {owner!r} {how} without a "
                        f"retain() -- when {owner!r} is released the storage "
                        "recycles and the view reads another request's "
                        "bytes; retain() the buffer for the view's lifetime "
                        "(and release with it), or copy the bytes out",
                    )


class InterfaceConformanceRule(Rule):
    """StorageAPI wrappers must forward the FULL storage interface.

    MeteredDrive / FaultyDisk / HealthGatedDrive sit in every drive stack;
    a wrapper that pins an `inner` drive but neither defines `__getattr__`
    nor implements every StorageAPI method silently drops whatever the
    interface grew since the wrapper was written (`read_file_into`,
    `append_iov`) -- callers fall back to slow paths or AttributeError at
    runtime. The interface roster is read from storage/interface.py, so the
    rule tracks StorageAPI growth automatically."""

    id = "interface-conformance"
    title = "StorageAPI wrapper missing interface methods"
    scope = ("minio_tpu/storage/", "minio_tpu/chaos/")

    @staticmethod
    def _iface_methods(project: ProjectContext) -> set[str]:
        ctx = project.get(STORAGE_IFACE)
        if ctx is None:
            return set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name == "StorageAPI":
                return {
                    n.name
                    for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and not n.name.startswith("_")
                }
        return set()

    @staticmethod
    def _wraps_inner(cls: ast.ClassDef) -> bool:
        """Does __init__ pin an `inner` drive? Both idioms count:
        `self.inner = inner` and `self.__dict__["inner"] = inner` (the
        __setattr__-forwarding form the real wrappers use)."""
        for node in cls.body:
            if not isinstance(node, ast.FunctionDef) or node.name != "__init__":
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "inner":
                        return True
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr == "__dict__"
                        and _str_const(t.slice) == "inner"
                    ):
                        return True
        return False

    def check(self, project: ProjectContext):
        methods = self._iface_methods(project)
        if not methods:
            return
        for ctx in project.iter_files(*self.scope):
            if ctx.relpath == STORAGE_IFACE:
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ClassDef) or not self._wraps_inner(node):
                    continue
                defined = {
                    n.name
                    for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                if "__getattr__" in defined:
                    continue
                for missing in sorted(methods - defined):
                    yield Finding(
                        self.id, ctx.relpath, node.lineno,
                        f"wrapper {node.name!r} neither defines __getattr__ "
                        f"nor forwards StorageAPI.{missing} -- the drive "
                        "stack silently loses the method",
                    )


ALL_RULES: list[Rule] = [
    SwallowedExceptRule(),
    RawTransportRule(),
    DeadlineRebindRule(),
    LockBlockingIORule(),
    ResourceLeakRule(),
    StageKeyRule(),
    MetricsRenderedRule(),
    TypedErrorsRule(),
    UnlockedGlobalRule(),
    LockOrderRule(),
    UnjoinedThreadRule(),
    CondWaitLoopRule(),
    SharedPublishRule(),
    UnsyncedCommitRule(),
    HotPathCopyRule(),
    ReleaseOnAllPathsRule(),
    DoubleReleaseRule(),
    ViewEscapeRule(),
    InterfaceConformanceRule(),
]

# deadline_lint.py's historical surface: the two rules that together are the
# old regex lint, runnable standalone by the shim and chaos_check.
DEADLINE_RULES: list[Rule] = [
    RawTransportRule(),
    DeadlineRebindRule(),
]
